"""Multi-tenant serving policy: fair-share scheduling + KV quotas.

The last unserved axis of the ROADMAP's millions-of-users north star:
every request now carries a tenant id and a QoS class (``nvext.tenant``
/ ``nvext.priority`` → ``PreprocessedRequest.tenant_id``/``qos`` →
``RequestControlMessage``), and this module is the one home of the
policy machinery that prices them:

- :class:`TenantPolicy` / the module-level :data:`TENANT_TABLE` — the
  per-tenant (weight, kv_quota_blocks, default qos) record, retuned
  LIVE over the ``tenant/control/{ns}`` kvstore key (``llmctl tenant
  {set-weight,set-quota,status}``) exactly like the router's
  TIER_WEIGHTS: the dict is mutated in place so every importer — the
  KvScheduler's share math, the tiers' quota checks, sim workers —
  sees a retune without restart.

- :class:`FairShareQueue` — weighted deficit round-robin over
  per-tenant queues with QoS preemption-priority ordering
  (interactive > standard > batch). A flooding tenant's backlog sits
  in ITS queue; drain order gives every backlogged tenant service
  proportional to its weight, so the flood is throttled to its share
  instead of starving the fleet (FlowKV's load-aware-per-flow lesson
  applied at admission). Deterministic: tenant order is sorted, no
  wall clock, no randomness — safe inside the virtual-clock sim and
  recorded replay.

- :class:`FairShareAdmission` — the serving-path gate
  (llm/engines/kv_routed.py): a tenant whose in-flight share exceeds
  its fair share of the fleet's slots WAITS in the fair-share queue
  instead of dispatching; releases wake waiters in WDRR order.

- :class:`TenantBlockLedger` — per-tenant block accounting across the
  KV tiers (device/host/disk/remote). Tiers note/forget residency per
  (tier, hash); eviction victim selection asks
  :meth:`is_over_quota_hash` FIRST, so one tenant's eviction storm
  lands on its own over-quota blocks before it can crater another
  tenant's hit rate (NetKV's instance-selection lesson generalized:
  state is priced per tenant, not just globally).

Everything here is control-plane pure Python: no jit, no wall clock,
no randomness — the noisy_neighbor sim scenario runs these exact
classes under the byte-identical-event-log determinism gate.
"""

from __future__ import annotations

import dataclasses
import json
import logging
from collections import deque
from typing import Deque, Dict, Iterable, List, Optional, Tuple

logger = logging.getLogger("dynamo_tpu.llm.tenancy")

__all__ = [
    "DEFAULT_TENANT", "QOS_CLASSES", "QOS_PRIORITY",
    "TenantPolicy", "TenantTable", "TENANT_TABLE", "set_tenant_policies",
    "tenant_control_key", "watch_tenants_loop",
    "FairShareQueue", "FairShareAdmission", "TenantBlockLedger",
]

DEFAULT_TENANT = "default"

# QoS preemption-priority order: lower rank drains first. Unknown
# classes coerce to "standard" (a typo'd priority must not jump or
# starve the queue).
QOS_CLASSES = ("interactive", "standard", "batch")
QOS_PRIORITY = {name: i for i, name in enumerate(QOS_CLASSES)}

TENANT_PREFIX = "tenant/"


def tenant_control_key(namespace: str) -> str:
    """``llmctl tenant`` target: a JSON {tenant: policy} table every
    watching worker/router applies live (the TIER_WEIGHTS retune
    pattern)."""
    return f"{TENANT_PREFIX}control/{namespace}"


@dataclasses.dataclass
class TenantPolicy:
    """One tenant's share contract.

    ``weight``: fair-share weight (WDRR quantum scale; share =
    weight / sum of ACTIVE tenants' weights).
    ``kv_quota_blocks``: per-tier resident-block quota; 0 = unlimited.
    ``qos``: default QoS class for requests that don't name one."""

    weight: float = 1.0
    kv_quota_blocks: int = 0
    qos: str = "standard"

    def __post_init__(self):
        if self.weight <= 0:
            raise ValueError(f"tenant weight must be > 0, got {self.weight}")
        if self.qos not in QOS_PRIORITY:
            self.qos = "standard"

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "TenantPolicy":
        fields = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in fields})


class TenantTable:
    """The live {tenant: TenantPolicy} map. Unknown tenants get the
    default policy (weight 1.0, no quota) — multi-tenancy is opt-in
    per tenant, never a hard gate on traffic."""

    def __init__(self, policies: Optional[Dict[str, TenantPolicy]] = None):
        self.policies: Dict[str, TenantPolicy] = dict(policies or {})
        self.default = TenantPolicy()

    def get(self, tenant: Optional[str]) -> TenantPolicy:
        return self.policies.get(tenant or DEFAULT_TENANT, self.default)

    def weight(self, tenant: Optional[str]) -> float:
        return self.get(tenant).weight

    def quota(self, tenant: Optional[str]) -> int:
        return self.get(tenant).kv_quota_blocks

    def qos_of(self, tenant: Optional[str],
               requested: Optional[str]) -> str:
        if requested in QOS_PRIORITY:
            return requested
        return self.get(tenant).qos

    def share(self, tenant: Optional[str],
              active: Iterable[str]) -> float:
        """Fair share of ``tenant`` among the ACTIVE tenant set (itself
        included whether listed or not)."""
        names = set(active)
        names.add(tenant or DEFAULT_TENANT)
        total = sum(self.weight(t) for t in names)
        if total <= 0:
            return 1.0
        return self.weight(tenant) / total

    def set(self, tenant: str, **updates) -> TenantPolicy:
        pol = self.policies.get(tenant, TenantPolicy())
        d = pol.to_dict()
        d.update({k: v for k, v in updates.items() if v is not None})
        pol = TenantPolicy.from_dict(d)
        self.policies[tenant] = pol
        return pol

    def to_json(self) -> bytes:
        return json.dumps({t: p.to_dict()
                           for t, p in sorted(self.policies.items())}).encode()

    @classmethod
    def from_json(cls, raw: bytes) -> "TenantTable":
        d = json.loads(raw)
        if not isinstance(d, dict):
            raise ValueError("tenant table must be a JSON object")
        return cls({t: TenantPolicy.from_dict(p) for t, p in d.items()
                    if isinstance(p, dict)})


# The process-wide table (the TIER_WEIGHTS pattern): mutated in place by
# set_tenant_policies so every importer — scheduler share math, tier
# quota checks — follows a live retune without re-plumbing references.
TENANT_TABLE = TenantTable()


def set_tenant_policies(policies: Dict[str, dict],
                        table: Optional[TenantTable] = None) -> TenantTable:
    """Replace the live table's policies in place from a JSON-shaped
    {tenant: {weight, kv_quota_blocks, qos}} map. Malformed entries are
    skipped loudly rather than poisoning the table."""
    table = table if table is not None else TENANT_TABLE
    fresh: Dict[str, TenantPolicy] = {}
    for t, p in policies.items():
        try:
            fresh[t] = TenantPolicy.from_dict(p)
        except (TypeError, ValueError) as e:
            logger.warning("ignoring malformed tenant policy %s: %s", t, e)
    table.policies.clear()
    table.policies.update(fresh)
    return table


async def watch_tenants_loop(runtime, namespace: str,
                             table: Optional[TenantTable] = None) -> None:
    """Standing task: apply ``llmctl tenant set-*`` live. Like the
    tier-weights watch, the STORED value applies at startup too —
    tenant policy is declarative config, so a late joiner converges to
    the namespace's current table."""
    from ..runtime.kvstore import WatchEventType
    from ..runtime.tracing import detach_trace
    detach_trace()
    key = tenant_control_key(namespace)

    def apply(raw: bytes) -> None:
        try:
            d = json.loads(raw)
        except ValueError:
            logger.warning("ignoring malformed tenant table at %s", key)
            return
        if not isinstance(d, dict):
            logger.warning("ignoring non-dict tenant table at %s", key)
            return
        eff = set_tenant_policies(d, table)
        logger.info("tenant policies -> %s",
                    {t: p.to_dict() for t, p in eff.policies.items()})

    entry = await runtime.store.kv_get(key)
    if entry is not None:
        apply(entry.value)
    watcher = await runtime.store.watch_prefix(key)
    async for ev in watcher:
        if ev.type == WatchEventType.PUT:
            apply(ev.entry.value)


# ---------------------------------------------------------------------------
# Weighted deficit round-robin with QoS classes
# ---------------------------------------------------------------------------


class FairShareQueue:
    """Per-tenant queues drained by weighted deficit round-robin, with
    QoS preemption-priority between classes.

    ``push(item, tenant, qos, cost)`` enqueues; ``pop()`` returns the
    next item: the highest-priority QoS class with ANY backlog drains
    first (an interactive request never waits behind a batch flood);
    within a class, tenants take turns in sorted-name order, each
    spending a deficit counter replenished by ``quantum × weight`` per
    round — a tenant whose items cost more than its deficit skips the
    round, which is exactly the throttle: a 10× flooding tenant gets
    ~its weight share of pops, no more.

    Deterministic by construction (sorted tenant order, no clock, no
    randomness): safe under the sim's byte-identical-event-log gate
    and in recorded replay."""

    QUANTUM = 4.0   # deficit replenished per round per unit weight

    def __init__(self, table: Optional[TenantTable] = None):
        self.table = table if table is not None else TENANT_TABLE
        # qos rank → tenant → deque of (item, cost)
        self._queues: Dict[int, Dict[str, Deque[Tuple[object, float]]]] = {}
        self._deficit: Dict[Tuple[int, str], float] = {}
        # round-robin cursor per qos class (tenant name last served)
        self._cursor: Dict[int, Optional[str]] = {}
        self._len = 0
        self.pushed_total: Dict[str, int] = {}
        self.popped_total: Dict[str, int] = {}

    def __len__(self) -> int:
        return self._len

    def backlog(self, tenant: str) -> int:
        return sum(len(q.get(tenant, ()))
                   for q in self._queues.values())

    def push(self, item, tenant: Optional[str] = None,
             qos: Optional[str] = None, cost: float = 1.0) -> None:
        tenant = tenant or DEFAULT_TENANT
        rank = QOS_PRIORITY.get(
            self.table.qos_of(tenant, qos), QOS_PRIORITY["standard"])
        per_class = self._queues.setdefault(rank, {})
        q = per_class.get(tenant)
        if q is None:
            q = per_class[tenant] = deque()
            self._deficit.setdefault((rank, tenant), 0.0)
        q.append((item, max(float(cost), 0.0)))
        self._len += 1
        self.pushed_total[tenant] = self.pushed_total.get(tenant, 0) + 1

    def _tenants_after(self, rank: int, names: List[str]) -> List[str]:
        """Backlogged tenants of one class in round-robin order starting
        AFTER the class cursor (sorted base order)."""
        cur = self._cursor.get(rank)
        if cur is None or cur not in names:
            return names
        i = names.index(cur)
        return names[i + 1:] + names[:i + 1]

    def pop(self):
        """Next (item, tenant) by QoS-then-WDRR order; None when empty."""
        if self._len == 0:
            return None
        for rank in sorted(self._queues):
            per_class = self._queues[rank]
            names = sorted(t for t, q in per_class.items() if q)
            if not names:
                continue
            order = self._tenants_after(rank, names)
            # at most two replenish rounds are ever needed: after one
            # full round every backlogged tenant's deficit >= quantum ×
            # weight >= the head item's cost for any sane cost scale;
            # the guard below hard-caps pathological costs
            for _round in range(64):
                for t in order:
                    q = per_class[t]
                    if not q:
                        continue
                    key = (rank, t)
                    item, cost = q[0]
                    if self._deficit[key] >= cost:
                        q.popleft()
                        self._deficit[key] -= cost
                        if not q:
                            # an emptied queue forfeits its leftover
                            # deficit: WDRR's anti-burst rule
                            self._deficit[key] = 0.0
                        self._cursor[rank] = t
                        self._len -= 1
                        self.popped_total[t] = (
                            self.popped_total.get(t, 0) + 1)
                        return item, t
                # replenish and go around again
                for t in order:
                    if per_class[t]:
                        self._deficit[(rank, t)] += (
                            self.QUANTUM * self.table.weight(t))
            # pathological cost scale: serve the head of the first
            # backlogged tenant rather than spin
            t = order[0]
            item, cost = per_class[t].popleft()
            self._deficit[(rank, t)] = 0.0
            self._cursor[rank] = t
            self._len -= 1
            self.popped_total[t] = self.popped_total.get(t, 0) + 1
            return item, t
        return None

    def popleft(self):
        """Deque-compatible spelling: returns the item alone (the sim
        worker's waiting-queue drop-in)."""
        got = self.pop()
        if got is None:
            raise IndexError("pop from empty FairShareQueue")
        return got[0]

    def __iter__(self):
        for per_class in self._queues.values():
            for q in per_class.values():
                for item, _cost in q:
                    yield item

    def clear(self) -> None:
        self._queues.clear()
        self._deficit.clear()
        self._cursor.clear()
        self._len = 0


# ---------------------------------------------------------------------------
# Serving-path admission gate
# ---------------------------------------------------------------------------


class FairShareAdmission:
    """Router-side admission: bound each tenant's IN-FLIGHT dispatches
    to its fair share of fleet capacity whenever there is contention.

    ``acquire(tenant, qos)`` returns immediately while the fleet has
    headroom OR the tenant is under its share; otherwise the caller
    waits in a :class:`FairShareQueue` and is woken by ``release`` in
    WDRR order. ``capacity`` is a callable returning the fleet's total
    request slots (the scheduler's scraped view) so the bound tracks
    scale-out live; 0/unknown capacity admits everything (cold fleet:
    admit-optimistic, the tiers' posture)."""

    def __init__(self, capacity, table: Optional[TenantTable] = None,
                 headroom: float = 0.85):
        import asyncio
        self._asyncio = asyncio
        self.capacity = capacity
        self.table = table if table is not None else TENANT_TABLE
        self.headroom = headroom
        self.inflight: Dict[str, int] = {}
        self.waiters = FairShareQueue(self.table)
        self.admitted_total: Dict[str, int] = {}
        self.throttled_total: Dict[str, int] = {}

    def _inflight_total(self) -> int:
        return sum(self.inflight.values())

    def would_throttle(self, tenant: str) -> bool:
        cap = int(self.capacity() or 0)
        if cap <= 0:
            return False
        total = self._inflight_total()
        if total < self.headroom * cap:
            return False          # headroom: nobody queues
        active = [t for t, n in self.inflight.items() if n > 0]
        share = self.table.share(tenant, active)
        return self.inflight.get(tenant, 0) >= max(share * cap, 1.0)

    async def acquire(self, tenant: Optional[str] = None,
                      qos: Optional[str] = None) -> str:
        tenant = tenant or DEFAULT_TENANT
        if self.would_throttle(tenant):
            self.throttled_total[tenant] = (
                self.throttled_total.get(tenant, 0) + 1)
            fut = self._asyncio.get_running_loop().create_future()
            self.waiters.push(fut, tenant, qos)
            await fut
        self.inflight[tenant] = self.inflight.get(tenant, 0) + 1
        self.admitted_total[tenant] = (
            self.admitted_total.get(tenant, 0) + 1)
        return tenant

    def release(self, tenant: str) -> None:
        n = self.inflight.get(tenant, 0)
        if n <= 1:
            self.inflight.pop(tenant, None)
        else:
            self.inflight[tenant] = n - 1
        # wake the next eligible waiter (WDRR order); skip waiters whose
        # tenant is STILL over its share — they re-queue at the tail of
        # their tenant queue, preserving the share bound
        requeue = []
        while len(self.waiters):
            got = self.waiters.pop()
            if got is None:
                break
            fut, t = got
            if fut.cancelled():
                continue
            if self.would_throttle(t):
                requeue.append((fut, t))
                continue
            fut.set_result(None)
            break
        for fut, t in requeue:
            self.waiters.push(fut, t)

    def counters(self) -> Dict[str, Dict[str, int]]:
        out: Dict[str, Dict[str, int]] = {}
        for t in set(self.admitted_total) | set(self.throttled_total):
            out[t] = {"admitted": self.admitted_total.get(t, 0),
                      "throttled": self.throttled_total.get(t, 0)}
        return out


# ---------------------------------------------------------------------------
# Per-tenant KV block accounting + quota enforcement
# ---------------------------------------------------------------------------


class TenantBlockLedger:
    """Per-(tier, tenant) resident-block accounting shared by the KV
    tiers. Tiers call :meth:`note`/:meth:`forget` at registration /
    invalidation; eviction victim selection asks
    :meth:`is_over_quota_hash` to land evictions on the over-quota
    tenant's blocks FIRST (device pool ``_evict_one``, host pool
    ``_slot_for``, disk/remote LRU reapers) — one tenant's eviction
    storm consumes its own residency before anyone else's."""

    TIERS = ("device", "host", "disk", "remote")

    KNOWN_CAP = 1 << 20   # hash→tenant memory bound (FIFO reap)

    def __init__(self, table: Optional[TenantTable] = None):
        self.table = table if table is not None else TENANT_TABLE
        # tier → hash → tenant
        self._present: Dict[str, Dict[int, str]] = {t: {}
                                                    for t in self.TIERS}
        # tier → tenant → count (maintained incrementally)
        self._counts: Dict[str, Dict[str, int]] = {t: {}
                                                   for t in self.TIERS}
        # persistent hash→tenant memory: a block evicted from the device
        # tier keeps its owner as it demotes host→disk→remote (the
        # colder tiers note residency AFTER the warmer tier forgot).
        # Bounded FIFO so a long-lived server never grows without limit.
        self._known: Dict[int, str] = {}

    def note(self, seq_hash: int, tenant: Optional[str],
             tier: str = "device") -> None:
        if tenant is None:
            tenant = self._known.get(seq_hash)
        if tenant is None:
            return
        self._known.pop(seq_hash, None)
        self._known[seq_hash] = tenant
        while len(self._known) > self.KNOWN_CAP:
            self._known.pop(next(iter(self._known)))
        present = self._present.setdefault(tier, {})
        old = present.get(seq_hash)
        if old == tenant:
            return
        counts = self._counts.setdefault(tier, {})
        if old is not None:
            counts[old] = max(counts.get(old, 0) - 1, 0)
        present[seq_hash] = tenant
        counts[tenant] = counts.get(tenant, 0) + 1

    def forget(self, seq_hash: int, tier: str = "device") -> None:
        present = self._present.get(tier)
        if not present:
            return
        tenant = present.pop(seq_hash, None)
        if tenant is not None:
            counts = self._counts[tier]
            counts[tenant] = max(counts.get(tenant, 0) - 1, 0)

    def tenant_of(self, seq_hash: int,
                  tier: Optional[str] = None) -> Optional[str]:
        if tier is not None:
            got = self._present.get(tier, {}).get(seq_hash)
        else:
            got = next((self._present[t][seq_hash] for t in self.TIERS
                        if seq_hash in self._present[t]), None)
        return got if got is not None else self._known.get(seq_hash)

    def blocks(self, tenant: str, tier: Optional[str] = None) -> int:
        if tier is not None:
            return self._counts.get(tier, {}).get(tenant, 0)
        return sum(c.get(tenant, 0) for c in self._counts.values())

    def is_over_quota(self, tenant: Optional[str],
                      tier: str = "device") -> bool:
        if tenant is None:
            return False
        quota = self.table.quota(tenant)
        if quota <= 0:
            return False
        return self._counts.get(tier, {}).get(tenant, 0) > quota

    def is_over_quota_hash(self, seq_hash: Optional[int],
                           tier: str = "device") -> bool:
        """Victim-preference predicate: True when the hash belongs to a
        tenant currently over its quota in this tier."""
        if seq_hash is None:
            return False
        return self.is_over_quota(
            self._present.get(tier, {}).get(seq_hash), tier)

    def snapshot(self) -> Dict[str, Dict[str, int]]:
        """tenant → {tier: blocks} for status surfaces."""
        out: Dict[str, Dict[str, int]] = {}
        for tier, counts in self._counts.items():
            for tenant, n in counts.items():
                if n:
                    out.setdefault(tenant, {})[tier] = n
        return out
