from .metrics import InflightGuard, ServiceMetrics
from .service import HttpService, ModelManager

__all__ = ["HttpService", "ModelManager", "ServiceMetrics", "InflightGuard"]
