"""OpenAI-compatible HTTP frontend.

Reference: the axum service in lib/llm/src/http/service/{service_v2.rs:24-132,
openai.rs:132-528, error.rs} — `/v1/chat/completions`, `/v1/completions`,
`/v1/models`, `/metrics`, `/health`; SSE streaming with a client-disconnect
monitor that calls `ctx.kill()`; a `ModelManager` of named engines that
discovery can add/remove at runtime.

Implementation is aiohttp (asyncio-native streaming + backpressure); engines
are anything implementing `AsyncEngine[openai-request-dict, Annotated[chunk]]`
— an in-process pipeline, a JAX engine, or a remote client over the request
plane, interchangeably.
"""

from __future__ import annotations

import asyncio
import json
import logging
import time
from typing import Dict, Optional

from aiohttp import web

from ...runtime.engine import AsyncEngine, Context, EngineContext
from ...runtime.tracing import Trace, span, use_trace
from ..protocols.annotated import Annotated
from ..protocols.openai import (aggregate_chat_stream,
                                aggregate_completion_stream)
from ..protocols.sse import encode_annotated, encode_done
from .metrics import ServiceMetrics

logger = logging.getLogger("dynamo_tpu.http")


class ModelManager:
    """Named engine registry (reference `ModelManager`, service_v2.rs)."""

    def __init__(self) -> None:
        self._chat: Dict[str, AsyncEngine] = {}
        self._completion: Dict[str, AsyncEngine] = {}
        self._cards: Dict[str, dict] = {}

    def add_chat_model(self, name: str, engine: AsyncEngine,
                       card: Optional[dict] = None) -> None:
        self._chat[name] = engine
        self._cards.setdefault(name, card or {})

    def add_completion_model(self, name: str, engine: AsyncEngine,
                             card: Optional[dict] = None) -> None:
        self._completion[name] = engine
        self._cards.setdefault(name, card or {})

    def remove_model(self, name: str,
                     model_type: Optional[str] = None) -> None:
        """Remove one registry's entry ("chat"/"completion") or, with no
        model_type, every trace of the name."""
        if model_type in (None, "chat"):
            self._chat.pop(name, None)
        if model_type in (None, "completion"):
            self._completion.pop(name, None)
        if name not in self._chat and name not in self._completion:
            self._cards.pop(name, None)

    def chat_engine(self, name: str) -> Optional[AsyncEngine]:
        return self._chat.get(name)

    def completion_engine(self, name: str) -> Optional[AsyncEngine]:
        return self._completion.get(name)

    def list_models(self) -> list:
        return sorted(set(self._chat) | set(self._completion))


def _chunk_token_count(chunk) -> int:
    """Text-bearing choices in an OpenAI chunk (for the output-token metric)."""
    if not isinstance(chunk, dict):
        return 0
    n = 0
    for choice in chunk.get("choices") or []:
        delta = choice.get("delta")
        if delta is not None:
            if delta.get("content"):
                n += 1
        elif choice.get("text"):
            n += 1
    return n


def _error_response(status: int, message: str, err_type: str = "invalid_request_error"):
    return web.json_response(
        {"error": {"message": message, "type": err_type, "code": status}},
        status=status)


MAX_N = 16          # parallel-sampling fan-out cap (engine slots are finite)


class _FanoutContext(EngineContext):
    """Parent context of an n>1 request: cancellation fans out to every
    per-choice child generation."""

    __slots__ = ("children",)

    def __init__(self):
        super().__init__()
        self.children: list = []

    def stop_generating(self) -> None:
        super().stop_generating()
        for c in self.children:
            c.stop_generating()

    def kill(self) -> None:
        super().kill()
        for c in self.children:
            c.kill()


async def _merge_choice_streams(streams, ectx: "_FanoutContext"):
    """n independent single-choice streams → one multi-choice stream
    (OpenAI `n` semantics): choice indices are rewritten to the sub-stream
    slot, chunk identity (id/created/model) is normalized to one stream's
    (each child pipeline minted its own), and per-stream usage folds into
    ONE trailing usage chunk — prompt counted once, completions summed.
    A child failure kills the sibling generations (their slots must not
    stay held) before the error surfaces."""
    from ..protocols.openai import usage_dict

    q: asyncio.Queue = asyncio.Queue(maxsize=4)   # backpressure: children
    done = object()                               # run at consumer speed

    async def pump(i, s):
        try:
            async for item in s:
                await q.put((i, item, None))
        except Exception as e:  # noqa: BLE001 — surfaced to the consumer
            await q.put((i, None, e))
        finally:
            await q.put((i, done, None))

    tasks = [asyncio.create_task(pump(i, s))
             for i, s in enumerate(streams)]
    usages: Dict[int, dict] = {}
    template: Optional[dict] = None
    pending = len(streams)
    try:
        while pending:
            i, item, err = await q.get()
            if err is not None:
                ectx.kill()               # reap the sibling generations
                raise err
            if item is done:
                pending -= 1
                continue
            ann = (item if isinstance(item, Annotated)
                   else Annotated.from_data(item))
            chunk = ann.data
            if isinstance(chunk, dict):
                if template is None and chunk.get("id"):
                    template = {k: chunk.get(k)
                                for k in ("id", "object", "created",
                                          "model")}
                elif template is not None and chunk.get("id"):
                    # one id per SSE stream (OpenAI contract) — children
                    # minted their own
                    chunk.update(template)
                for c in chunk.get("choices") or []:
                    c["index"] = i
                if chunk.get("usage") is not None:
                    usages[i] = chunk.pop("usage")
                    if not chunk.get("choices"):
                        continue          # combined usage emitted at the end
            yield ann
        if usages:
            vals = list(usages.values())
            combined = usage_dict(
                vals[0].get("prompt_tokens", 0),
                sum(v.get("completion_tokens", 0) for v in vals))
            yield Annotated.from_data({**(template or {}), "choices": [],
                                       "usage": combined})
    finally:
        for t in tasks:
            t.cancel()


async def _start_fanout(engine, body: dict, ectx: "_FanoutContext",
                        n: int):
    """Launch n single-choice generations CONCURRENTLY for one request
    (sequential dispatch would serialize per-child dial-back latency
    against remote engines). Seeded requests get seed+i per choice
    (reproducible but decorrelated); unseeded requests get a fresh random
    base per REQUEST (a constant base would make choices 1..n-1 identical
    across every request).

    This is whole-request fan-out: the prompt prefills n times and holds
    n engine slots. The deeper mechanism — one prefill, n decode streams
    sharing the prompt KV in the engine — would replace this layer's seed
    derivation and stream merging when the engine grows native n; until
    then the prefix cache absorbs the repeat prefills on cache-enabled
    engines."""
    import random

    base = (int(body["seed"]) if body.get("seed") is not None
            else random.getrandbits(31))

    async def one(i: int):
        sub = dict(body)
        sub["n"] = 1
        sub["seed"] = base + i
        sctx = EngineContext(f"{ectx.id}-c{i}")
        sctx.deadline_s = ectx.deadline_s   # children inherit the budget
        sctx.tenant = ectx.tenant           # ...and the tenant identity
        sctx.qos = ectx.qos
        ectx.children.append(sctx)
        return await engine.generate(Context(sub, sctx))

    results = await asyncio.gather(*(one(i) for i in range(n)),
                                   return_exceptions=True)
    errs = [r for r in results if isinstance(r, BaseException)]
    if errs:
        ectx.kill()          # reap the children that did start
        raise errs[0]
    return _merge_choice_streams(list(results), ectx)


class HttpService:
    """The frontend server (reference `HttpService` service_v2 builder)."""

    def __init__(self, port: int = 8080, host: str = "0.0.0.0",
                 manager: Optional[ModelManager] = None,
                 metrics: Optional[ServiceMetrics] = None):
        self.port = port
        self.host = host
        self.manager = manager or ModelManager()
        self.metrics = metrics or ServiceMetrics()
        self.app = web.Application()
        self.app.router.add_post("/v1/chat/completions", self._chat)
        self.app.router.add_post("/v1/completions", self._completions)
        self.app.router.add_get("/v1/models", self._models)
        self.app.router.add_get("/metrics", self._metrics)
        self.app.router.add_get("/health", self._health)
        self.app.router.add_get("/live", self._health)
        self.app.router.add_get("/traces", self._traces)
        self.app.router.add_get("/debug", self._debug)
        self._runner: Optional[web.AppRunner] = None
        self._site: Optional[web.TCPSite] = None

    # ------------------------------------------------------------ lifecycle
    async def start(self) -> None:
        if self._runner is not None:
            return  # already serving (run_forever after start is fine)
        self._runner = web.AppRunner(self.app)
        await self._runner.setup()
        self._site = web.TCPSite(self._runner, self.host, self.port)
        await self._site.start()
        if self.port == 0:
            # pick up the ephemeral port for tests
            self.port = self._site._server.sockets[0].getsockname()[1]  # type: ignore
        logger.info("HTTP service listening on %s:%s", self.host, self.port)

    async def stop(self) -> None:
        # claim before the await (DL008): concurrent stop()s must not
        # both run cleanup
        runner, self._runner = self._runner, None
        if runner is not None:
            await runner.cleanup()

    async def run_forever(self) -> None:
        await self.start()
        try:
            while True:
                await asyncio.sleep(3600)
        finally:
            await self.stop()

    # ------------------------------------------------------------- handlers
    async def _health(self, request: web.Request) -> web.Response:
        return web.json_response({"status": "healthy",
                                  "models": self.manager.list_models()})

    async def _traces(self, request: web.Request) -> web.Response:
        """Recent per-request traces (debug): stage latencies keyed by
        request id; ?request_id= filters to one request."""
        from ...runtime.tracing import tracer
        rid = request.query.get("request_id")
        data = tracer.find(rid) if rid else tracer.recent()
        return web.json_response({"traces": data,
                                  "completed": tracer.completed})

    async def _debug(self, request: web.Request) -> web.Response:
        """Operator introspection: tracer sampling state + every
        in-process engine flight recorder's ring (per-dispatch records,
        event-loop lag) — the same payload ``llmctl trace dump``
        collects from remote workers (engine/flight_recorder.py)."""
        from ...engine.flight_recorder import all_recorders
        from ...runtime.tracing import tracer
        try:
            last = int(request.query.get("last", "64"))
        except ValueError:
            last = 64
        return web.json_response({
            "tracer": tracer.stats(),
            "flight_recorders": {
                name: {"stats": fr.stats(), "records": fr.dump(last=last)}
                for name, fr in all_recorders().items()},
        })

    async def _models(self, request: web.Request) -> web.Response:
        now = int(time.time())
        data = []
        for m in self.manager.list_models():
            entry = {"id": m, "object": "model", "created": now,
                     "owned_by": "dynamo-tpu"}
            card = self.manager._cards.get(m)
            if card:
                # registry provenance (llm/registry.py): geometry +
                # program-set key so a client can tell which compiled
                # program family is serving the name
                entry["nvext"] = {k: card[k] for k in
                                  ("program_set", "revision", "endpoint",
                                   "kv_block_size")
                                  if card.get(k) is not None}
            data.append(entry)
        return web.json_response({"object": "list", "data": data})

    async def _metrics(self, request: web.Request) -> web.Response:
        return web.Response(body=self.metrics.render(),
                            content_type="text/plain", charset="utf-8")

    async def _chat(self, request: web.Request) -> web.StreamResponse:
        return await self._handle(request, "chat_completions")

    async def _completions(self, request: web.Request) -> web.StreamResponse:
        return await self._handle(request, "completions")

    async def _handle(self, request: web.Request,
                      endpoint: str) -> web.StreamResponse:
        try:
            body = await request.json()
        except json.JSONDecodeError as e:
            return _error_response(400, f"invalid JSON body: {e}")
        model = body.get("model")
        if not model:
            return _error_response(400, "missing 'model'")
        is_chat = endpoint == "chat_completions"
        engine = (self.manager.chat_engine(model) if is_chat
                  else self.manager.completion_engine(model))
        if engine is None:
            return _error_response(
                404, f"model '{model}' not found", "model_not_found")
        raw_n = body.get("n")
        if raw_n is None:
            n_choices = 1
        elif isinstance(raw_n, int) and not isinstance(raw_n, bool):
            n_choices = raw_n
        else:
            # 2.9 must not silently truncate to 2, nor true to 1
            return _error_response(400, "'n' must be an integer")
        if not 1 <= n_choices <= MAX_N:
            return _error_response(
                400, f"'n' must be between 1 and {MAX_N}")
        streaming = bool(body.get("stream", False))
        guard = self.metrics.inflight_guard(model, endpoint, streaming)
        ectx = EngineContext() if n_choices == 1 else _FanoutContext()
        # multi-tenant identity (llm/tenancy.py): tenant + QoS class ride
        # the EngineContext so egress stamps them on the request-plane
        # control message (codec.RequestControlMessage tenant/priority)
        nvext = body.get("nvext") or {}
        if nvext.get("tenant") is not None:
            ectx.tenant = str(nvext["tenant"])
        if nvext.get("priority") is not None:
            ectx.qos = str(nvext["priority"])
        # end-to-end deadline (docs/chaos.md): nvext.deadline_ms or the
        # X-Request-Deadline-Ms header arms a budget that rides the
        # request plane (codec.RequestControlMessage.deadline_ms) all
        # the way into the engine's per-tick cancellation sweep
        deadline_ms = ((body.get("nvext") or {}).get("deadline_ms")
                       or request.headers.get("X-Request-Deadline-Ms"))
        if deadline_ms is not None:
            try:
                ectx.set_deadline_ms(float(deadline_ms))
            except (TypeError, ValueError):
                return _error_response(
                    400, f"invalid deadline_ms: {deadline_ms!r}")
        # per-request trace (reference egress/push.rs:134-151): stage
        # latencies from HTTP ingress through dispatch to last byte, keyed
        # by the request id the control plane already carries everywhere
        with use_trace(Trace(ectx.id, role="frontend")) as ftrace:
            with span("dispatch", model=model, endpoint=endpoint):
                try:
                    if n_choices == 1:
                        stream = await engine.generate(Context(body, ectx))
                    else:
                        stream = await _start_fanout(engine, body, ectx,
                                                     n_choices)
                except ValueError as e:
                    ftrace.set_error(str(e))
                    guard.close()
                    return _error_response(400, str(e))
                except Exception as e:  # noqa: BLE001 — engine boundary
                    ftrace.set_error(str(e))
                    logger.exception("engine error on %s", endpoint)
                    guard.close()
                    return _error_response(
                        500, f"engine error: {e}", "internal_error")

            if streaming:
                include_usage = bool((body.get("stream_options") or {})
                                     .get("include_usage"))
                with span("stream"):
                    return await self._stream_sse(request, stream, ectx,
                                                  guard, include_usage)
            with span("aggregate"):
                return await self._unary(stream, ectx, guard, is_chat)

    async def _unary(self, stream, ectx: EngineContext, guard,
                     is_chat: bool) -> web.Response:
        try:
            folded = await (aggregate_chat_stream(stream) if is_chat
                            else aggregate_completion_stream(stream))
            guard.mark_ok()
            # surface the request id so a user report joins the
            # collector's trace tree (docs/observability.md)
            return web.json_response(
                folded, headers={"X-Request-Id": ectx.id})
        except RuntimeError as e:
            return _error_response(500, str(e), "internal_error")
        finally:
            guard.close()

    async def _stream_sse(self, request: web.Request, stream,
                          ectx: EngineContext, guard,
                          include_usage: bool) -> web.StreamResponse:
        resp = web.StreamResponse(status=200, headers={
            "Content-Type": "text/event-stream",
            "Cache-Control": "no-cache",
            "Connection": "keep-alive",
            "X-Accel-Buffering": "no",
            # join a user report to the collector's trace tree
            "X-Request-Id": ectx.id,
        })
        try:
            await resp.prepare(request)
        except (ConnectionResetError, asyncio.CancelledError):
            guard.mark_cancelled()
            guard.close()
            ectx.kill()
            raise

        # Disconnect monitor (reference openai.rs:406): if the client goes
        # away mid-stream, kill() the context so the engine frees its slot.
        # aiohttp has no disconnect future, so poll the transport.
        async def monitor():
            while True:
                await asyncio.sleep(0.25)
                tr = request.transport
                if tr is None or tr.is_closing():
                    guard.mark_cancelled()
                    ectx.kill()
                    return

        monitor_task = asyncio.create_task(monitor())
        first_chunk = True
        try:
            async for ann in stream:
                if not isinstance(ann, Annotated):
                    ann = Annotated.from_data(ann)
                chunk = ann.data
                if first_chunk and isinstance(chunk, dict):
                    # nvext.request_id on the first SSE chunk: SSE
                    # consumers that never see response headers (EventSource
                    # wrappers, log captures) can still join user reports
                    # to collector traces
                    first_chunk = False
                    chunk = dict(chunk)
                    chunk["nvext"] = {**(chunk.get("nvext") or {}),
                                      "request_id": ectx.id}
                    ann = Annotated(data=chunk, id=ann.id, event=ann.event,
                                    comment=ann.comment)
                if isinstance(chunk, dict) and not include_usage:
                    # usage chunks / piggybacked usage are opt-in for SSE
                    if chunk.get("usage") is not None and not chunk.get("choices"):
                        continue
                    if "usage" in chunk:
                        chunk = {k: v for k, v in chunk.items() if k != "usage"}
                        ann = Annotated(data=chunk, id=ann.id, event=ann.event,
                                        comment=ann.comment)
                n_tok = _chunk_token_count(chunk)
                if n_tok:
                    guard.note_token(n_tok)
                try:
                    await resp.write(encode_annotated(ann).encode())
                except (ConnectionResetError, asyncio.CancelledError):
                    guard.mark_cancelled()
                    ectx.kill()
                    return resp
            if not ectx.is_killed:
                try:
                    await resp.write(encode_done().encode())
                    guard.mark_ok()
                except (ConnectionResetError, asyncio.CancelledError):
                    guard.mark_cancelled()
        finally:
            monitor_task.cancel()
            guard.close()
        return resp
