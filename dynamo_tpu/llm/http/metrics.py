"""Prometheus metrics for the HTTP frontend.

Reference: lib/llm/src/http/service/metrics.rs:36-346 — the
`nv_llm_http_service_*` counter/gauge/histogram matrix and the RAII
`InflightGuard` that guarantees the inflight gauge decrements and the request
counter lands in exactly one of {success, error, cancelled} ("status" label)
no matter how the stream ends.
"""

from __future__ import annotations

import time
from typing import Optional

from prometheus_client import (CollectorRegistry, Counter, Gauge, Histogram,
                               generate_latest)

PREFIX = "nv_llm_http_service"

REQUEST_STATUS_SUCCESS = "success"
REQUEST_STATUS_ERROR = "error"
REQUEST_STATUS_CANCELLED = "cancelled"


class ServiceMetrics:
    def __init__(self, registry: Optional[CollectorRegistry] = None):
        self.registry = registry or CollectorRegistry()
        self.requests_total = Counter(
            f"{PREFIX}_requests_total",
            "Total requests by model/endpoint/type/status",
            ["model", "endpoint", "request_type", "status"],
            registry=self.registry)
        self.inflight = Gauge(
            f"{PREFIX}_inflight_requests",
            "Currently inflight requests",
            ["model", "endpoint"],
            registry=self.registry)
        self.request_duration = Histogram(
            f"{PREFIX}_request_duration_seconds",
            "End-to-end request duration",
            ["model", "endpoint"],
            registry=self.registry,
            buckets=(0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0))
        self.time_to_first_token = Histogram(
            f"{PREFIX}_time_to_first_token_seconds",
            "TTFT per streaming request",
            ["model", "endpoint"],
            registry=self.registry,
            buckets=(0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0))
        self.output_tokens = Counter(
            f"{PREFIX}_output_tokens_total",
            "Output tokens (streamed chunks) per model",
            ["model", "endpoint"],
            registry=self.registry)
        self.inter_token_latency = Histogram(
            f"{PREFIX}_inter_token_latency_seconds",
            "Gap between consecutive streamed tokens (ITL)",
            ["model", "endpoint"],
            registry=self.registry,
            buckets=(0.002, 0.005, 0.01, 0.02, 0.05, 0.1, 0.25, 0.5,
                     1.0, 2.5))

    def render(self) -> bytes:
        return generate_latest(self.registry)

    def inflight_guard(self, model: str, endpoint: str,
                       streaming: bool) -> "InflightGuard":
        return InflightGuard(self, model, endpoint, streaming)


class InflightGuard:
    """RAII-style inflight/request-status guard (reference metrics.rs
    `InflightGuard`): create on request admission, call `mark_ok()` on clean
    completion; anything else counts as error/cancelled on close."""

    def __init__(self, metrics: ServiceMetrics, model: str, endpoint: str,
                 streaming: bool):
        self._m = metrics
        self.model = model
        self.endpoint = endpoint
        self.request_type = "stream" if streaming else "unary"
        self._status = REQUEST_STATUS_ERROR
        self._start = time.monotonic()
        self._first_token_at: Optional[float] = None
        self._last_token_at: float = 0.0
        self._m.inflight.labels(model, endpoint).inc()
        self._closed = False

    def mark_ok(self) -> None:
        self._status = REQUEST_STATUS_SUCCESS

    def mark_cancelled(self) -> None:
        self._status = REQUEST_STATUS_CANCELLED

    def note_token(self, n: int = 1) -> None:
        now = time.monotonic()
        if self._first_token_at is None:
            self._first_token_at = now
            self._m.time_to_first_token.labels(self.model, self.endpoint).observe(
                now - self._start)
        else:
            # token-weighted ITL: the arrival gap is split across the n
            # tokens this chunk carries and observed once per token, so
            # histogram _count tracks output_tokens and quantiles weight
            # per token. n comes from the chunk's text-bearing choices —
            # a single choice whose delta batches several tokens' text
            # still counts once (the HTTP layer can't see token counts).
            per_tok = (now - self._last_token_at) / max(n, 1)
            itl = self._m.inter_token_latency.labels(self.model,
                                                     self.endpoint)
            for _ in range(max(n, 1)):
                itl.observe(per_tok)
        self._last_token_at = now
        self._m.output_tokens.labels(self.model, self.endpoint).inc(n)

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._m.inflight.labels(self.model, self.endpoint).dec()
        self._m.requests_total.labels(
            self.model, self.endpoint, self.request_type, self._status).inc()
        self._m.request_duration.labels(self.model, self.endpoint).observe(
            time.monotonic() - self._start)

    def __enter__(self) -> "InflightGuard":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.close()
