"""Model Deployment Card (MDC): canonical, serializable model metadata.

Reference: `ModelDeploymentCard` (lib/llm/src/model_card/model.rs:94-230) and
its builders from an HF-style local repo (model_card/create.rs:41-185). The
card is what travels through discovery so frontends/routers can preprocess for
a model they never loaded: tokenizer artifact, context length, EOS ids, chat
template, and a content checksum (`mdcsum`) used to verify that two processes
agree on preprocessing.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
from typing import Any, Dict, List, Optional

from .tokenizer import HuggingFaceTokenizer, load_tokenizer, read_special_token_ids


@dataclasses.dataclass
class ModelInfo:
    """Reference model_card `ModelInfo`: generation-relevant config."""

    model_type: str = "llama"
    context_length: int = 4096
    vocab_size: int = 0
    eos_token_ids: List[int] = dataclasses.field(default_factory=list)
    bos_token_id: Optional[int] = None


@dataclasses.dataclass
class PromptFormatArtifact:
    """Chat-template artifact (reference model_card `PromptFormatterArtifact`,
    incl. the `.jinja`-file quirk handled in preprocessor/prompt/template)."""

    chat_template: Optional[str] = None
    add_generation_prompt: bool = True


@dataclasses.dataclass
class ModelDeploymentCard:
    display_name: str
    service_name: str
    model_path: Optional[str] = None
    tokenizer_file: Optional[str] = None
    model_info: ModelInfo = dataclasses.field(default_factory=ModelInfo)
    prompt_format: PromptFormatArtifact = dataclasses.field(default_factory=PromptFormatArtifact)
    model_type: str = "chat"  # "chat" | "completion" (reference model_type.rs:36)
    revision: int = 0

    _tokenizer: Optional[HuggingFaceTokenizer] = dataclasses.field(
        default=None, repr=False, compare=False)

    # -- serialization -----------------------------------------------------
    def to_json_dict(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        d.pop("_tokenizer", None)
        return d

    @classmethod
    def from_json_dict(cls, d: Dict[str, Any]) -> "ModelDeploymentCard":
        d = dict(d)
        d.pop("_tokenizer", None)
        info = d.pop("model_info", {}) or {}
        fmt = d.pop("prompt_format", {}) or {}
        return cls(model_info=ModelInfo(**info),
                   prompt_format=PromptFormatArtifact(**fmt), **d)

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.to_json_dict(), f, indent=2)

    @classmethod
    def load(cls, path: str) -> "ModelDeploymentCard":
        with open(path) as f:
            return cls.from_json_dict(json.load(f))

    def mdcsum(self) -> str:
        """Content checksum (reference `mdcsum`, model_card/model.rs)."""
        blob = json.dumps(self.to_json_dict(), sort_keys=True).encode()
        return hashlib.blake2s(blob, digest_size=16).hexdigest()

    # -- tokenizer ---------------------------------------------------------
    def tokenizer(self) -> HuggingFaceTokenizer:
        if self._tokenizer is None:
            src = self.tokenizer_file or self.model_path
            if src is None:
                raise RuntimeError(f"MDC {self.display_name} has no tokenizer artifact")
            self._tokenizer = load_tokenizer(src)
        return self._tokenizer

    # -- builders ----------------------------------------------------------
    @classmethod
    def from_local_path(cls, model_dir: str,
                        display_name: Optional[str] = None) -> "ModelDeploymentCard":
        """Build from an HF-style directory (reference model_card/create.rs:41-185):
        reads tokenizer.json, config.json, generation_config.json and
        tokenizer_config.json (chat_template, incl. separate *.jinja files)."""
        name = display_name or os.path.basename(os.path.normpath(model_dir))
        card = cls(display_name=name, service_name=name, model_path=model_dir)
        tok_file = os.path.join(model_dir, "tokenizer.json")
        if os.path.exists(tok_file):
            card.tokenizer_file = tok_file
        tk = card.tokenizer()
        specials = read_special_token_ids(model_dir, tk)
        cfg: Dict[str, Any] = {}
        cfg_path = os.path.join(model_dir, "config.json")
        if os.path.exists(cfg_path):
            with open(cfg_path) as f:
                cfg = json.load(f)
        card.model_info = ModelInfo(
            model_type=cfg.get("model_type", "llama"),
            context_length=int(cfg.get("max_position_embeddings", 4096)),
            vocab_size=int(cfg.get("vocab_size", tk.vocab_size)),
            eos_token_ids=specials["eos_token_ids"],
            bos_token_id=specials["bos_token_id"],
        )
        card.prompt_format = _load_chat_template(model_dir)
        return card


def _load_chat_template(model_dir: str) -> PromptFormatArtifact:
    """chat_template from tokenizer_config.json; handles the list-valued form
    and standalone chat_template.jinja files (reference
    preprocessor/prompt/template/tokcfg.rs quirks)."""
    art = PromptFormatArtifact()
    cfg_path = os.path.join(model_dir, "tokenizer_config.json")
    template: Any = None
    if os.path.exists(cfg_path):
        with open(cfg_path) as f:
            template = json.load(f).get("chat_template")
    if template is None:
        for name in ("chat_template.jinja", "chat_template.json"):
            p = os.path.join(model_dir, name)
            if os.path.exists(p):
                with open(p) as f:
                    raw = f.read()
                if name.endswith(".json"):
                    try:
                        template = json.loads(raw).get("chat_template")
                    except json.JSONDecodeError:
                        template = None
                else:
                    template = raw
                break
    if isinstance(template, list):
        # list of {name, template} — prefer "default"
        by_name = {t.get("name"): t.get("template") for t in template
                   if isinstance(t, dict)}
        template = by_name.get("default") or next(iter(by_name.values()), None)
    if isinstance(template, str):
        art.chat_template = template
    return art
