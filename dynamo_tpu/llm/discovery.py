"""Model discovery: ModelEntry records in the KV store + the watcher that
keeps an HTTP frontend's model list in sync.

Reference: llmctl writes ``ModelEntry{name, endpoint, model_type}`` into etcd
(launch/llmctl/src/main.rs:81-210) and the HTTP service watches the prefix,
adding/removing served models as workers come and go
(lib/llm/src/http/service/discovery.rs:37-145, components/http/src/main.rs:
49-110). Same shape here: entries live under ``models/{chat|completion}/
{name}``; ``ModelWatcher`` wires a distributed Client per entry into a
``ModelManager``."""

from __future__ import annotations

import asyncio
import dataclasses
import json
import logging
from typing import Dict, Optional

from ..runtime.distributed import DistributedRuntime, Endpoint
from ..runtime.kvstore import WatchEventType

logger = logging.getLogger("dynamo_tpu.llm.discovery")

__all__ = ["ModelEntry", "ModelWatcher", "model_key", "MODELS_PREFIX"]

MODELS_PREFIX = "models/"


def model_key(model_type: str, name: str) -> str:
    return f"{MODELS_PREFIX}{model_type}/{name}"


@dataclasses.dataclass
class ModelEntry:
    """One served model → the dyn:// endpoint that serves it."""

    name: str
    endpoint: str                 # "dyn://ns/comp/ep" or "ns.comp.ep"
    model_type: str = "chat"      # chat | completion

    def to_json(self) -> bytes:
        return json.dumps(dataclasses.asdict(self)).encode()

    @classmethod
    def from_json(cls, raw: bytes) -> "ModelEntry":
        return cls(**json.loads(raw))


async def register_model(runtime: DistributedRuntime, entry: ModelEntry,
                         lease_id: int = 0) -> None:
    """Write a ModelEntry. Self-registering workers pass their primary
    lease so the entry dies with the worker (frontends then drop the model
    instead of routing to a ghost); llmctl-managed entries persist."""
    await runtime.store.kv_put(model_key(entry.model_type, entry.name),
                               entry.to_json(), lease_id=lease_id)


async def remove_model(runtime: DistributedRuntime, model_type: str,
                       name: str) -> bool:
    return await runtime.store.kv_delete(model_key(model_type, name))


async def list_models(runtime: DistributedRuntime) -> Dict[str, ModelEntry]:
    out: Dict[str, ModelEntry] = {}
    for e in await runtime.store.kv_get_prefix(MODELS_PREFIX):
        try:
            out[e.key] = ModelEntry.from_json(e.value)
        except Exception:  # noqa: BLE001
            logger.warning("bad model entry at %s", e.key)
    return out


class ModelWatcher:
    """Watches ``models/`` and keeps a ModelManager in sync: a PUT builds a
    routed Client pipeline to the entry's endpoint; a DELETE removes the
    model. The served request/response is the OpenAI JSON dict the worker's
    pipeline speaks (frontend stays model-agnostic)."""

    def __init__(self, runtime: DistributedRuntime, manager,
                 router_mode: str = "random"):
        self.runtime = runtime
        self.manager = manager
        self.router_mode = router_mode
        # key → endpoint path; engines are shared per endpoint (a worker
        # registering chat+completion costs one client, not two)
        self._entries: Dict[str, str] = {}
        self._engines: Dict[str, object] = {}      # endpoint path → engine
        self._watcher = None
        self._task: Optional[asyncio.Task] = None

    async def start(self) -> "ModelWatcher":
        self._watcher = await self.runtime.store.watch_prefix(MODELS_PREFIX)
        self._task = asyncio.get_running_loop().create_task(
            self._loop(), name="model-watcher")
        return self

    async def _loop(self) -> None:
        async for ev in self._watcher:
            key = ev.entry.key
            try:
                if ev.type == WatchEventType.PUT:
                    await self._add(key, ModelEntry.from_json(ev.entry.value))
                else:
                    await self._remove(key)
            except Exception:  # noqa: BLE001
                logger.exception("model watch event failed for %s", key)

    async def _engine_for(self, path: str):
        engine = self._engines.get(path)
        if engine is None:
            from .engines.remote import RemoteEngine
            endpoint = Endpoint.parse_path(self.runtime, path)
            engine = await RemoteEngine.start(endpoint,
                                              router_mode=self.router_mode)
            self._engines[path] = engine
        return engine

    def _canonical(self, endpoint: str) -> str:
        """Both accepted spellings (dyn://ns/c/e and ns.c.e) must share one
        client and one GC identity."""
        return Endpoint.parse_path(self.runtime, endpoint).path

    async def _gc_engine(self, path: str) -> None:
        if path not in self._entries.values():
            engine = self._engines.pop(path, None)
            if engine is not None:
                await engine.close()

    async def _add(self, key: str, entry: ModelEntry) -> None:
        path = self._canonical(entry.endpoint)
        old_path = self._entries.get(key)
        engine = await self._engine_for(path)
        self._entries[key] = path
        if old_path is not None and old_path != path:
            await self._gc_engine(old_path)   # re-registration moved target
        if entry.model_type == "completion":
            self.manager.add_completion_model(entry.name, engine)
        else:
            self.manager.add_chat_model(entry.name, engine)
        logger.info("model added: %s (%s) → %s", entry.name,
                    entry.model_type, entry.endpoint)

    async def _remove(self, key: str) -> None:
        path = self._entries.pop(key, None)
        if path is not None:
            await self._gc_engine(path)
        parts = key[len(MODELS_PREFIX):].split("/", 1)
        if len(parts) == 2:
            self.manager.remove_model(parts[1], model_type=parts[0])
            logger.info("model removed: %s (%s)", parts[1], parts[0])

    async def stop(self) -> None:
        # claim before the await (DL008): a racing second stop() must not
        # re-cancel/re-await the same pump
        task, self._task = self._task, None
        if task is not None:
            task.cancel()
            try:
                await task                # let an in-flight _add finish/abort
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
        if self._watcher is not None:
            self._watcher.close()
        for engine in self._engines.values():
            await engine.close()
        self._engines.clear()
        self._entries.clear()
