"""Persistent disk (G3) KV tier: content-addressed block store with
write-behind spill and cross-restart prefix reuse.

Reference: the KV storage manager's ladder Device → Pinned-Host → Disk →
Remote (SURVEY §KvStorageManager; README "KV cache offloading across
memory hierarchies"). Our ladder previously stopped at host DRAM — a
`HostKvPool` LRU eviction (offload.py) discarded the block forever and
every engine restart started stone cold. This module adds the capacity
tier below DRAM:

- :class:`DiskKvStore` — a capacity-bounded, content-addressed on-disk
  block store keyed by the existing xxh3 chained sequence hashes
  (blocks.py). Each block is one ``blk-<hash>.npz`` file written
  tmp → fsync → rename, acknowledged only by an fsync'd append to
  ``manifest.jsonl`` — so an acknowledged block survives kill -9 and a
  fresh engine warm-starts from the previous run's cache (the
  "Prefill-as-a-Service" semantics: cached KV outlives the process that
  produced it). A partially-written block is invisible on recovery: the
  rename is atomic and the manifest line lands only after the data file
  is durable (the runtime/wal.py torn-tail discipline applied per block).
- :class:`DiskSpillEngine` — the async write-behind pump: host-tier
  evictions become bounded-queue spill jobs; the file I/O runs off-thread
  (asyncio.to_thread) so spill never blocks the engine loop, and
  saturation DROPS the job with a counter instead of stalling
  (``dropped_jobs_total`` — the same backpressure contract the offload
  pump has).

Multihost: a follower mirrors the leader's disk tier verbatim — the
leader streams literal placement decisions ("kv_disk_store": hash +
evicted set) and the follower applies them via :meth:`DiskKvStore
.apply_put` with arena bytes staged from its own bit-identical host
mirror, never re-running the LRU policy (the HostKvPool.apply_store
contract extended one tier down; engine/multihost.py).
"""

from __future__ import annotations

import asyncio
import dataclasses
import io
import json
import logging
import os
import threading
import time
from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

logger = logging.getLogger("dynamo_tpu.kv.diskstore")

__all__ = ["DiskKvStore", "DiskSpillEngine", "SpillJob"]

_MANIFEST = "manifest.jsonl"
_META = "meta.json"


@dataclasses.dataclass
class _Entry:
    seq_hash: int
    tokens_hash: Optional[int]
    parent_hash: Optional[int]
    fname: str
    nbytes: int


def _blk_fname(seq_hash: int) -> str:
    return f"blk-{seq_hash & 0xFFFFFFFFFFFFFFFF:016x}.npz"


def _resolve_dtype(name: str) -> np.dtype:
    """Dtype by name, including the ml_dtypes extension types (bfloat16
    KV pools — np.savez alone would round-trip them as anonymous void
    '|V2' and the device scatter would reject them)."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes
        return np.dtype(getattr(ml_dtypes, name))


def _pack_block(values: dict) -> dict:
    """Per-block dict → npz-safe payload: raw uint8 bytes per key plus a
    JSON ``__meta__`` entry recording each array's true dtype and shape.
    Byte-exact for ANY dtype (incl. bfloat16 / int8 opaque rows)."""
    meta = {}
    out = {}
    for k, v in values.items():
        v = np.ascontiguousarray(v)
        meta[k] = {"dtype": str(v.dtype), "shape": list(v.shape)}
        out[k] = np.frombuffer(v.tobytes(), np.uint8)
    out["__meta__"] = np.frombuffer(json.dumps(meta).encode(), np.uint8)
    return out


def _unpack_block(z) -> dict:
    meta = json.loads(z["__meta__"].tobytes().decode())
    return {k: np.frombuffer(z[k].tobytes(),
                             _resolve_dtype(m["dtype"])).reshape(m["shape"])
            for k, m in meta.items()}


class DiskKvStore:
    """Content-addressed on-disk KV block store (the G3 tier).

    Keys are the chained xxh3 sequence hashes (blocks.py) — the same
    identity the device pool and host tier use, so a hash found here is
    byte-identical content by construction. Values are per-block dicts
    mirroring the host arena's per-row layout ({"k": [L, H, bs, D],
    "v": …}; int8/MLA pools ship one opaque "kv"/row entry), stored
    np.savez (no pickle).

    Durability contract (asserted by tests/test_kv_disk.py kill -9):
    - a block is acknowledged ⇔ its manifest "put" line is fsync'd;
    - the data file is fsync'd + atomically renamed BEFORE that line, so
      an acknowledged block always has whole bytes;
    - a crash between rename and manifest append leaves an orphan data
      file that recovery deletes — never a corrupt read;
    - deletes append a manifest "del" line before the unlink, so a crash
      between them leaves an orphan the next open removes.

    Thread-safety: index mutations lock (the spill pump writes from a
    worker thread while the engine loop matches/pins); file reads of
    pinned entries are safe against concurrent eviction because eviction
    skips pinned hashes (requeue, like the host pool).
    """

    def __init__(self, root: str, capacity_blocks: int,
                 expect_block_size: Optional[int] = None):
        self.root = root
        self.capacity = int(capacity_blocks)
        os.makedirs(root, exist_ok=True)
        self._lock = threading.RLock()
        # insertion order IS the LRU order (match_prefix re-inserts)
        self._entries: "OrderedDict[int, _Entry]" = OrderedDict()
        self._pins: Dict[int, int] = {}
        self._manifest_f: Optional[io.TextIOWrapper] = None
        self.meta: dict = {}
        # capacity-eviction hook: called with (seq_hash, tokens_hash,
        # parent_hash, values) BEFORE the block leaves the store — the
        # remote (G4) promotion feed (remotestore.py), mirroring the
        # host pool's on_evict one rung up. Fires on whichever thread
        # ran the put (usually the spill pump's worker thread); the
        # callee owns the values dict outright. clear()/apply_put
        # deletions do NOT fire it — only capacity pressure promotes.
        self.on_evict: Optional[Callable] = None
        # multi-tenant quota enforcement (llm/tenancy.py): optional
        # TenantBlockLedger — puts note each hash's tenant in the
        # "disk" tier (owner remembered from warmer tiers), capacity
        # eviction prefers an over-quota tenant's blocks. None = the
        # untenanted LRU exactly.
        self.tenancy = None
        self.tenant_evictions = 0
        # stats (nv_llm_kv_disk_* feed)
        self.stored_blocks_total = 0
        self.evicted_blocks_total = 0
        self.match_queries = 0
        self.match_hits = 0
        self.restored_blocks = 0        # entries recovered at open
        self.reaped_corrupt_blocks = 0  # missing/truncated payloads reaped
        self.bytes_used = 0
        self._recover(expect_block_size)

    # ------------------------------------------------------------- recovery
    def _recover(self, expect_block_size: Optional[int]) -> None:
        meta_path = os.path.join(self.root, _META)
        if os.path.exists(meta_path):
            try:
                with open(meta_path) as f:
                    self.meta = json.load(f)
            except (OSError, ValueError):
                self.meta = {}
        if (expect_block_size is not None and self.meta
                and self.meta.get("block_size") not in (None,
                                                        expect_block_size)):
            logger.warning(
                "disk KV store at %s was written with block_size=%s but "
                "this engine runs block_size=%d — starting cold (the "
                "cached blocks are not addressable under the new "
                "hash/block geometry)", self.root,
                self.meta.get("block_size"), expect_block_size)
            self._wipe()
        man_path = os.path.join(self.root, _MANIFEST)
        live: "OrderedDict[int, _Entry]" = OrderedDict()
        try:
            from ...runtime.faults import hit as _fault
            _fault("diskstore.recovery", exc=OSError)
            if os.path.exists(man_path):
                with open(man_path) as f:
                    for line in f:
                        line = line.strip()
                        if not line:
                            continue
                        try:
                            rec = json.loads(line)
                        except ValueError:
                            # torn tail: never acknowledged
                            break
                        if rec.get("op") == "put":
                            h = int(rec["h"])
                            live.pop(h, None)
                            live[h] = _Entry(
                                seq_hash=h,
                                tokens_hash=rec.get("th"),
                                parent_hash=rec.get("ph"),
                                fname=rec.get("f", _blk_fname(h)),
                                nbytes=int(rec.get("n", 0)))
                        elif rec.get("op") == "del":
                            live.pop(int(rec["h"]), None)
        except OSError:
            # an unreadable manifest (I/O error, yanked volume) must not
            # refuse serving: start cold — the cache is re-creatable,
            # the engine is not (graceful degradation over availability)
            logger.exception("disk KV manifest unreadable at %s — "
                             "starting cold", man_path)
            live = OrderedDict()
        # keep only entries whose data file actually exists AND has the
        # acknowledged byte count — a manifest line with a vanished or
        # truncated payload cannot serve reads. Our own writes are
        # atomic (tmp → fsync → rename), so a short file means external
        # damage (fs corruption, a copied-around cache dir): reap it and
        # count, never surface it (the kill-during-put regression in
        # tests/test_kv_disk.py).
        for h in list(live):
            e = live[h]
            path = os.path.join(self.root, e.fname)
            try:
                size = os.path.getsize(path)
            except OSError:
                live.pop(h)
                continue
            if e.nbytes and size < e.nbytes:
                live.pop(h)
                self.reaped_corrupt_blocks += 1
                logger.warning(
                    "disk KV block %x payload truncated (%d < %d bytes) "
                    "— reaped", h & 0xFFFFFFFFFFFFFFFF, size, e.nbytes)
        self._entries = live
        self.restored_blocks = len(live)
        self.bytes_used = sum(e.nbytes for e in live.values())
        # remove orphan data files: written (renamed) but never
        # acknowledged, or deleted-in-manifest but not yet unlinked
        keep = {e.fname for e in live.values()}
        for fn in os.listdir(self.root):
            if fn in (_MANIFEST, _META) or fn in keep:
                continue
            if fn.startswith(("blk-", "tmp-")):
                try:
                    os.unlink(os.path.join(self.root, fn))
                except OSError:
                    pass
        # compact: rewrite the manifest as pure puts of the live set
        self._rewrite_manifest()
        if expect_block_size is not None:
            self.meta.setdefault("block_size", expect_block_size)
            self._write_meta()
        if live:
            logger.info("disk KV store warm start: %d blocks (%.1f MB) "
                        "recovered from %s", len(live),
                        self.bytes_used / 1e6, self.root)

    def _wipe(self) -> None:
        for fn in os.listdir(self.root):
            try:
                os.unlink(os.path.join(self.root, fn))
            except OSError:
                pass
        self.meta = {}
        self._entries = OrderedDict()
        self.bytes_used = 0

    def _write_meta(self) -> None:
        tmp = os.path.join(self.root, _META + ".tmp")
        with open(tmp, "w") as f:
            json.dump(self.meta, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(self.root, _META))

    def _rewrite_manifest(self) -> None:
        if self._manifest_f is not None:
            self._manifest_f.close()
            self._manifest_f = None
        tmp = os.path.join(self.root, _MANIFEST + ".tmp")
        with open(tmp, "w") as f:
            for e in self._entries.values():
                f.write(json.dumps({"op": "put", "h": e.seq_hash,
                                    "th": e.tokens_hash,
                                    "ph": e.parent_hash,
                                    "f": e.fname, "n": e.nbytes}) + "\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(self.root, _MANIFEST))
        self._fsync_dir()
        self._manifest_f = open(os.path.join(self.root, _MANIFEST), "a")

    def _fsync_dir(self) -> None:
        try:
            fd = os.open(self.root, os.O_RDONLY)
            try:
                os.fsync(fd)
            finally:
                os.close(fd)
        except OSError:
            pass                        # not all filesystems support it

    def _append_manifest(self, recs: List[dict]) -> None:
        if self._manifest_f is None:
            self._manifest_f = open(os.path.join(self.root, _MANIFEST), "a")
        for rec in recs:
            self._manifest_f.write(json.dumps(rec) + "\n")
        self._manifest_f.flush()
        os.fsync(self._manifest_f.fileno())

    # -------------------------------------------------------------- queries
    def __len__(self) -> int:
        return len(self._entries)

    @property
    def used_blocks(self) -> int:
        return len(self._entries)

    def contains(self, seq_hash: int) -> bool:
        with self._lock:
            return seq_hash in self._entries

    def hit_rate(self) -> float:
        return self.match_hits / max(self.match_queries, 1)

    def match_prefix(self, seq_hashes: Sequence[int],
                     pin: bool = False) -> List[int]:
        """Longest leading run of hashes present; returns the matched
        HASHES (content addressing has no slot indirection) and freshens
        LRU order. ``pin=True`` pins the matched entries atomically under
        the lock so the spill pump's capacity evictions (worker thread)
        cannot delete them before the off-thread onboard read."""
        out: List[int] = []
        with self._lock:
            for h in seq_hashes:
                self.match_queries += 1
                e = self._entries.get(h)
                if e is None:
                    break
                self.match_hits += 1
                self._entries.move_to_end(h)
                if pin:
                    self._pins[h] = self._pins.get(h, 0) + 1
                out.append(h)
        return out

    def pin(self, seq_hashes: Sequence[int]) -> None:
        with self._lock:
            for h in seq_hashes:
                self._pins[h] = self._pins.get(h, 0) + 1

    def unpin(self, seq_hashes: Sequence[int]) -> None:
        with self._lock:
            for h in seq_hashes:
                n = self._pins.get(h, 0) - 1
                if n <= 0:
                    self._pins.pop(h, None)
                else:
                    self._pins[h] = n

    def registered_entries(self) -> List[tuple]:
        """Every resident block as (seq_hash, tokens_hash, parent_hash) —
        the reannounce inventory (router radix index bring-up)."""
        with self._lock:
            return [(e.seq_hash, e.tokens_hash, e.parent_hash)
                    for e in self._entries.values()]

    # ---------------------------------------------------------------- reads
    def fetch(self, seq_hashes: Sequence[int]) -> dict:
        """Stacked wire values for ``seq_hashes``, keyed like the host
        pool's fetch: {key: [L, H, n, bs, D]}. Callers pin first — an
        unpinned entry may be evicted mid-read."""
        blocks = []
        for h in seq_hashes:
            with self._lock:
                e = self._entries.get(h)
            if e is None:
                raise KeyError(f"disk KV block {h:#x} is not resident")
            with np.load(os.path.join(self.root, e.fname)) as z:
                blocks.append(_unpack_block(z))
        return {k: np.ascontiguousarray(
                    np.stack([b[k] for b in blocks], axis=2))
                for k in blocks[0]}

    # --------------------------------------------------------------- writes
    def _validate_layout(self, values: dict) -> None:
        layout = {k: [list(v.shape), str(np.dtype(v.dtype))]
                  for k, v in values.items()}
        known = self.meta.get("layout")
        if known is None:
            self.meta["layout"] = layout
            self._write_meta()
        elif known != layout:
            logger.warning(
                "disk KV store layout changed (%s -> %s) — dropping the "
                "stale cache (a restored block of the old shape would "
                "corrupt the device scatter)", known, layout)
            with self._lock:
                self._wipe()
            self.meta = {"layout": layout,
                         "block_size": self.meta.get("block_size")}
            self._write_meta()
            self._rewrite_manifest()

    def _tenant_victim(self) -> Optional[int]:
        """Bounded LRU-front scan for an unpinned block whose tenant is
        over its disk-tier quota (llm/tenancy.py) — it evicts before
        anyone else's. None = no preferred victim in scan range."""
        if self.tenancy is None:
            return None
        for i, h in enumerate(self._entries):
            if i >= 64:
                break
            if self._pins.get(h):
                continue
            if self.tenancy.is_over_quota_hash(h, "disk"):
                self.tenant_evictions += 1
                return h
        return None

    def _evict_for_capacity(self) -> List[int]:
        """Pick LRU victims (skipping pinned, which requeue) until one
        slot is free; an over-quota tenant's blocks go first
        (_tenant_victim). Returns the evicted hashes; [] when nothing
        had to go; raises BlockingIOError when everything is pinned."""
        evicted: List[int] = []
        scanned = 0
        while len(self._entries) >= self.capacity:
            if scanned >= len(self._entries):
                raise BlockingIOError("disk KV store full and all pinned")
            h = self._tenant_victim()
            if h is None:
                h = next(iter(self._entries))
            if self._pins.get(h):
                self._entries.move_to_end(h)   # requeue pinned candidate
                scanned += 1
                continue
            if self.on_evict is not None:
                # read the bytes BEFORE the unlink and hand them to the
                # remote (G4) promotion feed; best-effort — a failed
                # read just forfeits the promotion, never the eviction
                e = self._entries[h]
                try:
                    with np.load(os.path.join(self.root, e.fname)) as z:
                        values = _unpack_block(z)
                    self.on_evict(h, e.tokens_hash, e.parent_hash, values)
                except Exception:  # noqa: BLE001
                    logger.exception("disk-tier evict hook failed")
            evicted.append(h)
            self._delete_locked(h)
        return evicted

    def _delete_locked(self, h: int) -> None:
        e = self._entries.pop(h, None)
        if e is None:
            return
        self.bytes_used -= e.nbytes
        self.evicted_blocks_total += 1
        if self.tenancy is not None:
            self.tenancy.forget(h, "disk")
        # manifest del BEFORE unlink: a crash in between leaves an orphan
        # file the next open removes — never a live entry without bytes
        self._append_manifest([{"op": "del", "h": h}])
        try:
            os.unlink(os.path.join(self.root, e.fname))
        except OSError:
            pass

    def put(self, seq_hash: int, values: dict,
            tokens_hash: Optional[int] = None,
            parent_hash: Optional[int] = None) -> Optional[List[int]]:
        """Store one block under its chained hash. Returns the list of
        hashes evicted to make room (usually []), or None when the block
        was skipped (already resident, zero capacity, or everything
        pinned). Durable on return: data fsync'd + renamed, manifest line
        fsync'd."""
        if self.capacity <= 0:
            return None
        with self._lock:
            if seq_hash in self._entries:
                self._entries.move_to_end(seq_hash)
                return None
            try:
                evicted = self._evict_for_capacity()
            except BlockingIOError:
                return None
            self._validate_layout(values)
            nbytes = self._write_block(seq_hash, values, tokens_hash,
                                       parent_hash)
            self._entries[seq_hash] = _Entry(seq_hash, tokens_hash,
                                             parent_hash,
                                             _blk_fname(seq_hash), nbytes)
            self.bytes_used += nbytes
            self.stored_blocks_total += 1
            if self.tenancy is not None:
                # owner carried from the warmer tiers (ledger memory)
                self.tenancy.note(seq_hash, None, "disk")
            return evicted

    def _write_block(self, seq_hash: int, values: dict,
                     tokens_hash, parent_hash) -> int:
        from ...runtime.faults import hit as _fault
        from ...runtime.faults import mangle as _mangle
        _fault("diskstore.write")           # enospc/delay chaos site
        fname = _blk_fname(seq_hash)
        tmp = os.path.join(self.root, "tmp-" + fname)
        buf = io.BytesIO()
        np.savez(buf, **_pack_block(values))
        data = buf.getvalue()
        nbytes = len(data)                  # the INTENDED byte count —
        # a torn write (chaos or external damage) leaves fewer bytes on
        # disk than the manifest acknowledges, which is exactly what
        # recovery's size check reaps
        with open(tmp, "wb") as f:
            f.write(_mangle("diskstore.write", data))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(self.root, fname))
        self._fsync_dir()
        # the acknowledgement: manifest line AFTER the durable data file
        self._append_manifest([{"op": "put", "h": seq_hash,
                                "th": tokens_hash, "ph": parent_hash,
                                "f": fname, "n": nbytes}])
        return nbytes

    def apply_put(self, seq_hash: int, evicted: Sequence[int],
                  values: dict, tokens_hash: Optional[int] = None,
                  parent_hash: Optional[int] = None) -> None:
        """Apply one of the leader's literal spill placements to a mirror
        store (multihost follower): delete exactly the leader's eviction
        set, then store — the LRU policy never re-runs on followers
        (HostKvPool.apply_store one tier down)."""
        with self._lock:
            for h in evicted:
                self._delete_locked(h)
            if seq_hash in self._entries:
                return
            self._validate_layout(values)
            nbytes = self._write_block(seq_hash, values, tokens_hash,
                                       parent_hash)
            self._entries[seq_hash] = _Entry(seq_hash, tokens_hash,
                                             parent_hash,
                                             _blk_fname(seq_hash), nbytes)
            self.bytes_used += nbytes
            self.stored_blocks_total += 1

    def clear(self) -> int:
        """Drop every resident block (llmctl kv flush --clear). Returns
        the number of blocks removed."""
        with self._lock:
            n = len(self._entries)
            for h in list(self._entries):
                self._delete_locked(h)
            return n

    def close(self) -> None:
        if self._manifest_f is not None:
            self._manifest_f.close()
            self._manifest_f = None


@dataclasses.dataclass
class SpillJob:
    """One evicted host-tier block headed for disk. ``values`` is a
    host-side COPY of the arena row (taken before the eviction's
    overwrite), so the job owns its bytes outright — no pins needed."""

    seq_hash: int
    tokens_hash: Optional[int]
    parent_hash: Optional[int]
    values: dict


class DiskSpillEngine:
    """Asynchronous host→disk write-behind pump.

    The host pool's eviction hook offers jobs on the engine loop; the
    pump batches them and runs the fsync-heavy file writes off-thread
    (asyncio.to_thread), so spill NEVER blocks the engine loop. The
    queue is bounded: saturation drops the job and counts it
    (``dropped_jobs_total``) — losing a cache block under pressure is
    strictly better than stalling decode (the KvOffloadEngine
    backpressure contract, one tier down)."""

    def __init__(self, store: DiskKvStore, max_queue_jobs: int = 256,
                 max_batch_jobs: int = 32,
                 on_commit: Optional[Callable[[list], None]] = None):
        self.store = store
        self.max_queue_jobs = max_queue_jobs
        self.max_batch_jobs = max_batch_jobs
        # called on the loop with [(hash, tokens_hash, parent, evicted)]
        # after each committed batch — the leader's dispatch-stream hook
        # (engine/multihost.py "kv_disk_store")
        self.on_commit = on_commit
        self._queue: asyncio.Queue = asyncio.Queue()
        self._task: Optional[asyncio.Task] = None
        self.spilled_blocks_total = 0
        self.dropped_jobs_total = 0
        # writes the disk refused (ENOSPC, I/O error): the pump SHEDS
        # the job — losing a re-creatable cache block — and serving
        # continues (nv_llm_kv_disk_spill_shed_writes_total)
        self.shed_writes_total = 0
        self.write_s = 0.0

    def offer(self, job: SpillJob) -> bool:
        """Non-blocking enqueue; False (counted) when the queue is
        saturated or the block is already resident on disk."""
        if self.store.contains(job.seq_hash):
            return False
        if self._queue.qsize() >= self.max_queue_jobs:
            self.dropped_jobs_total += 1
            return False
        self._queue.put_nowait(job)
        self._ensure_task()
        return True

    def _ensure_task(self) -> None:
        if self._task is None or self._task.done():
            try:
                loop = asyncio.get_running_loop()
            except RuntimeError:
                return
            self._task = loop.create_task(self._run(), name="kv-disk-spill")

    async def _run(self) -> None:
        while True:
            job: SpillJob = await self._queue.get()
            jobs = [job]
            while (len(jobs) < self.max_batch_jobs
                   and not self._queue.empty()):
                jobs.append(self._queue.get_nowait())
            try:
                await self._process(jobs)
            except Exception:  # noqa: BLE001 — spill is best-effort
                logger.exception("disk spill batch failed")
            finally:
                for _ in jobs:
                    self._queue.task_done()
            await asyncio.sleep(0)      # yield to the engine loop

    async def _process(self, jobs: List[SpillJob]) -> None:
        def write_batch():
            from ...runtime.faults import hit as _fault
            out = []
            shed = 0
            t0 = time.monotonic()
            for j in jobs:
                try:
                    _fault("diskstore.spill")   # enospc/delay chaos site
                    evicted = self.store.put(j.seq_hash, j.values,
                                             j.tokens_hash, j.parent_hash)
                except OSError as e:
                    # full/failing disk mid-spill: SHED the write-behind
                    # job (the block is re-creatable from recompute) and
                    # keep pumping — disk pressure must degrade the
                    # cache, never the serving path
                    shed += 1
                    logger.warning("disk spill shed block %x: %s",
                                   j.seq_hash & 0xFFFFFFFFFFFFFFFF, e)
                    continue
                if evicted is not None:
                    out.append((j.seq_hash, j.tokens_hash, j.parent_hash,
                                list(evicted)))
            return out, shed, time.monotonic() - t0

        committed, shed, dt = await asyncio.to_thread(write_batch)
        self.write_s += dt
        self.shed_writes_total += shed
        self.spilled_blocks_total += len(committed)
        if self.on_commit is not None and committed:
            self.on_commit(committed)

    async def drain(self) -> None:
        self._ensure_task()
        await self._queue.join()

    async def stop(self) -> None:
        try:
            await asyncio.wait_for(self.drain(), timeout=10)
        except asyncio.TimeoutError:
            logger.warning("disk spill drain timed out; dropping queue")
            while not self._queue.empty():
                self._queue.get_nowait()
                self._queue.task_done()
        if self._task is not None:
            self._task.cancel()
            self._task = None
