"""Remote (G4) KV tier: the fleet fabric's storage rung.

Reference: the KV storage manager's ladder Device → Pinned-Host → Disk →
Remote (SURVEY §KvStorageManager) and the "accelerated cross-worker KV
transfer" pillar (NIXL). PAPERS.md grounds the design: FlowKV
(arXiv:2504.03775) low-latency disaggregated KV transfer and NetKV
(arXiv:2606.03910) network-aware decode-instance selection. Our ladder
previously stopped at the per-worker disk (G3) tier — a prefix evicted
to one worker's disk was invisible to every other worker, so a fleet
re-prefilled what the fleet had already computed. This module adds the
rung below disk, implementing the EXACT ``DiskKvStore`` contract
(``contains / match_prefix(pin) / fetch / put / apply_put``) so it slots
behind the same :class:`~dynamo_tpu.llm.kv.pool.KvBlockManager` cascade
and :class:`~dynamo_tpu.llm.kv.diskstore.DiskSpillEngine`-style
promotion pump with no engine changes — exactly the seam the disk tier
was built to leave open (ROADMAP "G4 → cross-datacenter KV fabric").

Two backends:

- :class:`ObjectKvBackend` over :class:`FsObjectStore` — a
  filesystem-rooted object store with a GCS/S3-shaped API
  (put/get/head/delete/list under string keys). Blocks are
  content-addressed npz objects written tmp → fsync → rename, so the
  acknowledged-iff-durable contract of the disk tier holds end to end:
  ``put`` returns only after the object is whole on stable storage, and
  a reader can never observe a torn object (the rename is atomic). This
  is the cross-datacenter durability rung — any worker pointed at the
  same root (a mounted bucket) reuses blocks any other worker produced.
- a **peer-worker backend** — another worker's disk/host store served
  over the runtime's netstore/tcp transport (``kv.fabric`` RPC
  endpoints, :mod:`dynamo_tpu.llm.kv.fabric`). :class:`RemoteKvStore`
  holds the hash→holder index (fed by the same tier-tagged ``kv_events``
  the router consumes) and a ``peer_fetch`` callable the fabric plugs
  in; the blocking fetch runs on the admission's off-thread onboard
  path, never on the engine loop.

The tier is deliberately *pessimistic about itself*: ``match_prefix``
consults a latency-aware admission gate (fabric.AdmissionGate) and
reports NO hit when the modeled fetch time loses to the modeled
recompute time at that depth — a remote hit that is slower than
re-prefilling is not a hit.
"""

from __future__ import annotations

import io
import json
import logging
import os
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .diskstore import _blk_fname, _pack_block, _unpack_block

logger = logging.getLogger("dynamo_tpu.kv.remotestore")

__all__ = ["FsObjectStore", "ObjectKvBackend", "RemoteKvStore",
           "pack_block_bytes", "unpack_block_bytes"]


# ---------------------------------------------------------------------------
# Wire/object serialization: one KV block ↔ npz bytes (chain meta rides
# inside, so a block object is self-describing — the fabric fetch plane
# and the object store share the format)
# ---------------------------------------------------------------------------


def pack_block_bytes(values: dict, tokens_hash: Optional[int] = None,
                     parent_hash: Optional[int] = None) -> bytes:
    """One per-block dict ({key: [L, H, bs, D]}) → self-describing npz
    bytes. Byte-exact for any dtype (bfloat16 / int8 opaque rows) —
    the diskstore pack discipline applied to an in-memory buffer."""
    payload = _pack_block(values)
    payload["__chain__"] = np.frombuffer(
        json.dumps({"th": tokens_hash, "ph": parent_hash}).encode(),
        np.uint8)
    buf = io.BytesIO()
    np.savez(buf, **payload)
    return buf.getvalue()


def unpack_block_bytes(data: bytes) -> Tuple[dict, Optional[int],
                                             Optional[int]]:
    """npz bytes → (values, tokens_hash, parent_hash). Raises ValueError
    on a torn/truncated payload (callers treat that as a miss)."""
    try:
        with np.load(io.BytesIO(data)) as z:
            chain = {}
            if "__chain__" in z.files:
                chain = json.loads(z["__chain__"].tobytes().decode())
            values = _unpack_block(z)
    except Exception as e:  # noqa: BLE001 — any corruption is a miss
        raise ValueError(f"torn KV block payload: {e}") from e
    return values, chain.get("th"), chain.get("ph")


# ---------------------------------------------------------------------------
# Object store (GCS/S3-shaped, filesystem-rooted)
# ---------------------------------------------------------------------------


class FsObjectStore:
    """Filesystem-rooted object store speaking the GCS/S3 verb set:
    ``put_object / get_object / head_object / delete_object /
    list_objects``. The root is the "bucket" (in production a
    gcsfuse/s3fs mount or an NFS export shared across the fleet); keys
    may contain ``/`` and map to subdirectories.

    Durability: ``put_object`` writes tmp → fsync → atomic rename →
    directory fsync, so an acknowledged object always has whole bytes
    and a crashed writer leaves only an invisible ``.tmp-`` dropping
    (reaped lazily). This is the acknowledged-iff-durable contract of
    the disk tier, one rung further out."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)
        self.put_objects_total = 0
        self.get_objects_total = 0

    def _path(self, key: str) -> str:
        if key.startswith("/") or ".." in key.split("/"):
            raise ValueError(f"invalid object key {key!r}")
        return os.path.join(self.root, key)

    def put_object(self, key: str, data: bytes) -> int:
        from ...runtime.faults import hit as _fault
        from ...runtime.faults import mangle as _mangle
        _fault("remotestore.put")           # enospc/delay chaos site
        data = _mangle("remotestore.put", data)
        path = self._path(key)
        d = os.path.dirname(path)
        os.makedirs(d, exist_ok=True)
        tmp = os.path.join(d, ".tmp-" + os.path.basename(path))
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        try:
            fd = os.open(d, os.O_RDONLY)
            try:
                os.fsync(fd)
            finally:
                os.close(fd)
        except OSError:
            pass                          # not all filesystems support it
        self.put_objects_total += 1
        return len(data)

    def get_object(self, key: str) -> Optional[bytes]:
        try:
            with open(self._path(key), "rb") as f:
                self.get_objects_total += 1
                return f.read()
        except OSError:
            return None

    def head_object(self, key: str) -> Optional[int]:
        """Object size, or None when absent (the S3 HEAD)."""
        try:
            return os.path.getsize(self._path(key))
        except OSError:
            return None

    def delete_object(self, key: str) -> bool:
        try:
            os.unlink(self._path(key))
            return True
        except OSError:
            return False

    def list_objects(self, prefix: str = "") -> List[Tuple[str, int, float]]:
        """[(key, size, mtime)] under ``prefix``, ``.tmp-`` droppings
        excluded (they were never acknowledged)."""
        out: List[Tuple[str, int, float]] = []
        base = self.root
        for dirpath, _dirs, files in os.walk(base):
            for fn in files:
                if fn.startswith(".tmp-"):
                    continue
                full = os.path.join(dirpath, fn)
                key = os.path.relpath(full, base).replace(os.sep, "/")
                if not key.startswith(prefix):
                    continue
                try:
                    st = os.stat(full)
                except OSError:
                    continue
                out.append((key, st.st_size, st.st_mtime))
        return sorted(out)


class ObjectKvBackend:
    """KV-block adapter over an object store: content-addressed blocks at
    ``blocks/blk-<hash>.npz``, keyed by the same chained xxh3 sequence
    hashes every other tier uses (a hash found here is byte-identical
    content by construction — the store is shared, so ANY fleet worker's
    put serves every other worker's get).

    Integrity: a torn or truncated object (external corruption — our own
    writes are atomic) is treated as absent, reaped, and counted
    (``reaped_corrupt_total``), mirroring the disk tier's recovery
    discipline. Capacity (optional): oldest-mtime objects are reaped
    once ``capacity_blocks`` is exceeded — approximate LRU, safe because
    every block is re-creatable from its producer's colder history."""

    _PREFIX = "blocks/"

    def __init__(self, root_or_store, capacity_blocks: int = 0):
        self.store = (root_or_store if not isinstance(root_or_store, str)
                      else FsObjectStore(root_or_store))
        self.capacity = int(capacity_blocks)
        self._lock = threading.RLock()
        # hash → size; refreshed from list at open, extended on put and on
        # contains-miss stat (another worker may have put since)
        self._index: Dict[int, int] = {}
        self._pins: Dict[int, int] = {}
        self.stored_blocks_total = 0
        self.evicted_blocks_total = 0
        self.reaped_corrupt_total = 0
        # multi-tenant quota enforcement (llm/tenancy.py): the capacity
        # reaper takes an over-quota tenant's objects first. None = the
        # untenanted oldest-mtime reap exactly.
        self.tenancy = None
        self.tenant_evictions = 0
        self._refresh_index()

    def _key(self, seq_hash: int) -> str:
        return self._PREFIX + _blk_fname(seq_hash)

    @staticmethod
    def _hash_of_key(key: str) -> Optional[int]:
        name = key.rsplit("/", 1)[-1]
        if not (name.startswith("blk-") and name.endswith(".npz")):
            return None
        try:
            h = int(name[4:-4], 16)
        except ValueError:
            return None
        # stored hashes are the signed-int views the rest of the ladder
        # uses; undo the unsigned filename mapping
        return h - (1 << 64) if h >= (1 << 63) else h

    def _refresh_index(self) -> None:
        with self._lock:
            self._index = {}
            for key, size, _mtime in self.store.list_objects(self._PREFIX):
                h = self._hash_of_key(key)
                if h is not None:
                    self._index[h] = size

    # ------------------------------------------------------------- queries
    def __len__(self) -> int:
        return len(self._index)

    @property
    def used_blocks(self) -> int:
        return len(self._index)

    @property
    def bytes_used(self) -> int:
        return sum(self._index.values())

    def contains(self, seq_hash: int) -> bool:
        with self._lock:
            if seq_hash in self._index:
                return True
        # shared store: another fleet worker may have put it since our
        # last look — one HEAD on the miss path keeps the index honest
        size = self.store.head_object(self._key(seq_hash))
        if size is None:
            return False
        with self._lock:
            self._index[seq_hash] = size
        return True

    def registered_entries(self) -> List[tuple]:
        """Every resident block as (seq_hash, tokens_hash, parent_hash).
        Chain meta lives inside each object — read lazily (reannounce is
        a bring-up path, not a hot one)."""
        out = []
        with self._lock:
            hashes = list(self._index)
        for h in hashes:
            data = self.store.get_object(self._key(h))
            if data is None:
                continue
            try:
                _values, th, ph = unpack_block_bytes(data)
            except ValueError:
                self._reap_corrupt(h)
                continue
            out.append((h, th, ph))
        return out

    # --------------------------------------------------------------- reads
    def _reap_corrupt(self, seq_hash: int) -> None:
        self.store.delete_object(self._key(seq_hash))
        with self._lock:
            self._index.pop(seq_hash, None)
        self.reaped_corrupt_total += 1
        logger.warning("reaped torn/truncated remote KV object %x",
                       seq_hash & 0xFFFFFFFFFFFFFFFF)

    def fetch_blocks(self, seq_hashes: Sequence[int]) -> List[dict]:
        """Per-block value dicts in order; KeyError on any miss (callers
        fall back to recompute — a remote miss is never fatal)."""
        blocks = []
        for h in seq_hashes:
            data = self.store.get_object(self._key(h))
            if data is None:
                raise KeyError(f"remote KV object {h:#x} is not resident")
            try:
                values, _th, _ph = unpack_block_bytes(data)
            except ValueError:
                self._reap_corrupt(h)
                raise KeyError(f"remote KV object {h:#x} was torn")
            blocks.append(values)
        return blocks

    # -------------------------------------------------------------- writes
    def put(self, seq_hash: int, values: dict,
            tokens_hash: Optional[int] = None,
            parent_hash: Optional[int] = None) -> Optional[List[int]]:
        """DiskKvStore.put shape: durable on return; returns the evicted
        hashes ([] usually) or None when skipped (already resident)."""
        if self.contains(seq_hash):
            return None
        data = pack_block_bytes(values, tokens_hash, parent_hash)
        self.store.put_object(self._key(seq_hash), data)
        with self._lock:
            self._index[seq_hash] = len(data)
            self.stored_blocks_total += 1
        if self.tenancy is not None:
            # owner carried from the warmer tiers (ledger memory)
            self.tenancy.note(seq_hash, None, "remote")
        return self._reap_for_capacity()

    def _reap_for_capacity(self) -> List[int]:
        if self.capacity <= 0 or len(self._index) <= self.capacity:
            return []
        aged = sorted(((mtime, key) for key, _sz, mtime
                       in self.store.list_objects(self._PREFIX)))
        if self.tenancy is not None:
            # quota preference (llm/tenancy.py): an over-quota tenant's
            # objects reap before anyone else's, age order within each
            # class — its eviction storm consumes its own residency
            over = [e for e in aged if self.tenancy.is_over_quota_hash(
                self._hash_of_key(e[1]), "remote")]
            if over:
                self.tenant_evictions += len(over)
                aged = over + [e for e in aged if e not in over]
        evicted: List[int] = []
        with self._lock:
            excess = len(self._index) - self.capacity
        for _mtime, key in aged:
            if excess <= 0:
                break
            h = self._hash_of_key(key)
            if h is None or self._pins.get(h):
                continue
            self.store.delete_object(key)
            with self._lock:
                self._index.pop(h, None)
            if self.tenancy is not None:
                self.tenancy.forget(h, "remote")
            self.evicted_blocks_total += 1
            evicted.append(h)
            excess -= 1
        return evicted

    def delete(self, seq_hash: int) -> None:
        self.store.delete_object(self._key(seq_hash))
        with self._lock:
            self._index.pop(seq_hash, None)

    def clear(self) -> int:
        with self._lock:
            hashes = list(self._index)
        for h in hashes:
            self.delete(h)
        return len(hashes)

    # ---------------------------------------------------------------- pins
    def pin(self, seq_hashes: Sequence[int]) -> None:
        with self._lock:
            for h in seq_hashes:
                self._pins[h] = self._pins.get(h, 0) + 1

    def unpin(self, seq_hashes: Sequence[int]) -> None:
        with self._lock:
            for h in seq_hashes:
                n = self._pins.get(h, 0) - 1
                if n <= 0:
                    self._pins.pop(h, None)
                else:
                    self._pins[h] = n


# ---------------------------------------------------------------------------
# The remote tier (DiskKvStore contract over both backends)
# ---------------------------------------------------------------------------


class RemoteKvStore:
    """The G4 rung behind the KvBlockManager cascade.

    Residency is the union of (a) the shared object store and (b) the
    hash→holder peer index, fed by the same tier-tagged ``kv_events``
    the router's radix index consumes (fabric.KvFabric subscribes and
    calls :meth:`note_peer_stored`/:meth:`note_peer_removed`). Reads
    prefer the object store (no peer round-trip, durable) and fall back
    to a ``peer_fetch`` callable (fabric RPC). ``match_prefix`` runs the
    latency-aware admission gate: no admission, no hit — the engine
    recomputes instead of waiting on a link that loses to prefill.

    Thread-safety mirrors the disk store: the promotion pump writes from
    a worker thread while the engine loop matches/pins; peer fetches run
    on the admission's off-thread onboard path."""

    def __init__(self, object_backend: Optional[ObjectKvBackend] = None):
        self.object = object_backend
        # fabric plugs these in at attach:
        #   peer_fetch(worker_id, [hashes]) -> {key: [L, H, n, bs, D]}
        self.peer_fetch: Optional[Callable] = None
        #   admission(n_blocks, holders) -> bool  (fabric.AdmissionGate)
        self.admission: Optional[Callable] = None
        #   peer_usable(worker_id) -> bool (fabric circuit breaker): a
        #   tripped peer's holdings stop counting as reachable — its
        #   matched runs fall through to recompute instead of waiting
        #   out a browning-out link (docs/chaos.md)
        self.peer_usable: Optional[Callable] = None
        self._lock = threading.RLock()
        # hash → {worker_id: announce monotonic time} (insertion-ordered;
        # first holder is the fetch's first choice)
        self._peers: Dict[int, Dict[int, float]] = {}
        self._pins: Dict[int, int] = {}
        # stats (nv_llm_kv_remote_* feed)
        self.match_queries = 0
        self.match_hits = 0
        self.admission_rejects_total = 0
        self.fetched_blocks_total = 0
        self.fetch_failures_total = 0
        self.peer_fetched_blocks_total = 0

    # ---------------------------------------------------------- tenancy
    @property
    def tenancy(self):
        """Per-tenant quota ledger (llm/tenancy.py) — lives on the
        object backend, where capacity reaping happens."""
        return self.object.tenancy if self.object is not None else None

    @tenancy.setter
    def tenancy(self, ledger) -> None:
        if self.object is not None:
            self.object.tenancy = ledger

    # ---------------------------------------------------------- index feed
    def note_peer_stored(self, worker_id: int,
                         seq_hashes: Sequence[int]) -> None:
        now = time.monotonic()
        with self._lock:
            for h in seq_hashes:
                self._peers.setdefault(h, {})[worker_id] = now

    def note_peer_removed(self, worker_id: int,
                          seq_hashes: Sequence[int]) -> None:
        with self._lock:
            for h in seq_hashes:
                holders = self._peers.get(h)
                if holders is not None:
                    holders.pop(worker_id, None)
                    if not holders:
                        del self._peers[h]

    def forget_peer(self, worker_id: int) -> None:
        """Peer's lease died: its holdings are unreachable. (A graceful
        restart re-announces and repopulates — the warm-start path.)"""
        with self._lock:
            for h in list(self._peers):
                self._peers[h].pop(worker_id, None)
                if not self._peers[h]:
                    del self._peers[h]

    def peer_block_count(self) -> int:
        with self._lock:
            return len(self._peers)

    # ------------------------------------------------------------- queries
    def holders_of(self, seq_hash: int) -> List[int]:
        with self._lock:
            holders = list(self._peers.get(seq_hash, ()))
        if self.peer_usable is not None:
            holders = [w for w in holders if self.peer_usable(w)]
        return holders

    def holds_durable(self, seq_hash: int) -> bool:
        """True when OUR durable (object) backend holds the hash — the
        announce-worthy residency; peer-held blocks are the peer's to
        announce."""
        return self.object is not None and self.object.contains(seq_hash)

    def contains(self, seq_hash: int) -> bool:
        with self._lock:
            if self._peers.get(seq_hash):
                return True
        return self.object is not None and self.object.contains(seq_hash)

    @property
    def used_blocks(self) -> int:
        return (self.object.used_blocks if self.object is not None else 0)

    @property
    def capacity(self) -> int:
        return self.object.capacity if self.object is not None else 0

    @property
    def bytes_used(self) -> int:
        return self.object.bytes_used if self.object is not None else 0

    @property
    def stored_blocks_total(self) -> int:
        return (self.object.stored_blocks_total
                if self.object is not None else 0)

    @property
    def evicted_blocks_total(self) -> int:
        return (self.object.evicted_blocks_total
                if self.object is not None else 0)

    def hit_rate(self) -> float:
        return self.match_hits / max(self.match_queries, 1)

    def registered_entries(self) -> List[tuple]:
        """Durable (object-held) blocks only — what THIS worker may
        re-announce at bring-up (tier="remote")."""
        if self.object is None:
            return []
        return self.object.registered_entries()

    def match_prefix(self, seq_hashes: Sequence[int],
                     pin: bool = False) -> List[int]:
        """Longest leading run of reachable hashes, gated by the fabric's
        latency-aware admission model: when the modeled fetch of the run
        loses to the modeled recompute, the WHOLE run reports as a miss
        (a slow remote hit is not a hit). ``pin`` protects matched
        object entries from the capacity reaper until the admission's
        off-thread read completes; peer-held entries cannot be pinned
        across the wire — a peer eviction mid-fetch surfaces as a fetch
        failure and the engine falls back to recompute."""
        run: List[int] = []
        holders: List[List[int]] = []
        for h in seq_hashes:
            self.match_queries += 1
            hs = self.holders_of(h)
            if not hs and not (self.object is not None
                               and self.object.contains(h)):
                break
            self.match_hits += 1
            run.append(h)
            holders.append(hs)
        if not run:
            return []
        if self.admission is not None and not self.admission(len(run),
                                                             holders):
            self.admission_rejects_total += 1
            # the walked hashes were reachable — the gate, not absence,
            # refused them; undo their hit accounting so hit_rate stays
            # the serving truth
            self.match_hits -= len(run)
            return []
        if pin:
            self.pin(run)
        return run

    # ---------------------------------------------------------------- pins
    def pin(self, seq_hashes: Sequence[int]) -> None:
        with self._lock:
            for h in seq_hashes:
                self._pins[h] = self._pins.get(h, 0) + 1
        if self.object is not None:
            self.object.pin(seq_hashes)

    def unpin(self, seq_hashes: Sequence[int]) -> None:
        with self._lock:
            for h in seq_hashes:
                n = self._pins.get(h, 0) - 1
                if n <= 0:
                    self._pins.pop(h, None)
                else:
                    self._pins[h] = n
        if self.object is not None:
            self.object.unpin(seq_hashes)

    # ---------------------------------------------------------------- reads
    def fetch(self, seq_hashes: Sequence[int],
              trace_ctx: Optional[dict] = None) -> dict:
        """Stacked wire values ({key: [L, H, n, bs, D]}) like the disk
        tier's fetch. Runs on the off-thread onboard path. Raises
        KeyError when any block is unreachable (peer gone, object torn)
        — the engine's graceful-fallback signal, never a crash.

        ``trace_ctx`` is the requesting request's propagation record
        (runtime/tracing.py TraceContext dict): peer RPCs forward it so
        the serving peer's fetch appears as a child span in the one
        fleet trace tree."""
        try:
            blocks = self._fetch_blocks(seq_hashes, trace_ctx)
        except Exception:
            self.fetch_failures_total += 1
            raise
        self.fetched_blocks_total += len(blocks)
        return {k: np.ascontiguousarray(
                    np.stack([b[k] for b in blocks], axis=2))
                for k in blocks[0]}

    def _fetch_blocks(self, seq_hashes: Sequence[int],
                      trace_ctx: Optional[dict] = None) -> List[dict]:
        # contiguous segmentation: object-held blocks read locally, the
        # rest grouped into per-peer runs so one RPC serves each run
        out: List[Optional[dict]] = [None] * len(seq_hashes)
        peer_runs: Dict[int, List[int]] = {}
        for i, h in enumerate(seq_hashes):
            if self.object is not None and self.object.contains(h):
                out[i] = self.object.fetch_blocks([h])[0]
            else:
                holders = self.holders_of(h)
                if not holders or self.peer_fetch is None:
                    raise KeyError(f"remote KV block {h:#x} has no "
                                   f"reachable holder")
                peer_runs.setdefault(holders[0], []).append(i)
        for wid, idxs in peer_runs.items():
            hashes = [seq_hashes[i] for i in idxs]
            stacked = self.peer_fetch(wid, hashes, trace_ctx)
            for j, i in enumerate(idxs):
                out[i] = {k: np.ascontiguousarray(v[:, :, j])
                          for k, v in stacked.items()}
            self.peer_fetched_blocks_total += len(idxs)
        return [b for b in out]  # type: ignore[misc]

    # --------------------------------------------------------------- writes
    def put(self, seq_hash: int, values: dict,
            tokens_hash: Optional[int] = None,
            parent_hash: Optional[int] = None) -> Optional[List[int]]:
        """Durable object put (the promotion pump's sink). Peer-only
        fabrics (no object backend) store nothing — the pump's offer is
        refused upstream via contains()."""
        if self.object is None:
            return None
        return self.object.put(seq_hash, values, tokens_hash, parent_hash)

    def apply_put(self, seq_hash: int, evicted: Sequence[int],
                  values: dict, tokens_hash: Optional[int] = None,
                  parent_hash: Optional[int] = None) -> None:
        """Literal-placement mirror (the DiskKvStore.apply_put contract):
        delete exactly the given eviction set, then store."""
        if self.object is None:
            return
        for h in evicted:
            self.object.delete(h)
        if not self.object.contains(seq_hash):
            self.object.put(seq_hash, values, tokens_hash, parent_hash)

    def clear(self) -> int:
        return self.object.clear() if self.object is not None else 0

    def close(self) -> None:
        pass                              # nothing held open
