"""Fleet KV fabric: peer-to-peer block transfer + latency-aware admission.

The transport half of the G4 remote tier (llm/kv/remotestore.py): every
worker registers a ``kv_fabric`` RPC endpoint next to its serving
endpoint — discovered through the kvstore like any component — that
serves its OWN disk/host-resident KV blocks to the fleet. A worker whose
admission cascade bottoms out locally fetches the prefix from whichever
peer announced it (the same tier-tagged ``kv_events`` the router
consumes feed the hash→holder index), onboards it through the existing
off-thread promote path, and decodes bit-exact vs local recompute —
prefix KV produced anywhere in the fleet is reusable everywhere
(FlowKV, arXiv:2504.03775, low-latency disaggregated KV transfer).

What makes it production-shaped rather than a dumb cache:

- :class:`PeerLinkTable` — measured link-cost tables: each peer is
  probed at attach (RTT + bandwidth) and every real transfer updates a
  decay-averaged estimate, so the model tracks the link the fleet
  actually has, not a config constant (tools/bandwidth_model.py holds
  the analytic anchors this extends).
- :class:`AdmissionGate` — promote a remote hit only when the modeled
  fetch time (RTT + bytes/bandwidth) beats the modeled recompute time
  (prefix depth / measured prefill rate). A remote hit slower than
  re-prefilling is reported as a miss and the engine recomputes.
- NetKV-style router scoring (kv_router/scoring.py, arXiv:2606.03910)
  consumes the same link model via ForwardPassMetrics ``remote_link_*``:
  decode-instance selection subtracts modeled transfer cost from
  tier-discounted overlap instead of chasing overlap depth alone.

Wire format: blocks travel as the self-describing npz bytes of
remotestore.pack_block_bytes over the NATIVE data plane — the request
plane carries only a small ``fetch_native`` control message naming the
hashes and a dial-back address; the serving peer then streams each
block as one length-prefixed two-part frame (csrc/data_plane.cpp via
runtime/tcp.open_stream_sender: framing + socket writes on a dedicated
C++ thread, falling through to the pure-asyncio sender with identical
frames when the toolchain is missing) and the fetching side unpacks the
raw frame bytes off its event loop. When the native library is absent
on the serving peer it declines and the fetch gracefully falls back to
the legacy base64-over-JSON ``fetch`` op (counted in
``dataplane_fallbacks_total``) — the block payload is byte-identical on
both paths by construction (tests/test_kv_fabric.py differential).
"""

from __future__ import annotations

import asyncio
import dataclasses
import json
import logging
import os
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from ...runtime.codec import ConnectionInfo, FrameKind
from ...runtime.engine import AsyncEngine, Context, ManyOut, ResponseStream
from .remotestore import (RemoteKvStore, pack_block_bytes,
                          unpack_block_bytes)

logger = logging.getLogger("dynamo_tpu.kv.fabric")

__all__ = ["FABRIC_ENDPOINT", "LinkStats", "PeerLinkTable", "AdmissionGate",
           "PrefillRateEstimator", "KvFabricServer", "KvFabric",
           "CircuitBreaker", "dataplane_serving_available"]

FABRIC_ENDPOINT = "kv_fabric"
PROBE_BYTES = 256 * 1024
# ops/test lever: DYN_KV_FABRIC_DATAPLANE=0 forces the JSON fallback on
# both sides (the differential test drives each path deliberately)
DATAPLANE_ENV = "DYN_KV_FABRIC_DATAPLANE"


def dataplane_serving_available() -> bool:
    """Whether THIS process can serve native-dataplane fetches: the env
    gate is on and the C++ data plane (csrc/data_plane.cpp) loads. A
    peer where either fails declines ``fetch_native`` and the fetching
    side falls back to the JSON path — never an error."""
    if os.environ.get(DATAPLANE_ENV, "1") == "0":
        return False
    from ...runtime.native_tcp import load_data_plane_lib
    return load_data_plane_lib() is not None


# ---------------------------------------------------------------------------
# Link-cost model
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class LinkStats:
    """Decay-averaged link estimate for one peer (or the object store)."""

    rtt_s: float = 1e-3
    gbps: float = 1.0
    samples: int = 0

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


class CircuitBreaker:
    """Per-peer circuit breaker: consecutive-failure / latency-SLO trip
    → open (the peer earns NO fetch traffic, NO admission-gate credit)
    → half-open after ``cooldown_s`` (exactly ONE trial fetch allowed)
    → closed on trial success, re-opened on trial failure.

    Why latency trips too: a browning-out peer — alive enough to answer
    probes, slow enough to lose to recompute — never produces a hard
    failure, yet every fetch routed to it burns the caller's TTFT. When
    ``latency_slo_s`` is set, ``failure_threshold`` consecutive
    transfers slower than the SLO trip the breaker exactly like errors.

    ``now`` is injectable (tests, the virtual-clock sim) — the breaker
    never reads a clock the caller didn't choose."""

    def __init__(self, failure_threshold: int = 3,
                 cooldown_s: float = 30.0,
                 latency_slo_s: Optional[float] = None,
                 now=time.monotonic):
        self.failure_threshold = int(failure_threshold)
        self.cooldown_s = float(cooldown_s)
        self.latency_slo_s = latency_slo_s
        self._now = now
        self.state = "closed"             # closed | open | half_open
        self.consecutive_failures = 0
        self.slow_streak = 0
        self.trips_total = 0
        self._opened_at = 0.0
        self._trial_inflight = False

    def _trip(self) -> None:
        if self.state != "open":
            self.trips_total += 1
        self.state = "open"
        self._opened_at = self._now()
        self._trial_inflight = False

    def _refresh(self) -> None:
        if (self.state == "open"
                and self._now() - self._opened_at >= self.cooldown_s):
            self.state = "half_open"      # cooldown elapsed: probe-able
            self._trial_inflight = False

    def would_allow(self) -> bool:
        """Pure check (pricing/holder filtering): could a fetch be
        routed here right now? Never consumes the half-open trial slot."""
        self._refresh()
        if self.state == "closed":
            return True
        if self.state == "open":
            return False
        return not self._trial_inflight   # half-open: one trial at a time

    def allow(self) -> bool:
        """Consuming check (the fetch path): like :meth:`would_allow`,
        but a half-open True CLAIMS the single trial slot — released by
        record_success/record_failure."""
        if not self.would_allow():
            return False
        if self.state == "half_open":
            self._trial_inflight = True
        return True

    def record_success(self, latency_s: Optional[float] = None) -> None:
        self._trial_inflight = False
        self.consecutive_failures = 0
        if (self.latency_slo_s is not None and latency_s is not None
                and latency_s > self.latency_slo_s):
            # "success" slower than the SLO is a brownout datapoint, not
            # a recovery — streaks of them trip exactly like failures
            self.slow_streak += 1
            if self.state == "half_open":
                self._trip()              # trial was too slow: back off
            elif self.slow_streak >= self.failure_threshold:
                self._trip()
            return
        self.slow_streak = 0
        if self.state in ("half_open", "open"):
            self.state = "closed"         # half-open trial passed
        # closed stays closed — success never flaps state (hysteresis)

    def record_failure(self) -> None:
        self._trial_inflight = False
        self.consecutive_failures += 1
        if self.state == "half_open":
            self._trip()                  # trial failed: full cooldown again
        elif self.consecutive_failures >= self.failure_threshold:
            self._trip()

    def describe(self) -> dict:
        return {"state": self.state,
                "consecutive_failures": self.consecutive_failures,
                "slow_streak": self.slow_streak,
                "trips_total": self.trips_total}


class PeerLinkTable:
    """Measured per-peer link costs. Probed once at attach, then every
    real transfer folds into an exponential moving average (alpha 0.3:
    responsive to a changed path, stable against one slow batch).

    Every peer also carries a :class:`CircuitBreaker`: tripped peers are
    skipped by ``link_for_holders`` (their holdings price as a dead link
    → the admission gate rejects → the engine recomputes), which is how
    a browning-out peer loses NetKV routing credit without any central
    coordination."""

    ALPHA = 0.3

    def __init__(self, default_gbps: float = 1.0,
                 default_rtt_s: float = 1e-3,
                 breaker_failure_threshold: int = 3,
                 breaker_cooldown_s: float = 30.0,
                 breaker_latency_slo_s: Optional[float] = None,
                 now=time.monotonic):
        self.default = LinkStats(rtt_s=default_rtt_s, gbps=default_gbps)
        self._links: Dict[int, LinkStats] = {}
        self._now = now
        self._breaker_kw = dict(
            failure_threshold=breaker_failure_threshold,
            cooldown_s=breaker_cooldown_s,
            latency_slo_s=breaker_latency_slo_s)
        self._breakers: Dict[int, CircuitBreaker] = {}
        # a LinkStats with no bandwidth: what a fully-tripped holder set
        # prices as (modeled fetch = inf → the gate always rejects)
        self._dead = LinkStats(rtt_s=float("inf"), gbps=0.0)

    def get(self, worker_id: Optional[int]) -> LinkStats:
        if worker_id is None:
            return self.default
        return self._links.get(worker_id, self.default)

    def _entry(self, worker_id: int) -> LinkStats:
        link = self._links.get(worker_id)
        if link is None:
            link = LinkStats(rtt_s=self.default.rtt_s,
                             gbps=self.default.gbps)
            self._links[worker_id] = link
        return link

    def observe_rtt(self, worker_id: int, rtt_s: float) -> None:
        link = self._entry(worker_id)
        if link.samples == 0:
            link.rtt_s = rtt_s
        else:
            link.rtt_s += self.ALPHA * (rtt_s - link.rtt_s)
        link.samples += 1

    def observe_transfer(self, worker_id: int, nbytes: int,
                         seconds: float) -> None:
        if seconds <= 0 or nbytes <= 0:
            return
        link = self._entry(worker_id)
        gbps = nbytes / seconds / 1e9
        if link.samples == 0:
            link.gbps = gbps
        else:
            link.gbps += self.ALPHA * (gbps - link.gbps)
        link.samples += 1

    def drop(self, worker_id: int) -> None:
        self._links.pop(worker_id, None)
        self._breakers.pop(worker_id, None)

    # ------------------------------------------------------ circuit breaker
    def breaker(self, worker_id: int) -> CircuitBreaker:
        b = self._breakers.get(worker_id)
        if b is None:
            b = CircuitBreaker(now=self._now, **self._breaker_kw)
            self._breakers[worker_id] = b
        return b

    def usable(self, worker_id: int) -> bool:
        """False while the peer's breaker is open (and not yet due for a
        half-open trial) — the RemoteKvStore.peer_usable plug. Pure:
        never claims the half-open trial slot (the fetch path does)."""
        return self.breaker(worker_id).would_allow()

    def record_success(self, worker_id: int,
                       latency_s: Optional[float] = None) -> None:
        self.breaker(worker_id).record_success(latency_s)

    def record_failure(self, worker_id: int) -> None:
        self.breaker(worker_id).record_failure()

    def open_breaker_count(self) -> int:
        return sum(1 for b in self._breakers.values()
                   if b.state != "closed")

    def breaker_trips_total(self) -> int:
        return sum(b.trips_total for b in self._breakers.values())

    def breaker_snapshot(self) -> Dict[int, dict]:
        return {wid: b.describe() for wid, b in self._breakers.items()}

    def link_for_holders(self, holders: Sequence[Sequence[int]]) -> LinkStats:
        """The link the fetch of a matched run would ride: the first
        UNTRIPPED peer holder's measured link, the object-store default
        when every block is object-held, or a dead link (gbps=0 →
        modeled fetch inf → the gate rejects) when every holder's
        breaker is open — a browning-out peer's blocks price like a
        miss, so the engine recomputes instead of waiting it out."""
        any_peer = False
        for hs in holders:
            for wid in hs:
                any_peer = True
                b = self._breakers.get(wid)
                if b is None or b.would_allow():
                    return self.get(wid)
        return self._dead if any_peer else self.default

    def avg_gbps(self) -> float:
        if not self._links:
            return self.default.gbps
        return sum(l.gbps for l in self._links.values()) / len(self._links)

    def avg_rtt_s(self) -> float:
        if not self._links:
            return self.default.rtt_s
        return sum(l.rtt_s for l in self._links.values()) / len(self._links)

    def snapshot(self) -> Dict[int, dict]:
        return {wid: l.to_dict() for wid, l in self._links.items()}


# ---------------------------------------------------------------------------
# Latency-aware admission
# ---------------------------------------------------------------------------


class PrefillRateEstimator:
    """Age-weighted measured prefill rate (ROADMAP KV-fabric item (c)):
    the admission gate's recompute side.

    A cumulative tokens/wall ratio is the wrong estimator on a YOUNG
    engine: the first prefill admissions include XLA compilation, so
    their rate is 10-100x below steady state and a cumulative mean stays
    skewed for thousands of admissions — making modeled recompute look
    expensive and over-admitting remote fetches that lose to a warmed-up
    recompute. This estimator

    - EXCLUDES the first ``warmup_samples`` admissions outright (while
      young it reports 0.0 — "rate unknown", which the gate and the
      router's NetKV model already treat as admit-optimistically, the
      correct posture for a cold engine), and
    - decay-averages per-admission rates afterwards (EMA, same alpha
      discipline as PeerLinkTable), so one anomalous admission — a GC
      pause, a host stall — washes out instead of anchoring the price.
    """

    def __init__(self, warmup_samples: int = 2, alpha: float = 0.3):
        self.warmup_samples = int(warmup_samples)
        self.alpha = float(alpha)
        self.samples = 0
        self.warmup_skipped = 0
        self._rate = 0.0

    def observe(self, tokens: int, wall_s: float) -> None:
        if tokens <= 0 or wall_s <= 0:
            return
        self.samples += 1
        if self.samples <= self.warmup_samples:
            self.warmup_skipped += 1
            return
        r = tokens / wall_s
        if self._rate <= 0:
            self._rate = r
        else:
            self._rate += self.alpha * (r - self._rate)

    def rate(self) -> float:
        """tok/s estimate; 0.0 until warmup passes (unknown → the gate
        admits, matching the tiers' optimistic cold behavior)."""
        return self._rate


class AdmissionGate:
    """Promote a remote hit only when the modeled fetch beats the modeled
    recompute at that depth.

    - fetch(n)     = rtt + n · bytes_per_block / bandwidth
    - recompute(n) = n · block_size / prefill_tok_per_s

    ``prefill_tok_per_s`` is a callable so the gate tracks the engine's
    MEASURED prefill rate (EngineCore.measured_prefill_tok_per_s), not a
    spec-sheet constant; before the first prefill lands (rate unknown)
    the gate admits — the tiers below make the same optimistic choice.
    ``mode``: "auto" (the model), "always" / "never" (ops overrides,
    also the test escape hatch)."""

    def __init__(self, bytes_per_block: int, block_size: int,
                 prefill_tok_per_s, mode: str = "auto"):
        if mode not in ("auto", "always", "never"):
            raise ValueError(f"unknown admission mode {mode!r}")
        self.bytes_per_block = int(bytes_per_block)
        self.block_size = int(block_size)
        self._prefill_rate = prefill_tok_per_s
        self.mode = mode
        self.accepts_total = 0
        self.rejects_total = 0

    def prefill_tok_per_s(self) -> float:
        rate = self._prefill_rate
        return float(rate() if callable(rate) else rate)

    def modeled_fetch_s(self, n_blocks: int, link: LinkStats) -> float:
        if link.gbps <= 0:
            return float("inf")
        return link.rtt_s + n_blocks * self.bytes_per_block / (link.gbps
                                                               * 1e9)

    def modeled_fetch_overlap_s(self, n_blocks: int, link: LinkStats,
                                n_layers: int,
                                hidden_compute_s: float = 0.0) -> float:
        """Overlap-aware fetch model (llm/kv/stream.py): when the bytes
        arrive as a per-layer stream the consumer scatters layer l while
        layer l+1 is on the wire, so only max(serial/L, serial − hidden)
        of the transfer is EXPOSED on the critical path. n_layers ≤ 1
        (monolithic payload) degrades to modeled_fetch_s exactly."""
        if link.gbps <= 0:
            return float("inf")
        from .stream import exposed_transfer_s
        serial = n_blocks * self.bytes_per_block / (link.gbps * 1e9)
        return link.rtt_s + exposed_transfer_s(serial, n_layers,
                                               hidden_compute_s)

    def modeled_recompute_s(self, n_blocks: int) -> float:
        rate = self.prefill_tok_per_s()
        if rate <= 0:
            return float("inf")          # unknown rate: admit (see class doc)
        return n_blocks * self.block_size / rate

    def admit(self, n_blocks: int, link: LinkStats) -> bool:
        if self.mode == "always":
            self.accepts_total += 1
            return True
        if self.mode == "never":
            self.rejects_total += 1
            return False
        ok = (self.modeled_fetch_s(n_blocks, link)
              < self.modeled_recompute_s(n_blocks))
        if ok:
            self.accepts_total += 1
        else:
            self.rejects_total += 1
        return ok

    def crossover_blocks(self, link: LinkStats) -> float:
        """Smallest hit depth (blocks) at which the fetch starts paying:
        rtt / (per-block recompute − per-block transfer). inf when the
        link's per-block cost never beats recompute."""
        rate = self.prefill_tok_per_s()
        if rate <= 0:
            return 0.0                   # unknown rate: everything admits
        if link.gbps <= 0:
            return float("inf")
        per_block_gain = (self.block_size / rate
                          - self.bytes_per_block / (link.gbps * 1e9))
        if per_block_gain <= 0:
            return float("inf")
        return link.rtt_s / per_block_gain

    def crossover_blocks_overlap(self, link: LinkStats,
                                 n_layers: int) -> float:
        """crossover_blocks under the streaming bound: with L layers
        pipelined, the exposed per-block transfer is 1/L of the serial
        cost (the other L−1 frames hide under the consumer's scatter),
        so the fetch starts paying at a SHALLOWER depth. n_layers ≤ 1
        degrades to crossover_blocks exactly."""
        rate = self.prefill_tok_per_s()
        if rate <= 0:
            return 0.0                   # unknown rate: everything admits
        if link.gbps <= 0:
            return float("inf")
        layers = max(int(n_layers), 1)
        per_block_gain = (self.block_size / rate
                          - self.bytes_per_block / (link.gbps * 1e9)
                          / layers)
        if per_block_gain <= 0:
            return float("inf")
        return link.rtt_s / per_block_gain


# ---------------------------------------------------------------------------
# RPC plane: per-worker kv_fabric endpoint
# ---------------------------------------------------------------------------


class KvFabricServer(AsyncEngine):
    """Serves THIS worker's disk/host-resident blocks to the fleet.

    Ops (request = one JSON dict, response = one JSON dict):
    - ``probe``: echo ``nbytes`` of payload — the client times the round
      trip to measure RTT (nbytes=0) and bandwidth (nbytes large).
    - ``match``: which of ``hashes`` this worker can serve.
    - ``fetch_native``: the DEFAULT block transport — the request names
      the hashes plus the caller's dial-back ``conn`` (its process
      stream server, runtime/tcp.TcpStreamServer); the blocks stream
      back as raw length-prefixed two-part frames on the native data
      plane (csrc/data_plane.cpp), one DATA frame per block with the
      hash in the JSON header and the npz bytes as the data part —
      no base64, no JSON in the bulk path. A peer without the native
      lib (or with DYN_KV_FABRIC_DATAPLANE=0) declines with
      ``fallback`` and the caller retries over ``fetch``.
    - ``fetch``: the JSON fallback — packed npz, base64-framed in the
      response dict. Byte-identical payloads to the native path.

    Missing hashes are reported, never fatal — the caller recomputes.
    File reads and frame unpacks run off-thread; the serving loop never
    blocks on I/O (the disk tier's loop-stall contract extended to
    serving peers)."""

    def __init__(self, core):
        self.core = core
        self.fetches_served = 0
        self.blocks_served = 0
        self.probes_served = 0
        self.dataplane_fetches_served = 0

    def _read_block(self, seq_hash: int) -> Optional[bytes]:
        """One packed block from the coldest-first local tiers (runs in a
        worker thread)."""
        disk = self.core.disk_store
        if disk is not None and disk.contains(seq_hash):
            disk.pin([seq_hash])
            try:
                stacked = disk.fetch([seq_hash])
            except KeyError:
                return None
            finally:
                disk.unpin([seq_hash])
            e = next((en for en in disk.registered_entries()
                      if en[0] == seq_hash), (seq_hash, None, None))
            values = {k: v[:, :, 0] for k, v in stacked.items()}
            return pack_block_bytes(values, e[1], e[2])
        host = self.core.kv_manager.host_pool
        if host is not None and host.contains(seq_hash):
            slot = host._by_hash.get(seq_hash)
            if slot is None:
                return None
            host.pin([slot])
            try:
                values = host.row_copy(slot)
            finally:
                host.unpin([slot])
            th, ph = host.meta_for(seq_hash)
            return pack_block_bytes(values, th, ph)
        return None

    def _serveable(self, seq_hash: int) -> bool:
        disk = self.core.disk_store
        host = self.core.kv_manager.host_pool
        return ((disk is not None and disk.contains(seq_hash))
                or (host is not None and host.contains(seq_hash)))

    def _read_all(self, hashes: Sequence[int]):
        """Packed bytes per hash (worker thread) → ({hash: bytes},
        [missing]). Shared by both transports — byte-identical payloads
        by construction."""
        blocks, missing = {}, []
        for h in hashes:
            data = self._read_block(h)
            if data is None:
                missing.append(h)
            else:
                blocks[h] = data
        return blocks, missing

    async def _stream_native(self, conn: dict, hashes: Sequence[int],
                             blocks: Dict[int, bytes]) -> bool:
        """Dial the caller back and stream one two-part frame per block
        over the native data plane (open_stream_sender picks the C++
        sender; identical frames from the asyncio sender otherwise).
        Returns False when the dial-back itself failed — the caller
        falls back to the JSON path; a mid-stream failure surfaces to
        the caller as a torn stream (→ recompute), never an error."""
        from ...runtime.faults import hit_async as _fault
        from ...runtime.faults import mangle as _mangle
        from ...runtime.tcp import open_stream_sender
        try:
            await _fault("fabric.dialback", exc=ConnectionError)
            sender = await open_stream_sender(
                ConnectionInfo.from_dict(conn), timeout=5.0)
        except Exception:  # noqa: BLE001 — caller's server unreachable
            logger.warning("fabric dataplane dial-back to %s failed",
                           conn.get("address"), exc_info=True)
            return False
        try:
            for h in hashes:
                # torn-frame chaos site: truncated npz bytes must surface
                # on the fetching side as a failed unpack → recompute
                await sender.send(_mangle("dataplane.frame", blocks[h]),
                                  header=json.dumps({"h": int(h)}).encode())
            await sender.finish()
        except Exception as e:  # noqa: BLE001 — torn stream: caller recomputes
            logger.warning("fabric dataplane stream failed mid-fetch: %s", e)
            try:
                await sender.finish(error=str(e))
            except Exception:  # noqa: BLE001
                pass
        return True

    async def _probe_stream(self, conn: dict, nbytes: int) -> bool:
        """Dial the prober back and stream ``nbytes`` of payload over
        the native data plane — the SAME path fetches ride, so the
        measured bandwidth prices the transfers that will actually
        happen (the request-plane echo measured the wrong path once
        dataplane fetch was the default). False = dial-back failed →
        the prober falls back to the request-plane echo."""
        from ...runtime.tcp import open_stream_sender
        try:
            sender = await open_stream_sender(
                ConnectionInfo.from_dict(conn), timeout=5.0)
        except Exception:  # noqa: BLE001 — prober's server unreachable
            logger.warning("fabric probe dial-back to %s failed",
                           conn.get("address"), exc_info=True)
            return False
        chunk = bytes(min(max(nbytes, 1), 1 << 18))
        sent = 0
        try:
            while sent < nbytes:
                part = chunk[:nbytes - sent] if nbytes - sent < len(chunk) \
                    else chunk
                await sender.send(part, header=b"{}")
                sent += len(part)
            await sender.finish()
        except Exception as e:  # noqa: BLE001 — torn probe: prober times out
            logger.warning("fabric probe stream failed: %s", e)
            try:
                await sender.finish(error=str(e))
            except Exception:  # noqa: BLE001
                pass
        return True

    async def _handle(self, d: dict) -> dict:
        import base64
        op = d.get("op")
        if op == "probe":
            self.probes_served += 1
            n = int(d.get("nbytes", 0))
            return {"ok": True, "payload": "0" * n}
        if op == "probe_native":
            # bandwidth probe over the native data plane (the path
            # fetches ride); decline → request-plane echo fallback
            self.probes_served += 1
            if not await asyncio.to_thread(dataplane_serving_available):
                return {"ok": True, "fallback": "json"}
            n = int(d.get("nbytes", 0))
            if not await self._probe_stream(d.get("conn") or {}, n):
                return {"ok": True, "fallback": "json"}
            return {"ok": True, "dataplane": True, "nbytes": n}
        if op == "match":
            hashes = [int(h) for h in d.get("hashes", [])]
            return {"ok": True,
                    "resident": [self._serveable(h) for h in hashes]}
        if op in ("fetch", "fetch_native"):
            hashes = [int(h) for h in d.get("hashes", [])]
            native = (op == "fetch_native")
            if native and not await asyncio.to_thread(
                    dataplane_serving_available):
                # lib absent / env-gated: decline, the caller rides JSON
                return {"ok": True, "fallback": "json"}

            # the requesting worker forwarded its request's TraceContext:
            # serve the fetch under a CHILD trace so the peer-side read
            # lands in the same fleet tree the collector assembles
            from ...runtime.tracing import Trace, use_trace
            tctx = d.get("trace")
            if tctx:
                with use_trace(Trace.from_wire(
                        tctx, tctx.get("trace_id", "?"),
                        role="kv_peer")) as ptrace:
                    with ptrace.span("fabric.fetch", blocks=len(hashes),
                                     dataplane=native):
                        blocks, missing = await asyncio.to_thread(
                            self._read_all, hashes)
                    if missing:
                        ptrace.event("fabric.missing", n=len(missing))
            else:
                blocks, missing = await asyncio.to_thread(
                    self._read_all, hashes)
            if missing:
                # caller recomputes; nothing streams (native included)
                return {"ok": True, "blocks": {}, "missing": missing}
            if native:
                if not await self._stream_native(d.get("conn") or {},
                                                 hashes, blocks):
                    return {"ok": True, "fallback": "json"}
                self.fetches_served += 1
                self.dataplane_fetches_served += 1
                self.blocks_served += len(blocks)
                return {"ok": True, "dataplane": True,
                        "blocks": len(blocks), "missing": []}
            self.fetches_served += 1
            self.blocks_served += len(blocks)
            # bulk base64 is CPU work — encode off the serving loop
            enc = await asyncio.to_thread(
                lambda: {str(h): base64.b64encode(b).decode()
                         for h, b in blocks.items()})
            return {"ok": True, "blocks": enc, "missing": []}
        return {"ok": False, "error": f"unknown fabric op {op!r}"}

    async def generate(self, request) -> ManyOut:
        resp = await self._handle(request.data)
        return ResponseStream.from_iterable([resp], request.ctx)

    def stats(self) -> dict:
        return {"fabric_fetches_served": self.fetches_served,
                "fabric_blocks_served": self.blocks_served}


# ---------------------------------------------------------------------------
# Orchestrator
# ---------------------------------------------------------------------------


class KvFabric:
    """One worker's view of the fleet KV fabric.

    ``attach`` wires the whole thing: serve our ``kv_fabric`` endpoint,
    start the peer client (discovery-watched like any component),
    subscribe the component's ``kv_events`` to feed the hash→holder
    index, probe every live peer for its link cost, and hand the engine
    a :class:`RemoteKvStore` that sits behind the existing
    KvBlockManager cascade."""

    FETCH_TIMEOUT_S = 60.0

    def __init__(self, store: RemoteKvStore, links: PeerLinkTable,
                 gate: AdmissionGate, worker_id: Optional[int] = None,
                 runtime=None):
        self.store = store
        self.links = links
        self.gate = gate
        self.worker_id = worker_id
        self.server: Optional[KvFabricServer] = None
        self.client = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._runtime = runtime       # dial-back stream server for fetches
        self._sub = None
        self._tasks: List[asyncio.Task] = []
        self._known_peers: set = set()
        self.peer_fetches_total = 0
        # native-dataplane fetch accounting (the nv_llm_kv_remote_
        # dataplane_* gauge feeds): fallbacks = fetches that had to ride
        # the JSON path because the peer declined (lib absent/env off)
        self.dataplane_fetches_total = 0
        self.dataplane_fallbacks_total = 0
        # probes that had to ride the request-plane echo because the
        # peer declined the native-dataplane probe (ROADMAP PaaS ext.)
        self.probe_fallbacks_total = 0
        self.use_dataplane = os.environ.get(DATAPLANE_ENV, "1") != "0"
        store.peer_fetch = self.fetch_sync
        store.admission = self._admit
        # circuit breaker (docs/chaos.md): tripped peers vanish from the
        # store's holder view, so their matched runs fall through to
        # recompute instead of waiting out a browning-out link
        store.peer_usable = links.usable

    # ------------------------------------------------------------ wiring
    @classmethod
    async def attach(cls, core, runtime, endpoint,
                     default_gbps: float = 1.0,
                     probe_peers: bool = True) -> "KvFabric":
        """Build + wire a fabric for ``core`` next to its serving
        ``endpoint`` (the fabric endpoint shares the component:
        ``dyn://{ns}/{comp}/kv_fabric``)."""
        component = runtime.namespace(endpoint.namespace).component(
            endpoint.component)
        fabric_ep = component.endpoint(FABRIC_ENDPOINT)

        store = core.remote_store
        if store is None:
            store = RemoteKvStore()       # peer-only fabric (no object dir)
        links = PeerLinkTable(default_gbps=default_gbps)
        gate = AdmissionGate(
            bytes_per_block=core.kv_bytes_per_block(),
            block_size=core.cfg.kv_block_size,
            prefill_tok_per_s=core.measured_prefill_tok_per_s,
            mode=core.cfg.kv_remote_admission)
        self = cls(store, links, gate, runtime=runtime)
        self._loop = asyncio.get_running_loop()

        # serve our blocks to the fleet
        self.server = KvFabricServer(core)
        await fabric_ep.serve(self.server,
                              decode_req=lambda raw: json.loads(raw))
        lease = await runtime.primary_lease()
        self.worker_id = lease.id

        # peer client over the same endpoint's discovery prefix
        self.client = fabric_ep.client()
        self.client.on_instances_changed = self._instances_changed
        await self.client.start()
        self._known_peers = {wid for wid in self.client.instance_ids()
                             if wid != self.worker_id}

        # hash→holder feed: the same tier-tagged kv_events the router eats
        self._sub = await component.subscribe_event("kv_events")
        self._tasks.append(asyncio.get_running_loop().create_task(
            self._event_loop(), name="kv-fabric-events"))

        core.attach_kv_fabric(self)
        if probe_peers:
            for wid in list(self._known_peers):
                try:
                    await self.probe(wid)
                except Exception:  # noqa: BLE001 — a dark peer is not fatal
                    logger.warning("fabric probe of peer %x failed", wid)
        logger.info("kv fabric attached: worker %s, %d live peer(s)",
                    f"{self.worker_id:x}" if self.worker_id else "?",
                    len(self._known_peers))
        return self

    def _instances_changed(self, present: set) -> None:
        present = {wid for wid in present if wid != self.worker_id}
        for gone in self._known_peers - present:
            self.store.forget_peer(gone)
            self.links.drop(gone)
        new = present - self._known_peers
        self._known_peers = present
        for wid in new:
            # probe the newcomer off the watch callback
            t = asyncio.get_running_loop().create_task(
                self._probe_safe(wid), name=f"kv-fabric-probe-{wid:x}")
            self._tasks.append(t)

    async def _probe_safe(self, wid: int) -> None:
        try:
            await self.probe(wid)
        except Exception:  # noqa: BLE001
            logger.warning("fabric probe of new peer %x failed", wid)

    async def _event_loop(self) -> None:
        from ..kv_router.protocols import RouterEvent
        async for msg in self._sub:
            try:
                ev = RouterEvent.from_dict(json.loads(msg.payload))
            except Exception:  # noqa: BLE001
                continue
            if ev.worker_id == self.worker_id or ev.worker_id < 0:
                continue
            if ev.stored is not None:
                # only tiers the peer's fabric server can actually serve
                if getattr(ev.stored, "tier", "device") in ("host", "disk"):
                    self.store.note_peer_stored(ev.worker_id,
                                                ev.stored.block_hashes)
            if ev.removed is not None:
                self.store.note_peer_removed(ev.worker_id,
                                             ev.removed.block_hashes)

    # -------------------------------------------------------------- probes
    RPC_TIMEOUT_S = 15.0

    async def _call(self, worker_id: int, payload: dict,
                    trace_ctx: Optional[dict] = None) -> dict:
        # explicit propagation (metadata override in runtime/egress.py):
        # this coroutine runs off the request's async chain, so the
        # request's trace identity arrives by value, not contextvar
        ctx = Context(payload,
                      metadata={"trace_context": trace_ctx}
                      if trace_ctx else None)

        async def call_once() -> dict:
            stream = await self.client.direct(ctx, worker_id)
            async for item in stream:
                if not item.get("ok"):
                    raise RuntimeError(item.get("error",
                                                "fabric call failed"))
                return item
            raise RuntimeError(
                "fabric peer closed the stream without a reply")

        # bounded: a partitioned peer must fail this worker's admission
        # in RPC_TIMEOUT_S, not hold the onboard path for the transport
        # stack's worst case (chaos contract: no unbounded fabric await)
        try:
            return await asyncio.wait_for(call_once(), self.RPC_TIMEOUT_S)
        except (asyncio.TimeoutError, TimeoutError):
            raise RuntimeError(
                f"fabric call to peer {worker_id:x} timed out after "
                f"{self.RPC_TIMEOUT_S:.0f}s (partitioned?)") from None

    async def _probe_native(self, worker_id: int,
                            nbytes: int) -> Optional[tuple]:
        """Bandwidth probe over the native data plane — the SAME path
        fetches ride (csrc/data_plane.cpp), so the measured gbps prices
        real transfers instead of the request-plane JSON hop. Returns
        (bytes_received, wall_s) or None when the peer declined (lib
        absent / env off) or we have no dial-back server — the caller
        falls back to the request-plane echo."""
        rt = self._runtime
        if rt is None or not self.use_dataplane:
            return None
        await rt.tcp.start()
        rx = rt.tcp.register()
        try:
            t0 = time.monotonic()
            r = await self._call(worker_id, {
                "op": "probe_native", "nbytes": int(nbytes),
                "conn": rt.tcp.connection_info(rx).to_dict()})
            if not r.get("dataplane"):
                return None               # peer declined → echo fallback
            got = 0
            loop = asyncio.get_running_loop()
            deadline = loop.time() + self.RPC_TIMEOUT_S
            while True:
                f = await rx.next_frame(
                    timeout=max(deadline - loop.time(), 0.001))
                if f is None or f.kind == FrameKind.ERROR:
                    raise RuntimeError(
                        f"dataplane probe of peer {worker_id:x} tore")
                if f.kind == FrameKind.SENTINEL:
                    break
                if f.kind == FrameKind.DATA:
                    got += len(f.data)
            return got, time.monotonic() - t0
        finally:
            rx.close()
            rt.tcp.unregister(rx.stream_id)

    async def probe(self, worker_id: int,
                    nbytes: int = PROBE_BYTES) -> LinkStats:
        """Measure the peer's link at attach: a zero-payload round trip
        for RTT, then a bulk transfer for bandwidth — over the NATIVE
        data plane by default (the path fetches actually ride; ROADMAP
        PaaS extension), falling back to the request-plane echo when
        either side lacks the native lib. Decay-averaged into the link
        table (later real transfers keep refining it)."""
        t0 = time.monotonic()
        await self._call(worker_id, {"op": "probe", "nbytes": 0})
        rtt = time.monotonic() - t0
        self.links.observe_rtt(worker_id, rtt)
        native = None
        try:
            native = await self._probe_native(worker_id, nbytes)
        except Exception:  # noqa: BLE001 — torn probe: echo still works
            logger.warning("native dataplane probe of peer %x failed; "
                           "falling back to request-plane echo",
                           worker_id, exc_info=True)
        if native is not None:
            got, dt = native
            # the control RPC's round trip rides inside dt — subtract
            # the measured rtt so the estimate reflects the stream
            self.links.observe_transfer(worker_id, got,
                                        max(dt - rtt, 1e-6))
            return self.links.get(worker_id)
        self.probe_fallbacks_total += 1
        t0 = time.monotonic()
        r = await self._call(worker_id, {"op": "probe", "nbytes": nbytes})
        dt = time.monotonic() - t0
        got = len(r.get("payload", ""))
        self.links.observe_transfer(worker_id, got, dt)
        return self.links.get(worker_id)

    # ------------------------------------------------------------- fetches
    async def _fetch_blobs_native(self, worker_id: int,
                                  seq_hashes: Sequence[int],
                                  trace_ctx: Optional[dict] = None
                                  ) -> Optional[List[bytes]]:
        """Native-dataplane fetch: register a dial-back stream on this
        process's TcpStreamServer, send the control RPC, drain one
        two-part frame per block. Returns the packed bytes in request
        order; None when the peer DECLINED (lib absent / env off — the
        caller falls back to JSON); KeyError on missing hashes or a
        torn/timed-out stream (the caller recomputes)."""
        rt = self._runtime
        if rt is None:
            return None
        await rt.tcp.start()
        rx = rt.tcp.register()
        try:
            payload = {"op": "fetch_native",
                       "hashes": [int(h) for h in seq_hashes],
                       "conn": rt.tcp.connection_info(rx).to_dict()}
            if trace_ctx:
                payload["trace"] = trace_ctx
            r = await self._call(worker_id, payload, trace_ctx=trace_ctx)
            if r.get("missing"):
                raise KeyError(f"peer {worker_id:x} no longer holds "
                               f"{len(r['missing'])} requested block(s)")
            if not r.get("dataplane"):
                return None               # peer declined → JSON fallback
            by_hash: Dict[int, bytes] = {}
            loop = asyncio.get_running_loop()
            deadline = loop.time() + self.FETCH_TIMEOUT_S
            while True:
                f = await rx.next_frame(
                    timeout=max(deadline - loop.time(), 0.001))
                if f is None:
                    raise KeyError(
                        f"dataplane fetch from peer {worker_id:x} timed "
                        f"out after {self.FETCH_TIMEOUT_S:.0f}s")
                if f.kind == FrameKind.DATA:
                    by_hash[int(f.header_json()["h"])] = f.data
                elif f.kind == FrameKind.SENTINEL:
                    break
                elif f.kind == FrameKind.ERROR:
                    raise KeyError(
                        f"dataplane fetch from peer {worker_id:x} tore "
                        f"mid-stream: "
                        f"{f.header_json().get('error', 'stream error')}")
            try:
                blobs = [by_hash[int(h)] for h in seq_hashes]
            except KeyError:
                raise KeyError(
                    f"dataplane fetch from peer {worker_id:x} ended "
                    f"with {len(by_hash)}/{len(seq_hashes)} block frames")
            self.dataplane_fetches_total += 1
            return blobs
        finally:
            rx.close()
            rt.tcp.unregister(rx.stream_id)

    async def _fetch_blobs_json(self, worker_id: int,
                                seq_hashes: Sequence[int],
                                trace_ctx: Optional[dict] = None
                                ) -> List[bytes]:
        """Legacy request-plane fetch (base64-framed JSON) — the
        graceful fallback when the peer lacks the native data plane."""
        import base64
        payload = {"op": "fetch",
                   "hashes": [int(h) for h in seq_hashes]}
        if trace_ctx:
            payload["trace"] = trace_ctx
        r = await self._call(worker_id, payload, trace_ctx=trace_ctx)
        if r.get("missing"):
            raise KeyError(f"peer {worker_id:x} no longer holds "
                           f"{len(r['missing'])} requested block(s)")
        blocks = r["blocks"]
        return await asyncio.to_thread(
            lambda: [base64.b64decode(blocks[str(int(h))])
                     for h in seq_hashes])

    async def fetch_async(self, worker_id: int, seq_hashes: Sequence[int],
                          trace_ctx: Optional[dict] = None) -> dict:
        """One peer fetch for a run of blocks → stacked wire values
        ({key: [L, H, n, bs, D]}). Block bytes ride the native data
        plane by default (length-prefixed binary frames, zero-copy
        unpack off the loop); a peer without the native lib serves the
        base64-over-JSON fallback with byte-identical payloads.
        KeyError when the peer cannot serve every requested hash
        (evicted since the announce) or the stream tears — the
        graceful-fallback-to-recompute signal. ``trace_ctx``
        (TraceContext dict) rides the RPC so the peer serves under a
        child trace.

        Every outcome feeds the peer's circuit breaker: failures and
        SLO-slow transfers trip it (the peer loses holder credit and
        admission eligibility until a half-open trial passes);
        successes close it."""
        from ...runtime.faults import hit_async as _fault
        t0 = time.monotonic()
        if not self.links.breaker(worker_id).allow():
            raise KeyError(f"peer {worker_id:x} circuit breaker is open")
        try:
            await _fault("fabric.fetch", exc=KeyError)
            blobs = None
            if self.use_dataplane:
                blobs = await self._fetch_blobs_native(
                    worker_id, seq_hashes, trace_ctx)
                if blobs is None:
                    self.dataplane_fallbacks_total += 1
            if blobs is None:
                blobs = await self._fetch_blobs_json(worker_id, seq_hashes,
                                                     trace_ctx)
        except Exception:
            self.links.record_failure(worker_id)
            raise

        def unpack_all():
            # npz decode + stack is bulk CPU work — decode keeps stepping
            # on this loop while the fetched run is unpacked off-thread
            blocks = [unpack_block_bytes(b)[0] for b in blobs]
            return {k: np.ascontiguousarray(
                        np.stack([b[k] for b in blocks], axis=2))
                    for k in blocks[0]}

        try:
            unpacked = await asyncio.to_thread(unpack_all)
        except Exception:
            # torn frames (truncated npz) are a peer-quality signal too
            self.links.record_failure(worker_id)
            raise
        elapsed = time.monotonic() - t0
        self.links.record_success(worker_id, elapsed)
        self.links.observe_transfer(worker_id, sum(len(b) for b in blobs),
                                    elapsed)
        self.peer_fetches_total += 1
        return unpacked

    def fetch_sync(self, worker_id: int, seq_hashes: Sequence[int],
                   trace_ctx: Optional[dict] = None) -> dict:
        """RemoteKvStore.peer_fetch plug: called from the admission's
        off-thread onboard prep, so blocking on the loop's RPC future is
        safe (and the loop keeps decoding throughout). ``trace_ctx`` is
        passed explicitly because contextvars don't cross the thread
        hop — the requesting request's trace identity travels by value."""
        if self._loop is None:
            raise KeyError("fabric not attached")
        fut = asyncio.run_coroutine_threadsafe(
            self.fetch_async(worker_id, seq_hashes, trace_ctx), self._loop)
        try:
            return fut.result(timeout=self.FETCH_TIMEOUT_S)
        except Exception as e:
            fut.cancel()
            if isinstance(e, KeyError):
                raise
            raise KeyError(f"fabric fetch from peer {worker_id:x} "
                           f"failed: {e}") from e

    def _admit(self, n_blocks: int,
               holders: Sequence[Sequence[int]]) -> bool:
        return self.gate.admit(n_blocks,
                               self.links.link_for_holders(holders))

    # -------------------------------------------------------------- stats
    def metrics(self) -> dict:
        """The nv_llm_kv_remote_* ForwardPassMetrics slice."""
        s = self.store
        return {
            "remote_used_blocks": s.used_blocks,
            "remote_capacity_blocks": s.capacity,
            "remote_peer_blocks": s.peer_block_count(),
            "remote_stored_total": s.stored_blocks_total,
            "remote_hit_rate": s.hit_rate(),
            "remote_fetch_failures_total": s.fetch_failures_total,
            "remote_admission_rejects_total": s.admission_rejects_total,
            "remote_link_gbps": self.links.avg_gbps(),
            "remote_link_rtt_s": self.links.avg_rtt_s(),
            "remote_dataplane_fetches_total": self.dataplane_fetches_total,
            "remote_dataplane_fallbacks_total":
                self.dataplane_fallbacks_total,
            # circuit breaker (the Grafana "Degradation" row): peers
            # currently tripped/half-open + cumulative trips
            "remote_breaker_open_peers": self.links.open_breaker_count(),
            "remote_breaker_trips_total":
                self.links.breaker_trips_total(),
        }

    async def close(self) -> None:
        if self._sub is not None:
            self._sub.close()
        for t in self._tasks:
            t.cancel()
        for t in self._tasks:
            try:
                await t
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
        if self.client is not None:
            await self.client.close()
