from .blocks import (TokenBlockSequence, chain_hash, compute_block_hashes,
                     hash_tokens)
from .pool import KvBlockManager, KvBlockPool, PrefillPlan

__all__ = ["TokenBlockSequence", "chain_hash", "compute_block_hashes",
           "hash_tokens", "KvBlockManager", "KvBlockPool", "PrefillPlan"]
