"""KV tier admin surface: `llmctl kv {status,flush}` over the runtime KV
store (the planner/spec admin pattern, llm/slo.py / engine/spec/admin.py).

Workers publish a :class:`KvTierStatus` snapshot under
``kvtier/status/{namespace}`` every few seconds and watch
``kvtier/control/{namespace}`` for flush commands; `llmctl kv status`
reads the snapshots, `llmctl kv flush` writes a control nonce that makes
every watching worker persist its host-resident blocks to the disk (G3)
tier NOW (EngineCore.flush_host_to_disk — the pre-restart barrier), or
with ``--clear`` drop the disk cache instead."""

from __future__ import annotations

import asyncio
import dataclasses
import json
import logging
import time
from typing import Optional

logger = logging.getLogger("dynamo_tpu.kv.admin")

KV_PREFIX = "kvtier/"


def kv_status_key(namespace: str) -> str:
    return f"{KV_PREFIX}status/{namespace}"


def kv_control_key(namespace: str) -> str:
    return f"{KV_PREFIX}control/{namespace}"


def kv_weights_key(namespace: str) -> str:
    """llmctl kv set-weights target: a JSON {tier: weight} partial map
    every watching worker/router applies live
    (kv_router/scoring.set_tier_weights)."""
    return f"{KV_PREFIX}weights/{namespace}"


@dataclasses.dataclass
class KvTierStatus:
    """One worker's KV-ladder snapshot (the llmctl kv status payload)."""

    namespace: str = ""
    host_blocks: int = 0
    host_capacity: int = 0
    host_hit_rate: float = 0.0
    disk_dir: str = ""
    disk_blocks: int = 0
    disk_capacity: int = 0
    disk_hit_rate: float = 0.0
    disk_bytes: int = 0
    spill_dropped: int = 0
    offload_dropped: int = 0
    disk_onboards: int = 0
    # remote (G4) fleet fabric (llm/kv/remotestore.py + fabric.py)
    remote_blocks: int = 0
    remote_capacity: int = 0
    remote_peer_blocks: int = 0
    remote_hit_rate: float = 0.0
    remote_onboards: int = 0
    remote_fetch_failures: int = 0
    remote_link_gbps: float = 0.0
    remote_link_rtt_s: float = 0.0
    updated_at: float = 0.0

    def to_json(self) -> bytes:
        return json.dumps(dataclasses.asdict(self)).encode()

    @classmethod
    def from_json(cls, raw: bytes) -> "KvTierStatus":
        d = json.loads(raw)
        fields = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in fields})


def snapshot(core, namespace: str) -> KvTierStatus:
    """Current tier state of one EngineCore."""
    host = core.kv_manager.host_pool
    disk = core.disk_store
    remote = getattr(core, "remote_store", None)
    fabric = getattr(core, "kv_fabric", None)
    return KvTierStatus(
        remote_blocks=remote.used_blocks if remote is not None else 0,
        remote_capacity=remote.capacity if remote is not None else 0,
        remote_peer_blocks=(remote.peer_block_count()
                            if remote is not None else 0),
        remote_hit_rate=remote.hit_rate() if remote is not None else 0.0,
        remote_onboards=getattr(core, "remote_onboards", 0),
        remote_fetch_failures=(remote.fetch_failures_total
                               if remote is not None else 0),
        remote_link_gbps=(fabric.links.avg_gbps()
                          if fabric is not None else 0.0),
        remote_link_rtt_s=(fabric.links.avg_rtt_s()
                           if fabric is not None else 0.0),
        namespace=namespace,
        host_blocks=len(host) if host is not None else 0,
        host_capacity=host.capacity if host is not None else 0,
        host_hit_rate=host.hit_rate() if host is not None else 0.0,
        disk_dir=disk.root if disk is not None else "",
        disk_blocks=disk.used_blocks if disk is not None else 0,
        disk_capacity=disk.capacity if disk is not None else 0,
        disk_hit_rate=disk.hit_rate() if disk is not None else 0.0,
        disk_bytes=disk.bytes_used if disk is not None else 0,
        spill_dropped=(core.spill_engine.dropped_jobs_total
                       if core.spill_engine is not None else 0),
        offload_dropped=(core.offload_engine.dropped_jobs_total
                         if core.offload_engine is not None else 0),
        disk_onboards=core.disk_onboards,
        updated_at=time.time(),
    )


async def publish_status_loop(core, runtime, namespace: str,
                              interval: float = 2.0) -> None:
    """Standing task: publish this worker's tier snapshot (llmctl kv
    status reads it; components/metrics.py scrapes the same numbers off
    ForwardPassMetrics — this key is the human/CLI view)."""
    from ...runtime.tracing import detach_trace
    detach_trace()
    while True:
        try:
            await runtime.store.kv_put(kv_status_key(namespace),
                                       snapshot(core, namespace).to_json())
        except Exception:  # noqa: BLE001 — store may flap
            logger.exception("kv tier status publish failed")
        await asyncio.sleep(interval)


async def watch_control_loop(core, runtime, namespace: str) -> None:
    """Standing task: act on llmctl kv flush. The control record carries
    a monotonically fresh nonce so re-delivered watches are idempotent;
    ``clear`` drops the disk cache instead of persisting into it."""
    from ...runtime.kvstore import WatchEventType
    from ...runtime.tracing import detach_trace

    detach_trace()
    key = kv_control_key(namespace)
    seen: Optional[float] = None

    async def act(raw: bytes) -> None:
        nonlocal seen
        try:
            d = json.loads(raw)
        except ValueError:
            logger.warning("ignoring malformed kv control at %s", key)
            return
        nonce = d.get("flush")
        if nonce is None or nonce == seen:
            return
        seen = nonce
        if d.get("clear"):
            n = core.disk_store.clear() if core.disk_store is not None else 0
            logger.info("kv control: cleared %d disk blocks", n)
        else:
            n = await core.flush_host_to_disk()
            logger.info("kv control: flushed %d host blocks to disk", n)
        # acknowledge by refreshing the status snapshot immediately
        await runtime.store.kv_put(kv_status_key(namespace),
                                   snapshot(core, namespace).to_json())

    # NOTE: deliberately no act() on the stored value at startup — a
    # flush requested for the PREVIOUS process must not replay into a
    # fresh engine; only post-start control writes apply.
    entry = await runtime.store.kv_get(key)
    if entry is not None:
        try:
            seen = json.loads(entry.value).get("flush")
        except ValueError:
            pass
    watcher = await runtime.store.watch_prefix(key)
    async for ev in watcher:
        if ev.type == WatchEventType.PUT:
            try:
                await act(ev.entry.value)
            except Exception:  # noqa: BLE001 — one bad command must not
                logger.exception("kv control command failed")


async def watch_weights_loop(runtime, namespace: str) -> None:
    """Standing task: apply `llmctl kv set-weights` live. Unlike the
    flush control, the STORED value applies at startup too — tier
    weights are declarative config, not a one-shot command, so a late
    joiner must converge to the namespace's current table. Workers and
    routers both run this; the scoring module's TIER_WEIGHTS dict is
    mutated in place so every importer (indexer tier discounting,
    scheduler NetKV credit) sees the change without restart."""
    from ...runtime.kvstore import WatchEventType
    from ...runtime.tracing import detach_trace
    from ..kv_router.scoring import set_tier_weights

    detach_trace()
    key = kv_weights_key(namespace)

    def apply(raw: bytes) -> None:
        try:
            weights = json.loads(raw)
        except ValueError:
            logger.warning("ignoring malformed kv weights at %s", key)
            return
        if not isinstance(weights, dict):
            logger.warning("ignoring non-dict kv weights at %s", key)
            return
        eff = set_tier_weights(weights)
        logger.info("kv tier weights -> %s", eff)

    entry = await runtime.store.kv_get(key)
    if entry is not None:
        apply(entry.value)
    watcher = await runtime.store.watch_prefix(key)
    async for ev in watcher:
        if ev.type == WatchEventType.PUT:
            apply(ev.entry.value)
