"""Streaming layer-wise KV handoff plane (FlowKV, PAPERS.md).

The monolithic disagg handoff (llm/disagg.py ``handoff_wire``) ships the
whole prompt's KV in one chunked payload AFTER the prefill-side gather
completes, so the entire device→host fetch + DCN transfer + decode-side
scatter sits serially on TTFT. This module pipelines that tail per layer:

- **producer** (PrefillWorker): the prefill engine's gather output is
  wrapped as a :class:`LayeredHarvest` — per-layer host fetches off the
  one dispatched device gather. :func:`send_layer_stream` announces the
  geometry up front with a :class:`LayerStreamManifest` frame, then chains
  one DATA frame per layer on the SAME dial-back stream the monolithic
  handoff uses (native dataplane when available, JSON fallback
  byte-identical — the frames are opaque header+payload pairs either way).
  Layer ``l+1``'s device→host fetch overlaps layer ``l``'s send.
- **consumer** (DisaggEngine → EngineCore): frames land in a
  :class:`LayerStreamPayload`; the decode engine admits the request
  immediately (slot reserved, not decode-visible) and scatters each layer
  into the paged pool as it arrives via the existing off-thread prep
  (engine/core.py ``_stream_onboard``), recorded per layer as the
  ``kv_layer_stream`` wire event. The request becomes decode-ready the
  tick the last layer lands.
- **fallback ladder** (never an error):
  1. a torn mid-stream layer frame (``disagg.layer_stream`` failpoint)
     degrades to the monolithic payload ON THE SAME STREAM — the consumer
     fills every remaining layer from it, bit-exactly;
  2. a dead stream / short frame / peer death fails the payload — the
     decode engine releases the half-onboarded blocks and re-admits COLD
     (local recompute, engine/core.py ``_stream_onboard`` failure path);
  3. no stream at all (old peer, device plane, multi-controller gather)
     is simply the monolithic handoff, unchanged.

Pricing: :func:`exposed_transfer_s` is the overlap cost model both
``AdmissionGate.modeled_fetch_overlap_s`` (llm/kv/fabric.py) and the
router's ``scoring.network_adjusted_overlap`` use — a transfer streamed
over ``n_layers`` frames and overlapped with ``hidden_s`` of compute
exposes only ``max(T / n_layers, T - hidden_s)`` of its serial cost
``T`` on the critical path (the first frame can't overlap anything that
hasn't started; compute can hide at most ``hidden_s`` of the rest).
"""

from __future__ import annotations

import asyncio
import dataclasses
import json
import logging
from typing import Callable, Dict, List, Optional

import numpy as np

from ...runtime import faults
from ..protocols.disagg import (KV_CHUNK_BYTES, KvPayload,
                                encode_kv_payload)

logger = logging.getLogger("dynamo_tpu.llm.kv.stream")

__all__ = ["LayerStreamManifest", "LayeredHarvest", "LayerStreamPayload",
           "MANIFEST_KIND", "LAYER_KIND", "send_layer_stream",
           "send_monolithic_payload", "decode_layer_frame",
           "exposed_transfer_s"]

# header "stream" discriminators — a consumer that sees neither treats
# the frame as the monolithic KV payload (protocols/disagg.py)
MANIFEST_KIND = "kv_layer_manifest"
LAYER_KIND = "kv_layer"


@dataclasses.dataclass
class LayerStreamManifest:
    """First frame of a layer stream: everything the consumer needs to
    admit the request and decode every later frame — the first token,
    the block hashes, and the per-layer array geometry. Wire dataclass
    (DL004-locked): evolve append-only with defaulted fields."""

    request_id: str
    first_token: int
    first_logprob: float
    seq_hashes: List[int]          # chained hashes of the FULL blocks
    num_layers: int
    shape: List[int]               # per-layer wire shape [H, n, bs, D]
    dtype: str                     # numpy dtype name (bf16 via ml_dtypes)
    keys: List[str]                # sorted pool key set ({"k","v"}/{"kv"})

    def to_header(self) -> bytes:
        d = dataclasses.asdict(self)
        d["stream"] = MANIFEST_KIND
        return json.dumps(d).encode()

    @classmethod
    def from_header(cls, h: dict) -> "LayerStreamManifest":
        return cls(request_id=h["request_id"],
                   first_token=int(h["first_token"]),
                   first_logprob=float(h["first_logprob"]),
                   seq_hashes=[int(x) for x in h["seq_hashes"]],
                   num_layers=int(h["num_layers"]),
                   shape=[int(x) for x in h["shape"]],
                   dtype=str(h["dtype"]), keys=list(h["keys"]))


@dataclasses.dataclass
class LayeredHarvest:
    """Prefill-side handle over ONE dispatched device gather: per-layer
    host fetches plus the whole-stack fetch the fallback ladder needs.
    Produced by EngineCore._handoff_and_finish when the decode side
    negotiated layer streaming; consumed by send_layer_stream (the
    callables run off-thread — they are device→host fetches)."""

    num_layers: int
    fetch_layer: Callable[[int], Dict[str, np.ndarray]]  # {"k": [H,n,bs,D]}
    fetch_all: Callable[[], Dict[str, np.ndarray]]       # {"k": [L,H,n,bs,D]}


def _np_dtype(name: str):
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes
        return np.dtype(getattr(ml_dtypes, name))


def encode_layer_frame(layer: int,
                       values: Dict[str, np.ndarray],
                       keys: List[str]) -> tuple:
    """→ (header, data) for one per-layer DATA frame. Byte layout matches
    the monolithic payload's per-key concatenation, restricted to one
    layer — the consumer's reassembled stack is bit-identical to a
    decoded monolithic payload."""
    header = json.dumps({"stream": LAYER_KIND, "layer": layer}).encode()
    return header, b"".join(np.ascontiguousarray(values[k]).tobytes()
                            for k in keys)


def decode_layer_frame(manifest: LayerStreamManifest,
                       data: bytes) -> Dict[str, np.ndarray]:
    """One layer's bytes → {key: [H, n, bs, D]}. A short/long payload
    raises ValueError — the consumer's cold-recompute rung, never a
    silently-corrupt scatter."""
    shape = tuple(manifest.shape)
    dt = _np_dtype(manifest.dtype)
    nbytes = int(np.prod(shape)) * dt.itemsize
    if len(data) != nbytes * len(manifest.keys):
        raise ValueError(
            f"short layer frame: {len(data)} bytes, expected "
            f"{nbytes * len(manifest.keys)}")
    return {key: np.frombuffer(
        data[i * nbytes:(i + 1) * nbytes], dtype=dt).reshape(shape)
        for i, key in enumerate(manifest.keys)}


class LayerStreamPayload:
    """Consumer-side assembler: the decode engine admits against this the
    moment the manifest lands; per-layer values fill in as frames arrive.

    Duck-compatible with KvPayload where admission needs it
    (request_id / first_token / first_logprob / seq_hashes); the engine's
    progressive onboard awaits :meth:`wait_layer` instead of reading
    ``.values``."""

    def __init__(self, manifest: LayerStreamManifest):
        self.manifest = manifest
        self.request_id = manifest.request_id
        self.first_token = manifest.first_token
        self.first_logprob = manifest.first_logprob
        self.seq_hashes = list(manifest.seq_hashes)
        self.num_layers = manifest.num_layers
        self._layers: Dict[int, Dict[str, np.ndarray]] = {}
        self._event = asyncio.Event()
        self._error: Optional[str] = None
        self.fallback_monolithic = False   # filled from a monolithic tail

    @property
    def complete(self) -> bool:
        return len(self._layers) >= self.num_layers

    @property
    def failed(self) -> bool:
        return self._error is not None

    @property
    def values(self) -> Dict[str, np.ndarray]:
        """Whole-stack view ({key: [L, H, n, bs, D]}) — valid only once
        complete; lets a fully-arrived payload admit through the
        monolithic precomputed path bit-identically."""
        if not self.complete:
            raise RuntimeError("layer stream incomplete")
        return {key: np.stack([self._layers[l][key]
                               for l in range(self.num_layers)])
                for key in self.manifest.keys}

    def put_layer(self, layer: int, vals: Dict[str, np.ndarray]) -> None:
        if not (0 <= layer < self.num_layers):
            raise ValueError(f"layer {layer} outside [0, {self.num_layers})")
        self._layers[layer] = vals
        self._event.set()

    def put_all(self, values: Dict[str, np.ndarray]) -> None:
        """Monolithic-fallback fill: a whole-stack payload arrived on the
        stream (the producer hit a torn frame) — every layer not yet
        delivered is sliced out of it."""
        self.fallback_monolithic = True
        for l in range(self.num_layers):
            if l not in self._layers:
                self._layers[l] = {k: v[l] for k, v in values.items()}
        self._event.set()

    def fail(self, msg: str) -> None:
        if self._error is None:
            self._error = msg
        self._event.set()

    def finish(self) -> None:
        """Stream ended: an incomplete payload is a failure (rung 2)."""
        if not self.complete:
            self.fail(f"layer stream ended at {len(self._layers)}/"
                      f"{self.num_layers} layers")

    async def wait_layer(self, layer: int) -> Dict[str, np.ndarray]:
        """Block until ``layer`` is available (or the stream failed)."""
        while True:
            if self._error is not None:
                raise RuntimeError(
                    f"kv layer stream failed: {self._error}")
            if layer in self._layers:
                return self._layers[layer]
            self._event.clear()
            await self._event.wait()


async def send_monolithic_payload(sender, payload: KvPayload) -> None:
    """The whole-stack payload as chunked DATA frames (the pre-streaming
    wire handoff, kept as the shared fallback rung). Does NOT finish the
    stream — the caller owns the SENTINEL."""
    header, data = encode_kv_payload(payload)
    await sender.send(data[:KV_CHUNK_BYTES], header=header)
    for off in range(KV_CHUNK_BYTES, len(data), KV_CHUNK_BYTES):
        await sender.send(data[off:off + KV_CHUNK_BYTES])


async def send_layer_stream(sender, request_id: str, first_token: int,
                            first_logprob: float, seq_hashes: List[int],
                            harvest: LayeredHarvest) -> dict:
    """Producer driver: manifest frame, then one DATA frame per layer,
    pipelining layer ``l+1``'s device→host fetch behind layer ``l``'s
    send. A torn frame (``disagg.layer_stream`` failpoint — the site
    models the wire tearing mid-stream) degrades to the monolithic
    payload on the same stream; the consumer never sees an error.

    Returns {"layers": n_streamed, "fallback": bool} for the worker's
    stats."""
    first = await asyncio.to_thread(harvest.fetch_layer, 0)
    keys = sorted(first)
    sample = first[keys[0]]
    manifest = LayerStreamManifest(
        request_id=request_id, first_token=first_token,
        first_logprob=first_logprob, seq_hashes=list(seq_hashes),
        num_layers=harvest.num_layers, shape=list(sample.shape),
        dtype=sample.dtype.name, keys=keys)
    await sender.send(b"", header=manifest.to_header())

    streamed = 0
    vals: Optional[Dict[str, np.ndarray]] = first
    prefetch: Optional[asyncio.Task] = None
    try:
        for layer in range(harvest.num_layers):
            if vals is None:
                vals = await prefetch
                prefetch = None
            if layer + 1 < harvest.num_layers:
                prefetch = asyncio.get_running_loop().create_task(
                    asyncio.to_thread(harvest.fetch_layer, layer + 1))
            header, data = encode_layer_frame(layer, vals, keys)
            expected = len(data)
            data = faults.mangle("disagg.layer_stream", data)
            if len(data) != expected:
                # rung 1: the frame tore mid-stream — degrade to the
                # monolithic payload on this same stream (byte-identical
                # to the pre-streaming handoff; the consumer fills every
                # remaining layer from it)
                logger.warning(
                    "layer stream for %s torn at layer %d/%d — "
                    "degrading to the monolithic handoff", request_id,
                    layer, harvest.num_layers)
                if prefetch is not None:
                    prefetch.cancel()
                    prefetch = None
                values = await asyncio.to_thread(harvest.fetch_all)
                await send_monolithic_payload(sender, KvPayload(
                    request_id=request_id, first_token=first_token,
                    first_logprob=first_logprob,
                    seq_hashes=list(seq_hashes), values=values))
                await sender.finish()
                return {"layers": streamed, "fallback": True}
            await sender.send(data, header=header)
            streamed += 1
            vals = None
        await sender.finish()
        return {"layers": streamed, "fallback": False}
    finally:
        if prefetch is not None:
            prefetch.cancel()


def exposed_transfer_s(transfer_s: float, n_layers: int,
                       hidden_s: float = 0.0) -> float:
    """Critical-path cost of a transfer of serial duration ``transfer_s``
    streamed as ``n_layers`` frames with ``hidden_s`` seconds of
    overlappable compute behind it.

    - The consumer can't act before the FIRST frame lands: at least
      ``transfer_s / n_layers`` is always exposed.
    - Compute hides at most ``hidden_s`` of the rest:
      ``transfer_s - hidden_s`` stays exposed when compute runs short.

    Monolithic transfers are the ``n_layers <= 1, hidden_s = 0`` case:
    exposed == transfer_s exactly, so gates pricing with this model are
    backwards-compatible by construction."""
    if transfer_s <= 0.0:
        return 0.0
    n = max(int(n_layers), 1)
    return max(transfer_s / n, transfer_s - max(hidden_s, 0.0))
