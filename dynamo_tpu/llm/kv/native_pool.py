"""ctypes wrapper over the native (C++) KV block reuse pool.

Same interface and semantics as pool.KvBlockPool (the reference's
`AvailableBlocks`/`ReservedBlocks` actor, lib/llm/src/kv/reuse.rs) with the
hash maps and the priority+LRU eviction set in C++ — O(log n) eviction vs
the Python fallback's O(n) min() scan, and no interpreter time on the
match/alloc/release fast paths. Stored/removed events come back through
return buffers; this wrapper fires the Python-side ``on_stored`` /
``on_removed`` callbacks so engine wiring is identical for both pools.
"""

from __future__ import annotations

import ctypes
from typing import Callable, List, Optional, Sequence

from ...utils import native

__all__ = ["NativeKvBlockPool", "load_native_pool_lib"]

_I64 = ctypes.c_int64
_U64 = ctypes.c_uint64
_P = ctypes.c_void_p


def load_native_pool_lib() -> Optional[ctypes.CDLL]:
    lib = native.load("kv_reuse_pool", ["kv_reuse_pool.cpp"])
    if lib is None or getattr(lib, "_kvpool_ready", False):
        return lib
    lib.kvpool_create.restype = _P
    lib.kvpool_create.argtypes = [_I64]
    lib.kvpool_destroy.argtypes = [_P]
    for fn in ("kvpool_free_blocks", "kvpool_reusable_blocks",
               "kvpool_match_queries", "kvpool_match_hits"):
        getattr(lib, fn).restype = _I64
        getattr(lib, fn).argtypes = [_P]
    lib.kvpool_match_prefix.restype = _I64
    lib.kvpool_match_prefix.argtypes = [_P, ctypes.POINTER(_U64), _I64,
                                        ctypes.POINTER(_I64)]
    lib.kvpool_peek_prefix.restype = _I64
    lib.kvpool_peek_prefix.argtypes = [_P, ctypes.POINTER(_U64), _I64]
    lib.kvpool_alloc_uninit.restype = _I64
    lib.kvpool_alloc_uninit.argtypes = [_P, _I64, ctypes.POINTER(_I64),
                                        ctypes.POINTER(_U64),
                                        ctypes.POINTER(_I64)]
    lib.kvpool_register.restype = _I64
    lib.kvpool_register.argtypes = [_P, _I64, _U64, _U64, _U64, _I64, _I64]
    lib.kvpool_hold.argtypes = [_P, ctypes.POINTER(_I64), _I64]
    lib.kvpool_release.argtypes = [_P, ctypes.POINTER(_I64), _I64]
    lib.kvpool_reset.restype = _I64
    lib.kvpool_reset.argtypes = [_P, ctypes.POINTER(_U64)]
    lib.kvpool_layout_stats.argtypes = [_P, ctypes.POINTER(_I64)]
    lib.kvpool_refcounts.argtypes = [_P, ctypes.POINTER(_I64), _I64,
                                     ctypes.POINTER(_I64)]
    lib.kvpool_relocate.restype = _I64
    lib.kvpool_relocate.argtypes = [_P, ctypes.POINTER(_I64),
                                    ctypes.POINTER(_I64), _I64]
    lib._kvpool_ready = True
    return lib


def _u64s(values: Sequence[int]):
    return (_U64 * len(values))(*[v & 0xFFFFFFFFFFFFFFFF for v in values])


def _i64s(values: Sequence[int]):
    return (_I64 * len(values))(*values)


class NativeKvBlockPool:
    """Drop-in for KvBlockPool backed by libkv_reuse_pool.so."""

    def __init__(self, num_blocks: int,
                 on_stored: Optional[Callable] = None,
                 on_removed: Optional[Callable] = None,
                 lib: Optional[ctypes.CDLL] = None):
        self._lib = lib or load_native_pool_lib()
        if self._lib is None:
            raise RuntimeError("native kv pool unavailable")
        self.num_blocks = num_blocks
        self._h = self._lib.kvpool_create(num_blocks)
        self.on_stored = on_stored
        self.on_removed = on_removed
        # scratch buffers reused across calls (single-threaded actor)
        self._bid_buf = (_I64 * num_blocks)()
        self._hash_buf = (_U64 * num_blocks)()
        self._n_removed = _I64(0)
        # Python-side shadow of registrations (seq_hash → (bid, tokens_hash,
        # parent_hash)) so reannounce() works without a C enumerate ABI;
        # register/alloc_uninit/reset already round-trip through Python, so
        # the shadow stays exact at zero native-call cost
        self._registered: dict = {}
        # multi-tenant ledger (llm/tenancy.py): the native pool ACCOUNTS
        # per-tenant residency (note on register, forget on removal) but
        # eviction order stays the C side's priority/LRU — quota-
        # preferred device eviction needs the Python pool
        # (DYN_NATIVE_KVPOOL=0); colder tiers quota-prefer either way.
        self.tenancy = None
        self.tenant_evictions = 0

    def __del__(self):
        h, self._h = getattr(self, "_h", None), None
        if h and getattr(self, "_lib", None) is not None:
            self._lib.kvpool_destroy(h)

    # ------------------------------------------------------------- queries
    @property
    def free_blocks(self) -> int:
        return self._lib.kvpool_free_blocks(self._h)

    @property
    def used_blocks(self) -> int:
        return (self.num_blocks - 1) - self.free_blocks

    @property
    def reusable_blocks(self) -> int:
        return self._lib.kvpool_reusable_blocks(self._h)

    @property
    def match_queries(self) -> int:
        return self._lib.kvpool_match_queries(self._h)

    @property
    def match_hits(self) -> int:
        return self._lib.kvpool_match_hits(self._h)

    def hit_rate(self) -> float:
        return self.match_hits / max(self.match_queries, 1)

    # ---------------------------------------------------- layout/contiguity
    def _layout_stats(self):
        buf = (_I64 * 7)()
        self._lib.kvpool_layout_stats(self._h, buf)
        return list(buf)

    @property
    def contig_runs(self) -> int:
        return self._layout_stats()[0]

    @property
    def free_uninit_blocks(self) -> int:
        return self._layout_stats()[2]

    @property
    def alloc_blocks_total(self) -> int:
        return self._layout_stats()[3]

    @property
    def alloc_runs_total(self) -> int:
        return self._layout_stats()[4]

    @property
    def alloc_requests_total(self) -> int:
        return self._layout_stats()[5]

    @property
    def defrag_moves_total(self) -> int:
        return self._layout_stats()[6]

    def frag_ratio(self) -> float:
        _runs, largest, free, *_ = self._layout_stats()
        return 0.0 if free == 0 else 1.0 - largest / free

    def contiguity_ratio(self) -> float:
        s = self._layout_stats()
        possible = s[3] - s[5]
        return 1.0 if possible <= 0 else (s[3] - s[4]) / possible

    @staticmethod
    def count_runs(blocks: Sequence[int]) -> int:
        from .pool import KvBlockPool
        return KvBlockPool.count_runs(blocks)

    def refcounts(self, blocks: Sequence[int]) -> List[int]:
        if not blocks:
            return []
        out = (_I64 * len(blocks))()
        self._lib.kvpool_refcounts(self._h, _i64s(blocks),
                                   len(blocks), out)
        return list(out)

    def relocate(self, moves) -> None:
        moves = list(moves)
        if not moves:
            return
        olds = [o for o, _ in moves]
        news = [n for _, n in moves]
        rc = self._lib.kvpool_relocate(self._h, _i64s(olds), _i64s(news),
                                       len(moves))
        if rc != 0:
            raise ValueError("relocate target not a fresh uninit block "
                             "or source not resident")
        # the reannounce shadow tracks bids — rebind moved registrations
        remap = dict(zip(olds, news))
        for h, (bid, seq_hash, tokens_hash, parent) in list(
                self._registered.items()):
            if bid in remap:
                self._registered[h] = (remap[bid], seq_hash, tokens_hash,
                                       parent)

    # ------------------------------------------------------------ matching
    def match_prefix(self, seq_hashes: Sequence[int]) -> List[int]:
        if not seq_hashes:
            return []
        # repeated hashes can match the same block more than once, so the
        # out buffer must be input-sized, not pool-sized
        buf = (self._bid_buf if len(seq_hashes) <= self.num_blocks
               else (_I64 * len(seq_hashes))())
        n = self._lib.kvpool_match_prefix(self._h, _u64s(seq_hashes),
                                          len(seq_hashes), buf)
        return list(buf[:n])

    def peek_prefix(self, seq_hashes: Sequence[int]) -> int:
        if not seq_hashes:
            return 0
        return self._lib.kvpool_peek_prefix(self._h, _u64s(seq_hashes),
                                            len(seq_hashes))

    # ----------------------------------------------------------- allocate
    def alloc_uninit(self, n: int) -> Optional[List[int]]:
        if n == 0:
            return []
        rc = self._lib.kvpool_alloc_uninit(
            self._h, n, self._bid_buf, self._hash_buf,
            ctypes.byref(self._n_removed))
        if rc != 0:
            return None
        removed = list(self._hash_buf[:self._n_removed.value])
        for h in removed:
            self._registered.pop(h, None)
            if self.tenancy is not None:
                self.tenancy.forget(
                    h - (1 << 64) if h >= (1 << 63) else h, "device")
        if removed and self.on_removed is not None:
            self.on_removed(removed)
        return list(self._bid_buf[:n])

    # ------------------------------------------------------------ register
    def register(self, bid: int, seq_hash: int, tokens_hash: int,
                 parent_hash: Optional[int], priority: int = 0,
                 tenant: Optional[str] = None) -> None:
        if self.tenancy is not None and tenant is not None:
            # ledger keys on the SIGNED hash view the rest of the tier
            # ladder uses (removals below convert back from the C u64)
            self.tenancy.note(seq_hash, tenant, "device")
        stored = self._lib.kvpool_register(
            self._h, bid, seq_hash & 0xFFFFFFFFFFFFFFFF,
            tokens_hash & 0xFFFFFFFFFFFFFFFF,
            (parent_hash or 0) & 0xFFFFFFFFFFFFFFFF,
            0 if parent_hash is None else 1, priority)
        if stored:
            # shadow keyed by the masked u64 the C side reports removals in
            self._registered[seq_hash & 0xFFFFFFFFFFFFFFFF] = (
                bid, seq_hash, tokens_hash, parent_hash)
            if self.on_stored is not None:
                self.on_stored(bid, seq_hash, tokens_hash, parent_hash)

    def hold(self, blocks: Sequence[int]) -> None:
        if blocks:
            self._lib.kvpool_hold(self._h, _i64s(blocks), len(blocks))

    def release(self, blocks: Sequence[int]) -> None:
        if blocks:
            self._lib.kvpool_release(self._h, _i64s(blocks), len(blocks))

    def reset(self) -> None:
        n = self._lib.kvpool_reset(self._h, self._hash_buf)
        removed = list(self._hash_buf[:n])
        for h in removed:
            self._registered.pop(h, None)
            if self.tenancy is not None:
                self.tenancy.forget(
                    h - (1 << 64) if h >= (1 << 63) else h, "device")
        if n and self.on_removed is not None:
            self.on_removed(removed)

    # --------------------------------------------------------- reannounce
    def registered_entries(self):
        """(bid, seq_hash, tokens_hash, parent_hash) per registered block
        (from the Python shadow — same shape as KvBlockPool's)."""
        return [v for v in self._registered.values()]

    def reannounce(self, announce: Optional[Callable] = None) -> int:
        """Parent-ordered replay of every stored-block announcement — the
        lease-reclaim recovery hook (see KvBlockPool.reannounce)."""
        announce = announce or self.on_stored
        if announce is None:
            return 0
        pending = self.registered_entries()
        emitted: set = set()
        n = 0
        while pending:
            progress = False
            deferred = []
            for bid, seq_hash, tokens_hash, parent in pending:
                if parent is None or parent in emitted:
                    announce(bid, seq_hash, tokens_hash, parent)
                    emitted.add(seq_hash)
                    n += 1
                    progress = True
                else:
                    deferred.append((bid, seq_hash, tokens_hash, parent))
            if not progress:
                for bid, seq_hash, tokens_hash, parent in deferred:
                    announce(bid, seq_hash, tokens_hash, parent)
                    n += 1
                break
            pending = deferred
        return n
