"""Token-block hashing: the identity scheme for KV reuse and routing.

Reference: lib/llm/src/tokens.rs:27-200 + tokens/blocks.rs — token sequences
split into fixed-size blocks; per-block `block_hash = xxh3(tokens)` and
chained `sequence_hash = xxh3([parent_seq_hash, block_hash])`, seed 1337
(kv_router/indexer.rs:64). The sequence hash identifies a block's *content in
context* (same tokens after a different prefix hash differently), which is
what makes prefix matching a single hash lookup.
"""

from __future__ import annotations

import struct
from typing import List, Optional, Sequence

import xxhash

HASH_SEED = 1337


def hash_tokens(tokens: Sequence[int]) -> int:
    """Local block hash: xxh3_64 over the little-endian u32 token ids."""
    buf = struct.pack(f"<{len(tokens)}I", *tokens)
    return xxhash.xxh3_64_intdigest(buf, seed=HASH_SEED)


def chain_hash(parent_seq_hash: Optional[int], block_hash: int) -> int:
    """sequence_hash = xxh3([parent_seq_hash, block_hash])."""
    if parent_seq_hash is None:
        buf = struct.pack("<Q", block_hash)
    else:
        buf = struct.pack("<QQ", parent_seq_hash, block_hash)
    return xxhash.xxh3_64_intdigest(buf, seed=HASH_SEED)


class TokenBlockSequence:
    """Splits a token stream into fixed-size blocks with chained hashes.

    Incremental: `extend` consumes tokens one block at a time so the decode
    loop can register blocks as they fill.
    """

    def __init__(self, block_size: int,
                 tokens: Optional[Sequence[int]] = None):
        self.block_size = block_size
        self.tokens: List[int] = []
        self.block_hashes: List[int] = []      # local hash per full block
        self.sequence_hashes: List[int] = []   # chained hash per full block
        if tokens:
            self.extend(tokens)

    def extend(self, tokens: Sequence[int]) -> None:
        self.tokens.extend(int(t) for t in tokens)
        self._absorb()

    def append(self, token: int) -> None:
        self.tokens.append(int(token))
        self._absorb()

    def _absorb(self) -> None:
        bs = self.block_size
        while len(self.block_hashes) < len(self.tokens) // bs:
            i = len(self.block_hashes)
            block = self.tokens[i * bs:(i + 1) * bs]
            bh = hash_tokens(block)
            parent = self.sequence_hashes[-1] if self.sequence_hashes else None
            self.block_hashes.append(bh)
            self.sequence_hashes.append(chain_hash(parent, bh))

    @property
    def num_full_blocks(self) -> int:
        return len(self.block_hashes)

    def partial_tokens(self) -> List[int]:
        return self.tokens[self.num_full_blocks * self.block_size:]


def compute_block_hashes(tokens: Sequence[int], block_size: int
                         ) -> List[int]:
    """Chained sequence hashes for every full block of `tokens` (reference
    `compute_block_hash_for_seq`, kv_router/indexer.rs:123)."""
    seq = TokenBlockSequence(block_size, tokens)
    return seq.sequence_hashes
