"""Refcounted device-block pool with prefix reuse and LRU+priority eviction.

TPU-native redesign of the reference's three cooperating pieces
(lib/llm/src/kv/manager.rs `KvStorageManager`, kv/reuse.rs `AvailableBlocks`
with its `PriorityKey{priority, return_tick, seq_hash}` eviction order, and
kv/reserved.rs `ReservedBlocks`): one pool object owning every block of the
engine's flat paged HBM pool.

States per block:
- uninitialized: free, content garbage (`_free_uninit`)
- inflight: refcount > 0, attached to ≥1 running sequence
- reusable: refcount == 0 but content valid & registered under its
  sequence hash — eligible for prefix matching, evicted priority-then-LRU
  when uninitialized blocks run out.

Single-threaded by design (one pool per engine loop — the same actor
discipline the reference enforces with its mpsc progress engine,
reuse.rs:638; here the asyncio loop IS the actor).
"""

from __future__ import annotations

import bisect
import dataclasses
import heapq
import logging
import os
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from .blocks import TokenBlockSequence

logger = logging.getLogger("dynamo_tpu.kv.pool")


class FreeRunIndex:
    """Coalescing index over the uninitialized free blocks: maximal runs
    of physically-adjacent block ids, with best-fit-run allocation.

    This is the device-pool half of the contiguity story (docs/
    kv_layout.md): logically paged KV does not have to be physically
    scattered — when a sequence's blocks land as few maximal runs, the
    decode kernel coalesces each run into ONE DMA per wave
    (engine/attention.py wave-coalescing) instead of one per block.

    Determinism contract (the native C++ pool mirrors this EXACTLY —
    tests/test_kv_pool.py differential fuzz): best fit = the smallest
    run with length >= n, ties broken by smallest start; when no run
    fits, take the LARGEST run (ties: smallest start) whole and repeat.
    Blocks are handed out ascending from each run's start.
    """

    def __init__(self):
        self._start: Dict[int, int] = {}   # run start -> length
        self._end: Dict[int, int] = {}     # run end (exclusive) -> start
        self._sorted: List[Tuple[int, int]] = []  # (length, start) sorted
        self.count = 0

    def __len__(self) -> int:
        return self.count

    @property
    def num_runs(self) -> int:
        return len(self._start)

    @property
    def largest_run(self) -> int:
        return self._sorted[-1][0] if self._sorted else 0

    def _remove_run(self, start: int, length: int) -> None:
        del self._start[start]
        del self._end[start + length]
        i = bisect.bisect_left(self._sorted, (length, start))
        assert self._sorted[i] == (length, start)
        self._sorted.pop(i)

    def _insert_run(self, start: int, length: int) -> None:
        self._start[start] = length
        self._end[start + length] = start
        bisect.insort(self._sorted, (length, start))

    def add(self, bid: int) -> None:
        """Return one block, coalescing with adjacent free runs."""
        start, length = bid, 1
        left = self._end.get(bid)
        if left is not None:                 # run ends exactly at bid
            llen = self._start[left]
            self._remove_run(left, llen)
            start, length = left, llen + 1
        rlen = self._start.get(bid + 1)
        if rlen is not None:                 # run starts right after bid
            self._remove_run(bid + 1, rlen)
            length += rlen
        self._insert_run(start, length)
        self.count += 1

    def take(self, n: int) -> List[int]:
        """Allocate n blocks as few maximal runs (contract above).
        Caller guarantees n <= len(self)."""
        out: List[int] = []
        while n > 0:
            i = bisect.bisect_left(self._sorted, (n, -1))
            if i < len(self._sorted):        # best fit: smallest len >= n
                length, start = self._sorted[i]
                take = n
            else:                            # largest run (tie: min start)
                length = self._sorted[-1][0]
                j = bisect.bisect_left(self._sorted, (length, -1))
                length, start = self._sorted[j]
                take = length
            self._remove_run(start, length)
            if take < length:
                self._insert_run(start + take, length - take)
            out.extend(range(start, start + take))
            n -= take
        self.count -= len(out)
        return out


@dataclasses.dataclass
class BlockMeta:
    block_id: int
    seq_hash: Optional[int] = None        # set when registered
    tokens_hash: Optional[int] = None     # local (unchained) hash
    parent_hash: Optional[int] = None
    refcount: int = 0
    priority: int = 0                     # lower evicts first
    return_tick: int = 0                  # LRU tiebreak


class KvBlockPool:
    """Owns block ids [1, num_blocks) — block 0 is the engine's trash block."""

    def __init__(self, num_blocks: int,
                 on_stored: Optional[Callable] = None,
                 on_removed: Optional[Callable] = None):
        self.num_blocks = num_blocks
        self._meta: Dict[int, BlockMeta] = {
            i: BlockMeta(i) for i in range(1, num_blocks)}
        # run-tracking free structure: maximal runs of adjacent block
        # ids, best-fit allocation — a sequence's new blocks land as few
        # physically-contiguous runs (the decode kernel's coalesced-DMA
        # contract, engine/attention.py)
        self._free_uninit = FreeRunIndex()
        for i in range(1, num_blocks):
            self._free_uninit.add(i)
        self._by_hash: Dict[int, int] = {}          # seq_hash → block_id
        self._reusable: Dict[int, int] = {}         # block_id → seq_hash (dict = insertion/LRU order)
        # lazy eviction heap keyed (priority, return_tick, bid): pushed
        # when a block becomes reusable; stale entries (re-matched,
        # re-registered with a new priority, already evicted) are
        # skipped at pop time by comparing against live meta — the
        # amortized-victim-selection treatment HostKvPool._slot_for got
        # (was an O(n) min() scan per eviction)
        self._evict_heap: List[Tuple[int, int, int]] = []
        self.evict_heap_skips = 0     # stale entries popped (regression stat)
        self._tick = 0
        self.on_stored = on_stored
        self.on_removed = on_removed
        # multi-tenant quota enforcement (llm/tenancy.py,
        # docs/multi_tenant.md): when a TenantBlockLedger is attached,
        # register() notes each hash's tenant in the device tier and
        # _evict_one prefers victims belonging to an OVER-QUOTA tenant
        # (bounded scan) — one tenant's eviction storm lands on its own
        # blocks first. None (the default) keeps eviction byte-identical
        # to the untenanted pool (the C++ mirror's differential-fuzz
        # contract is untouched).
        self.tenancy = None
        self.tenant_evictions = 0     # victims taken by quota preference
        # stats
        self.match_queries = 0
        self.match_hits = 0
        # contiguity accounting (nv_llm_kv_* layout gauges): how many
        # maximal runs each alloc was served as, vs the one-run ideal
        self.alloc_blocks_total = 0
        self.alloc_runs_total = 0
        self.alloc_requests_total = 0
        self.defrag_moves_total = 0

    # ------------------------------------------------------------- queries
    @property
    def free_blocks(self) -> int:
        return len(self._free_uninit) + len(self._reusable)

    @property
    def used_blocks(self) -> int:
        return (self.num_blocks - 1) - self.free_blocks

    @property
    def reusable_blocks(self) -> int:
        return len(self._reusable)

    @property
    def free_uninit_blocks(self) -> int:
        """Uninitialized free blocks only (no reusable content at
        stake) — the defrag pass allocates its target runs strictly
        from these so a layout move never evicts cached prefixes."""
        return len(self._free_uninit)

    def hit_rate(self) -> float:
        return self.match_hits / max(self.match_queries, 1)

    @property
    def contig_runs(self) -> int:
        """Maximal free runs in the uninit index (1 = fully coalesced)."""
        return self._free_uninit.num_runs

    def frag_ratio(self) -> float:
        """Fragmentation of the uninit free space: 1 - largest_run/free.
        0 = one maximal run (or nothing free); → 1 as the free space
        shatters into single blocks."""
        n = len(self._free_uninit)
        if n == 0:
            return 0.0
        return 1.0 - self._free_uninit.largest_run / n

    def contiguity_ratio(self) -> float:
        """Adjacency delivered / adjacency possible across all allocs:
        an n-block alloc served as r runs delivers n - r of its n - 1
        possible adjacent pairs. 1.0 = every alloc was one run."""
        possible = self.alloc_blocks_total - self.alloc_requests_total
        if possible <= 0:
            return 1.0
        return (self.alloc_blocks_total
                - self.alloc_runs_total) / possible

    @staticmethod
    def count_runs(blocks: Sequence[int]) -> int:
        """Maximal runs of consecutive ids in an ORDERED block list —
        the per-sequence fragmentation score the defrag pass ranks by."""
        if not blocks:
            return 0
        return 1 + sum(1 for a, b in zip(blocks, blocks[1:])
                       if b != a + 1)

    # ------------------------------------------------------------ matching
    def match_prefix(self, seq_hashes: Sequence[int]) -> List[int]:
        """Longest-prefix match: returns device block ids whose registered
        content equals the leading chained hashes. Matched blocks get a
        refcount hold (caller must release them later)."""
        out: List[int] = []
        for h in seq_hashes:
            self.match_queries += 1
            bid = self._by_hash.get(h)
            if bid is None:
                break
            self.match_hits += 1
            meta = self._meta[bid]
            if meta.refcount == 0:
                self._reusable.pop(bid, None)
            meta.refcount += 1
            out.append(bid)
        return out

    def peek_prefix(self, seq_hashes: Sequence[int]) -> int:
        """Length (in blocks) of the longest matchable prefix, without
        taking holds or touching stats — the disagg router's cheap estimate
        of local prefix overlap (reference disagg_router.rs prefix_hit_len
        input, computed by the worker before the remote/local decision)."""
        n = 0
        for h in seq_hashes:
            if h not in self._by_hash:
                break
            n += 1
        return n

    # ----------------------------------------------------------- allocate
    def alloc_uninit(self, n: int) -> Optional[List[int]]:
        """n fresh blocks (content garbage) as few maximal runs of
        adjacent ids (best-fit over the free-run index). When the uninit
        index runs short, reusable blocks are evicted FIRST — in strict
        priority-then-LRU order, preserving the eviction contract — and
        returned to the index (coalescing), THEN the runs are carved.
        Returns None if even eviction can't satisfy."""
        if n > self.free_blocks:
            return None
        for _ in range(n - len(self._free_uninit)):
            self._free_uninit.add(self._evict_one())
        out = self._free_uninit.take(n)
        for bid in out:
            self._meta[bid].refcount = 1
        if n:
            self.alloc_requests_total += 1
            self.alloc_blocks_total += n
            self.alloc_runs_total += self.count_runs(out)
        return out

    TENANT_EVICT_SCAN = 64   # bounded over-quota preference scan depth

    def _evict_one(self) -> int:
        # priority first (lower first), then LRU by return_tick — the
        # reference's PriorityKey ordering (reuse.rs) — via the lazy
        # heap: stale entries (block re-matched / re-keyed since push)
        # are skipped by comparing against live meta.
        if self.tenancy is not None:
            bid = self._evict_one_tenant_preferred()
            if bid is not None:
                self.tenant_evictions += 1
                self._invalidate(bid)
                return bid
        while True:
            prio, tick, bid = heapq.heappop(self._evict_heap)
            meta = self._meta[bid]
            if (bid in self._reusable and meta.priority == prio
                    and meta.return_tick == tick):
                break
            self.evict_heap_skips += 1
        self._invalidate(bid)
        return bid

    def _evict_one_tenant_preferred(self) -> Optional[int]:
        """Bounded scan of the eviction heap for a victim whose tenant
        is over its device-tier quota (llm/tenancy.py). Live entries
        passed over are pushed back (heap order preserved — they were
        popped, so no duplicates); stale entries are dropped exactly as
        the normal pop would. None = no over-quota victim in scan range
        → the caller falls through to the standard priority/LRU pop."""
        stash: List[Tuple[int, int, int]] = []
        found: Optional[int] = None
        for _ in range(min(len(self._evict_heap), self.TENANT_EVICT_SCAN)):
            if not self._evict_heap:
                break
            prio, tick, bid = heapq.heappop(self._evict_heap)
            meta = self._meta[bid]
            if not (bid in self._reusable and meta.priority == prio
                    and meta.return_tick == tick):
                self.evict_heap_skips += 1
                continue
            if self.tenancy.is_over_quota_hash(meta.seq_hash, "device"):
                found = bid
                break
            stash.append((prio, tick, bid))
        for e in stash:
            heapq.heappush(self._evict_heap, e)
        return found

    def _invalidate(self, bid: int) -> None:
        meta = self._meta[bid]
        self._reusable.pop(bid, None)
        if meta.seq_hash is not None:
            self._by_hash.pop(meta.seq_hash, None)
            if self.tenancy is not None:
                self.tenancy.forget(meta.seq_hash, "device")
            if self.on_removed is not None:
                self.on_removed([meta.seq_hash])
        meta.seq_hash = None
        meta.tokens_hash = None
        meta.parent_hash = None

    # ------------------------------------------------------------ register
    def register(self, bid: int, seq_hash: int, tokens_hash: int,
                 parent_hash: Optional[int], priority: int = 0,
                 tenant: Optional[str] = None) -> None:
        """Declare a block's content: it now holds the KV for the block whose
        chained hash is seq_hash. Emits a `stored` event. ``tenant``
        attributes the block in the attached TenantBlockLedger (quota
        accounting; no-op without a ledger)."""
        if self.tenancy is not None and tenant is not None:
            # note even on the duplicate/early-return paths below: the
            # content exists and serves this tenant's prefix either way
            self.tenancy.note(seq_hash, tenant, "device")
        meta = self._meta[bid]
        if meta.seq_hash == seq_hash:
            return
        existing = self._by_hash.get(seq_hash)
        if existing is not None and existing != bid:
            # duplicate content (two seqs computed the same prefix block):
            # keep the first registration; this block stays unregistered and
            # will return to the uninit pool on release.
            return
        if meta.seq_hash is not None:
            self._by_hash.pop(meta.seq_hash, None)
        meta.seq_hash = seq_hash
        meta.tokens_hash = tokens_hash
        meta.parent_hash = parent_hash
        if meta.priority != priority and bid in self._reusable:
            # re-key the lazy-heap entry: the old one goes stale and is
            # skipped at pop time (the C++ pool re-keys its set entry)
            heapq.heappush(self._evict_heap,
                           (priority, meta.return_tick, bid))
        meta.priority = priority
        self._by_hash[seq_hash] = bid
        if self.on_stored is not None:
            self.on_stored(bid, seq_hash, tokens_hash, parent_hash)

    def hold(self, blocks: Sequence[int]) -> None:
        """Add one reference to already-held blocks (pins them across an
        async copy, e.g. host offload write-back)."""
        for bid in blocks:
            if bid != 0:
                self._meta[bid].refcount += 1

    # ------------------------------------------------------------- release
    def release(self, blocks: Sequence[int]) -> None:
        """Drop one reference from each block; refcount-0 blocks become
        reusable (if registered) or uninitialized."""
        for bid in blocks:
            if bid == 0:
                continue
            meta = self._meta[bid]
            if meta.refcount == 0:
                continue          # double release is a no-op
            meta.refcount -= 1
            if meta.refcount == 0:
                self._tick += 1
                meta.return_tick = self._tick
                if meta.seq_hash is not None:
                    self._reusable[bid] = meta.seq_hash
                    heapq.heappush(
                        self._evict_heap,
                        (meta.priority, meta.return_tick, bid))
                else:
                    self._free_uninit.add(bid)

    def reset(self) -> None:
        """Drop all reusable content (reference reuse.rs `reset`)."""
        for bid in list(self._reusable):
            self._invalidate(bid)
            self._free_uninit.add(bid)

    # ------------------------------------------------------------ relocate
    def refcounts(self, blocks: Sequence[int]) -> List[int]:
        """Live refcounts (0 for the trash block) — the defrag pass
        skips blocks shared across sequences (refcount != 1)."""
        return [0 if bid == 0 else self._meta[bid].refcount
                for bid in blocks]

    def relocate(self, moves: Sequence[Tuple[int, int]]) -> None:
        """Rebind resident blocks old→new after the engine copied their
        DEVICE contents (engine/core.py defrag): hash registrations and
        refcounts follow the move, the old ids return to the free-run
        index. Each `new` must be a freshly alloc_uninit'd block
        (refcount 1, unregistered) and each `old` a resident block; no
        stored/removed events fire — the hashes are unchanged and block
        ids are worker-local."""
        for old, new in moves:
            m_old, m_new = self._meta[old], self._meta[new]
            if m_new.seq_hash is not None or m_new.refcount != 1:
                raise ValueError(
                    f"relocate target {new} is not a fresh uninit block")
            if m_old.refcount < 1:
                raise ValueError(f"relocate source {old} is not resident")
            m_new.refcount = m_old.refcount
            m_new.priority = m_old.priority
            m_new.return_tick = m_old.return_tick
            if m_old.seq_hash is not None:
                m_new.seq_hash = m_old.seq_hash
                m_new.tokens_hash = m_old.tokens_hash
                m_new.parent_hash = m_old.parent_hash
                self._by_hash[m_new.seq_hash] = new
            m_old.seq_hash = None
            m_old.tokens_hash = None
            m_old.parent_hash = None
            m_old.refcount = 0
            self._free_uninit.add(old)
            self.defrag_moves_total += 1

    # --------------------------------------------------------- reannounce
    def registered_entries(self) -> List[Tuple[int, int, int, Optional[int]]]:
        """Every registered block as (bid, seq_hash, tokens_hash,
        parent_hash) — the pool-side inventory behind ``reannounce``."""
        out = []
        for seq_hash, bid in self._by_hash.items():
            m = self._meta[bid]
            out.append((bid, seq_hash, m.tokens_hash, m.parent_hash))
        return out

    def reannounce(self, announce: Optional[Callable] = None) -> int:
        """Re-publish every registered block through ``announce`` (default:
        the ``on_stored`` sink), parents before children so a radix indexer
        re-chains without re-rooting. The recovery hook for a transient
        lease expiry: the router wiped this worker's index on the DELETE
        watch events, the lease reclaim replayed only discovery KEYS —
        this replays the KV content announcements (KNOWN_ISSUES)."""
        announce = announce or self.on_stored
        if announce is None:
            return 0
        pending = self.registered_entries()
        emitted: set = set()
        n = 0
        while pending:
            progress = False
            deferred = []
            for bid, seq_hash, tokens_hash, parent in pending:
                if parent is None or parent in emitted:
                    announce(bid, seq_hash, tokens_hash, parent)
                    emitted.add(seq_hash)
                    n += 1
                    progress = True
                else:
                    deferred.append((bid, seq_hash, tokens_hash, parent))
            if not progress:
                # orphans (parent evicted): emit anyway — the indexer
                # re-roots unknown parents at the top
                for bid, seq_hash, tokens_hash, parent in deferred:
                    announce(bid, seq_hash, tokens_hash, parent)
                    n += 1
                break
            pending = deferred
        return n


def make_kv_block_pool(num_blocks: int, on_stored=None, on_removed=None,
                       prefer_native: bool = True):
    """Pool factory: the C++ pool (csrc/kv_reuse_pool.cpp) when the
    toolchain is available and DYN_NATIVE_KVPOOL != 0, else the Python
    implementation above. Both expose the identical interface."""
    if prefer_native and os.environ.get("DYN_NATIVE_KVPOOL", "1") != "0":
        try:
            from .native_pool import NativeKvBlockPool
            return NativeKvBlockPool(num_blocks, on_stored=on_stored,
                                     on_removed=on_removed)
        except Exception as e:  # noqa: BLE001 — no toolchain / build failure
            logger.info("native kv pool unavailable (%s); using Python", e)
    return KvBlockPool(num_blocks, on_stored=on_stored,
                       on_removed=on_removed)


@dataclasses.dataclass
class PrefillPlan:
    """Outcome of preparing a sequence for prefill (reference
    `KvStorageManager::prepare_prefill_sequence` /
    `prepare_prefill_offload`, kv/manager.rs:21-168)."""

    hit_blocks: List[int]
    new_blocks: List[int]
    hit_tokens: int
    seq: TokenBlockSequence
    # host-tier hits: slots in the HostKvPool whose content must be copied
    # into the first len(host_slots) entries of new_blocks before prefill
    host_slots: List[int] = dataclasses.field(default_factory=list)
    # disk-tier (G3) hits: chained hashes resident in the DiskKvStore,
    # promoted into new_blocks[len(host_slots):len(host_slots) +
    # len(disk_hashes)] through the same off-thread onboard path. The
    # matched entries are PINNED against spill-pump eviction until the
    # admission completes (match_prefix(pin=True)).
    disk_hashes: List[int] = dataclasses.field(default_factory=list)
    # remote (G4) fabric hits: chained hashes reachable through the
    # RemoteKvStore (a peer worker's disk over the kv_fabric RPC plane,
    # or the shared object store) — the tail of the onboard run, after
    # the disk hits. Admission-gated at match time (remotestore.py:
    # modeled fetch must beat modeled recompute) and fetched on the same
    # off-thread onboard path; a fetch failure clears this list and the
    # engine gracefully recomputes the tail (never an error).
    remote_hashes: List[int] = dataclasses.field(default_factory=list)

    @property
    def all_blocks(self) -> List[int]:
        return self.hit_blocks + self.new_blocks

    @property
    def host_hit_tokens(self) -> int:
        return len(self.host_slots) * self.seq.block_size

    @property
    def disk_hit_tokens(self) -> int:
        return len(self.disk_hashes) * self.seq.block_size

    @property
    def remote_hit_tokens(self) -> int:
        return len(self.remote_hashes) * self.seq.block_size


class KvBlockManager:
    """Pool + hashing glue the engine admit path calls. Optionally backed by
    a host (TPU-VM DRAM) tier, a persistent disk (G3) tier, and a remote
    (G4) fleet-fabric tier: device misses cascade host → disk → remote
    (reference `prepare_prefill_offload` extended down the
    Device→Pinned→Disk→Remote ladder)."""

    def __init__(self, num_blocks: int, block_size: int,
                 on_stored=None, on_removed=None, enable_reuse: bool = True,
                 host_pool=None, disk_store=None, remote_store=None,
                 prefer_native: bool = True):
        self.block_size = block_size
        self.pool = make_kv_block_pool(num_blocks, on_stored=on_stored,
                                       on_removed=on_removed,
                                       prefer_native=prefer_native)
        self.enable_reuse = enable_reuse
        self.host_pool = host_pool
        self.disk_store = disk_store
        self.remote_store = remote_store
        # multi-tenant ledger (llm/tenancy.py) — attached by
        # EngineCore.enable_tenancy alongside the per-tier hooks
        self.tenancy = None

    def prepare_prefill(self, prompt: Sequence[int], extra_blocks: int = 1,
                        seq: Optional[TokenBlockSequence] = None,
                        cold: bool = False
                        ) -> Optional[PrefillPlan]:
        """Match the prompt's full blocks against the pool (device tier, then
        host tier), allocate the remainder (+ room for `extra_blocks` of
        generation). None = out of memory. At least one prompt token is
        always left to recompute so prefill produces the first-token
        logits. ``seq`` may carry the prompt's already-computed hash chain
        (e.g. from the disagg router's estimate) to avoid re-hashing.
        ``cold=True`` skips the host/disk/remote cascade entirely (device
        hits need no onboard) — the engine's graceful fallback after a
        tier onboard prep failed (EngineRequest.cold_admission)."""
        if seq is None:
            seq = TokenBlockSequence(self.block_size, prompt)
        matchable = seq.sequence_hashes
        # never match the *entire* prompt — hold back the final block so at
        # least one token runs through prefill
        if len(prompt) % self.block_size == 0 and matchable:
            matchable = matchable[:-1]
        hit_blocks = (self.pool.match_prefix(matchable)
                      if self.enable_reuse else [])
        hit_tokens = len(hit_blocks) * self.block_size
        host_slots: List[int] = []
        disk_hashes: List[int] = []
        if self.enable_reuse and not cold and self.host_pool is not None:
            host_slots = self.host_pool.match_prefix(
                matchable[len(hit_blocks):])
        if self.enable_reuse and not cold and self.disk_store is not None:
            # G3 cascade: the run of hashes past the host hits. pin=True
            # holds the matched entries against the spill pump's
            # capacity evictions (worker thread) until the admission's
            # off-thread read completes (core unpins)
            disk_hashes = self.disk_store.match_prefix(
                matchable[len(hit_blocks) + len(host_slots):], pin=True)
        remote_hashes: List[int] = []
        # Everything between the pin-taking disk match above and the
        # returned plan (which transfers pin ownership to the caller)
        # runs under an except-all: an unexpected raise — a buggy remote
        # store, a native-pool ABI error in alloc_uninit — must release
        # the device holds and tier pins before propagating, or the
        # engine slot leaks spill-pump victims forever (dynalint DL003,
        # PR 5's runtime assert made static for exception edges too).
        try:
            if (self.enable_reuse and not cold
                    and self.remote_store is not None):
                # G4 cascade: the run past the disk hits, reachable
                # through the fleet fabric (peer disk over RPC, or the
                # shared object store). The store's match is
                # admission-gated — it reports a miss when the modeled
                # fetch loses to recompute — and pin=True holds
                # object-held entries against the capacity reaper until
                # the admission's off-thread read completes.
                remote_hashes = self.remote_store.match_prefix(
                    matchable[len(hit_blocks) + len(host_slots)
                              + len(disk_hashes):], pin=True)
            total_needed = (len(prompt) + extra_blocks * self.block_size
                            + self.block_size - 1) // self.block_size
            n_new = total_needed - len(hit_blocks)
            new_blocks = self.pool.alloc_uninit(n_new)
            if new_blocks is None:
                self.pool.release(hit_blocks)
                if disk_hashes:
                    self.disk_store.unpin(disk_hashes)
                if remote_hashes:
                    self.remote_store.unpin(remote_hashes)
                return None
            if len(new_blocks) < (len(host_slots) + len(disk_hashes)
                                  + len(remote_hashes)):
                # the onboard path scatters host/disk/remote hits into
                # new_blocks[:n_onboard] — a plan where the allocation
                # can't cover the pinned tier hits would silently DROP
                # them (or scatter past the allocation). The cascade
                # math above guarantees this never happens; if a tier's
                # match_prefix over-returns (a buggy store), fail loudly
                # instead of serving garbage. The except below releases
                # every hold so the loud failure doesn't also leak pool
                # refcounts / tier pins.
                self.pool.release(new_blocks)
                raise RuntimeError(
                    f"prepare_prefill invariant violated: "
                    f"{len(new_blocks)} new blocks cannot cover "
                    f"{len(host_slots)} host + {len(disk_hashes)} disk "
                    f"+ {len(remote_hashes)} remote tier hits (prompt "
                    f"{len(prompt)}, device hits {len(hit_blocks)})")
        except Exception:
            self.pool.release(hit_blocks)
            if disk_hashes:
                self.disk_store.unpin(disk_hashes)
            if remote_hashes:
                self.remote_store.unpin(remote_hashes)
            raise
        return PrefillPlan(hit_blocks=hit_blocks, new_blocks=new_blocks,
                           hit_tokens=hit_tokens, seq=seq,
                           host_slots=host_slots, disk_hashes=disk_hashes,
                           remote_hashes=remote_hashes)

    def abort_plan(self, plan: "PrefillPlan") -> None:
        """Release a plan that will never admit: device block holds drop
        and the disk/remote-tier pins (taken at match) release."""
        self.pool.release(plan.all_blocks)
        if plan.disk_hashes and self.disk_store is not None:
            self.disk_store.unpin(plan.disk_hashes)
        if plan.remote_hashes and self.remote_store is not None:
            self.remote_store.unpin(plan.remote_hashes)

    def register_full_blocks(self, plan_blocks: List[int],
                             seq: TokenBlockSequence,
                             already_registered: int,
                             tenant: Optional[str] = None) -> int:
        """Register every newly-full block of `seq` (device block order ==
        block-hash order). Returns the new count of registered blocks.
        ``tenant`` attributes the blocks for per-tenant quota accounting
        (llm/tenancy.py; no-op without an attached ledger)."""
        n_full = seq.num_full_blocks
        for i in range(already_registered, n_full):
            if i >= len(plan_blocks):
                break
            parent = seq.sequence_hashes[i - 1] if i > 0 else None
            self.pool.register(plan_blocks[i], seq.sequence_hashes[i],
                               seq.block_hashes[i], parent, tenant=tenant)
        return min(n_full, len(plan_blocks))
