"""Host-memory KV tier: offloaded prefix blocks in TPU-VM DRAM.

Reference: the "KV cache offload to system memory" pillar — kv/storage.rs
``StorageType::{Device,Pinned,System}`` + CudaPinnedMemory staging +
``KvStorageManager::prepare_prefill_offload`` (kv/manager.rs:21-168), which
buys +40% TTFT on multi-turn workloads (docs/architecture.md:91). TPU-native
redesign: the host tier is one preallocated numpy arena (TPU-VM DRAM is the
pinned tier — no cudaHostAlloc analog needed), blocks keyed by chained
sequence hash with LRU eviction, and device↔host movement is the XLA
gather/scatter + single-transfer path in engine/block_copy.py.

Two pieces:
- :class:`HostKvPool` — the arena: slot allocation, hash→slot map, LRU.
- :class:`KvOffloadEngine` — async pump: drains an offload queue (device →
  host) off the engine's critical path, and performs synchronous onboarding
  (host → device) during admission, where the data is needed *now*.
"""

from __future__ import annotations

import asyncio
import dataclasses
import logging
import time
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

logger = logging.getLogger("dynamo_tpu.kv.offload")

__all__ = ["HostKvPool", "KvOffloadEngine", "OffloadJob", "make_host_pool"]


class HostKvPool:
    """Preallocated host arena of KV blocks keyed by sequence hash.

    Shapes: per block the head-major WIRE layout [L, H_kv, bs, D] for k and
    v — i.e. engine/block_copy.py's ``fetch_wire``/``to_wire_format`` output
    sliced per block (the device pool itself is block-major; convert before
    storing).
    """

    def __init__(self, capacity_blocks: int, num_layers: int,
                 num_kv_heads: int, block_size: int, head_dim: int,
                 dtype=np.float32, opaque_rows: bool = False):
        self.capacity = capacity_blocks
        self.num_kv_heads = num_kv_heads
        # the arena materializes on FIRST store: on a multi-controller
        # mesh each rank's pool holds only its local head shard, whose
        # count is known from the first fetched values, not the config
        # (engine/block_copy.py fetch_wire)
        self._shape_tail = (num_layers, num_kv_heads, block_size, head_dim)
        self._dtype = np.dtype(dtype)
        # opaque_rows (int8 pools): blocks are whole pool rows — values
        # plus in-row scale lanes — shipped as ONE wire "head" whose
        # width is the row width (make_host_pool). A multi-controller
        # rank's shard is then a clean fraction of that width, the same
        # laziness the head count has for full-precision pools.
        self.opaque_rows = opaque_rows
        self._arena: Optional[dict] = None
        self._free: List[int] = list(range(capacity_blocks - 1, -1, -1))
        self._by_hash: Dict[int, int] = {}       # seq_hash → slot
        self._lru: Dict[int, None] = {}          # EVICTABLE hashes, LRU order
        # hashes parked out of the eviction queue because their slot was
        # pinned when an eviction considered them; unpin re-queues them.
        # Keeping them out of _lru makes victim selection O(1) amortized
        # (each park/unpark pairs with one pin cycle) instead of a full
        # scan past every pinned entry per eviction.
        self._lru_parked: Dict[int, None] = {}
        self._hash_by_slot: Dict[int, int] = {}
        self._pins: Dict[int, int] = {}          # slot → pin count
        # per-hash (tokens_hash, parent_hash) — carried so a disk-tier
        # spill of an evicted block can re-announce it to the router's
        # radix index (diskstore.py; publisher tier tags)
        self._meta: Dict[int, tuple] = {}
        # write-behind spill hook: called with (evicted_hash, tokens_hash,
        # parent_hash, values_copy) BEFORE the arena row is overwritten —
        # the disk (G3) tier's feed. values_copy is a fresh per-block
        # dict the callee owns outright.
        self.on_evict: Optional[Callable] = None
        # multi-tenant quota enforcement (llm/tenancy.py): when a
        # TenantBlockLedger is attached, stores note each hash's tenant
        # in the "host" tier (owner remembered from the device tier's
        # registration) and victim selection prefers an OVER-QUOTA
        # tenant's blocks (bounded scan) before the plain LRU front.
        # None keeps eviction byte-identical to the untenanted pool.
        self.tenancy = None
        self.tenant_evictions = 0
        # stats
        self.stored_blocks_total = 0
        self.evicted_blocks_total = 0
        self.match_queries = 0
        self.match_hits = 0
        self.evict_scan_steps = 0   # pinned-candidate requeues (O(1) test)

    def __len__(self) -> int:
        return len(self._by_hash)

    @property
    def free_slots(self) -> int:
        return len(self._free)

    def _touch(self, seq_hash: int) -> None:
        """Freshen a resident hash's LRU position. Parked hashes (pinned
        at some eviction check) stay parked — unpin re-queues them."""
        if seq_hash in self._lru_parked:
            return
        self._lru.pop(seq_hash, None)
        self._lru[seq_hash] = None

    def _place(self, seq_hash: int, slot: int) -> None:
        self._by_hash[seq_hash] = slot
        self._hash_by_slot[slot] = seq_hash
        self._lru_parked.pop(seq_hash, None)
        self._lru[seq_hash] = None

    def _slot_for(self, seq_hash: int):
        """(slot, evicted_hash) — existing slot, else a fresh/evicted one.
        (None, None) if nothing is placeable (capacity 0 / all pinned).

        Victim selection is O(1) amortized: candidates pop from the
        evictable LRU front; a PINNED candidate is PARKED out of the
        queue entirely (re-queued by unpin) instead of being skipped in
        place — the old O(n) scan walked past every pinned entry on
        every eviction, O(n·m) for m stores against a mostly-pinned
        pool. Each park/unpark pairs with one pin cycle, so the
        amortized per-eviction cost is constant."""
        slot = self._by_hash.get(seq_hash)
        if slot is not None:
            self._touch(seq_hash)
            return slot, None
        evicted = None
        if not self._free:
            victim = None
            if self.tenancy is not None:
                # quota preference: the first unpinned over-quota
                # tenant's block within a bounded LRU-front scan evicts
                # before anyone else's (llm/tenancy.py)
                for i, h in enumerate(self._lru):
                    if i >= 64:
                        break
                    if self._pins.get(self._by_hash[h]):
                        continue
                    if self.tenancy.is_over_quota_hash(h, "host"):
                        victim = h
                        self.tenant_evictions += 1
                        break
            while victim is None and self._lru:
                h = next(iter(self._lru))
                if self._pins.get(self._by_hash[h]):
                    self._lru.pop(h)
                    self._lru_parked[h] = None   # park pinned candidate
                    self.evict_scan_steps += 1
                    continue
                victim = h
                break
            if victim is None:       # empty, or everything pinned mid-fetch
                return None, None
            self._lru.pop(victim)
            vslot = self._by_hash.pop(victim)
            self._hash_by_slot.pop(vslot, None)
            self.evicted_blocks_total += 1
            if self.tenancy is not None:
                self.tenancy.forget(victim, "host")
            if self.on_evict is not None and self._arena is not None:
                th, ph = self._meta.get(victim, (None, None))
                try:
                    self.on_evict(victim, th, ph,
                                  {key: arena[vslot].copy()
                                   for key, arena in self._arena.items()})
                except Exception:  # noqa: BLE001 — spill is best-effort
                    logger.exception("host-tier evict hook failed")
            self._meta.pop(victim, None)
            self._free.append(vslot)
            evicted = victim
        slot = self._free.pop()
        self._place(seq_hash, slot)
        return slot, evicted

    def store(self, seq_hashes: Sequence[int], values: dict,
              tokens_hashes: Optional[Sequence[int]] = None,
              parent_hashes: Optional[Sequence[Optional[int]]] = None
              ) -> list:
        """Write stacked blocks (e.g. {"k": [L, H, n, bs, D], "v": …};
        MLA latent pools ship one "kv" entry) under their hashes — the
        arena mirrors whatever key set the device pool has. Returns the
        literal placement decisions ``[(hash, slot, evicted_hash |
        None)]`` — len(result) blocks were stored (capacity may stop
        early). Multihost follower mirrors replay these decisions
        verbatim instead of re-running the LRU policy (apply_store).
        ``tokens_hashes``/``parent_hashes`` (aligned with seq_hashes)
        ride along so a later disk-tier spill can re-announce the block
        to the router's radix index with its chain intact."""
        decisions = []
        for i, h in enumerate(seq_hashes):
            slot, evicted = self._slot_for(h)
            if slot is None:
                break
            if tokens_hashes is not None:
                self._meta[h] = (tokens_hashes[i],
                                 parent_hashes[i] if parent_hashes
                                 is not None else None)
            self._ensure_arena(values)
            for key, arena in self._arena.items():
                arena[slot] = values[key][:, :, i]
            self.stored_blocks_total += 1
            if self.tenancy is not None:
                # owner carried over from the device-tier registration
                # (ledger hash→tenant memory, llm/tenancy.py)
                self.tenancy.note(h, None, "host")
            decisions.append((h, slot, evicted))
        return decisions

    def _ensure_arena(self, values: dict) -> None:
        if self._arena is None:
            first = next(iter(values.values()))
            # per-block shape: stacked values drop the n axis (store),
            # per-block dicts arrive without it (apply_store)
            blk = (first.shape[:2] + first.shape[3:]
                   if first.ndim == 5 else first.shape)
            L, _h, bs, d = self._shape_tail
            got_d = blk[3]
            d_ok = (d % got_d == 0 if self.opaque_rows else got_d == d)
            if (blk[0], blk[2]) != (L, bs) or not d_ok:
                raise ValueError(
                    f"host-tier block shape {tuple(blk)} does not "
                    f"match config {self._shape_tail} (heads — and for "
                    f"opaque int8 rows the row width — may differ per "
                    f"rank; layers/block_size may not)")
            shape = (self.capacity,) + tuple(blk)
            self._arena = {key: np.zeros(shape, self._dtype)
                           for key in values}

    def apply_store(self, seq_hash: int, slot: int,
                    evicted_hash: Optional[int],
                    block_values: dict) -> None:
        """Apply one of the leader's literal store decisions to a mirror
        pool (multihost follower): same hash→slot placement, same
        eviction, arena bytes from the FOLLOWER's own device KV (which is
        bit-identical to the leader's by the dispatch-stream induction).
        ``block_values``: key → ONE block [L, H, bs, D]."""
        if evicted_hash is not None:
            old = self._by_hash.pop(evicted_hash, None)
            self._lru.pop(evicted_hash, None)
            self._lru_parked.pop(evicted_hash, None)
            self._meta.pop(evicted_hash, None)
            if old is not None:
                self._hash_by_slot.pop(old, None)
                if old != slot:
                    self._free.append(old)
            self.evicted_blocks_total += 1
        if self._by_hash.get(seq_hash) != slot:
            try:
                self._free.remove(slot)
            except ValueError:
                pass
        self._place(seq_hash, slot)
        self._ensure_arena(block_values)
        for key, arena in self._arena.items():
            arena[slot] = block_values[key]
        self.stored_blocks_total += 1

    def match_prefix(self, seq_hashes: Sequence[int]) -> List[int]:
        """Longest leading run of hashes present. Returns their slots and
        freshens LRU order."""
        out: List[int] = []
        for h in seq_hashes:
            self.match_queries += 1
            slot = self._by_hash.get(h)
            if slot is None:
                break
            self.match_hits += 1
            self._touch(h)
            out.append(slot)
        return out

    def fetch(self, slots: Sequence[int]) -> dict:
        """Stacked values for ``slots``, keyed like the device pool:
        {key: [L, H, n, bs, D]}."""
        idx = np.asarray(slots, dtype=np.int64)
        return {key: np.ascontiguousarray(
                    arena[idx].transpose(1, 2, 0, 3, 4))
                for key, arena in self._arena.items()}

    def pin(self, slots: Sequence[int]) -> None:
        """Exclude ``slots`` from LRU eviction while an async onboarding
        fetch reads them off the loop thread (the offload pump's stores
        could otherwise evict+reuse an arena row mid-copy)."""
        for s in slots:
            self._pins[s] = self._pins.get(s, 0) + 1

    def unpin(self, slots: Sequence[int]) -> None:
        for s in slots:
            n = self._pins.get(s, 0) - 1
            if n <= 0:
                self._pins.pop(s, None)
                # re-queue a candidate parked while this slot was pinned
                # (to the LRU back — the documented requeue semantics)
                h = self._hash_by_slot.get(s)
                if h is not None and h in self._lru_parked:
                    self._lru_parked.pop(h)
                    self._lru[h] = None
            else:
                self._pins[s] = n

    def contains(self, seq_hash: int) -> bool:
        return seq_hash in self._by_hash

    def hit_rate(self) -> float:
        return self.match_hits / max(self.match_queries, 1)

    def meta_for(self, seq_hash: int) -> tuple:
        """(tokens_hash, parent_hash) recorded at store time (None, None
        when the storer carried no chain info)."""
        return self._meta.get(seq_hash, (None, None))

    def resident_entries(self) -> List[tuple]:
        """Every resident block as (seq_hash, tokens_hash, parent_hash,
        slot) — the flush-to-disk inventory (EngineCore
        flush_host_to_disk / llmctl kv flush)."""
        return [(h, *self._meta.get(h, (None, None)), slot)
                for h, slot in self._by_hash.items()]

    def row_copy(self, slot: int) -> dict:
        """Fresh per-block copy of one arena row ({key: [L, H, bs, D]})
        — what a spill job owns."""
        return {key: arena[slot].copy()
                for key, arena in self._arena.items()}


def make_host_pool(capacity_blocks: int, model_cfg, block_size: int,
                   kv_quantization: str, pool_row_lanes: int,
                   param_dtype) -> HostKvPool:
    """The one way to build a host pool matched to an engine's device
    pool (core.py and the offline replayer share it so they can't
    drift). Full-precision llama pools use the head-major wire layout
    [L, KVH, bs, Dh]; int8 pools AND MLA latent pools ship whole rows
    (``pool_row_lanes`` wide — values + in-row scale lanes for int8,
    rank+rope lanes for MLA) as one opaque wire "head" — a bit-exact
    round trip with no requantization error."""
    if kv_quantization != "none":
        return HostKvPool(capacity_blocks, model_cfg.num_layers, 1,
                          block_size, pool_row_lanes, dtype=np.int8,
                          opaque_rows=True)
    if model_cfg.kv_lora_rank > 0:
        return HostKvPool(capacity_blocks, model_cfg.num_layers, 1,
                          block_size, pool_row_lanes, dtype=param_dtype,
                          opaque_rows=True)
    return HostKvPool(capacity_blocks, model_cfg.num_layers,
                      model_cfg.num_kv_heads, block_size,
                      model_cfg.head_dim, dtype=param_dtype)


class KvStoreEmitError(RuntimeError):
    """The on_store (dispatch-stream) emission failed AFTER the host pool
    committed a store: multihost follower mirrors can no longer be proven
    identical. Never swallowed by the pump's best-effort handler — the
    pump dies and the broken stream fails every later recorded admission
    (engine/multihost.py DispatchStreamLeader.rec)."""


@dataclasses.dataclass
class OffloadJob:
    """Device blocks to write back to host. The enqueuer pre-holds
    ``block_ids`` in the device pool (an extra refcount) so they cannot be
    reused mid-copy; :class:`KvOffloadEngine` releases that hold via its
    ``release_holds`` callback once the copy lands (or fails)."""

    block_ids: List[int]
    seq_hashes: List[int]
    # local (unchained) hashes aligned with seq_hashes; optional — when
    # present the host pool records them so disk-tier spills can
    # re-announce evicted blocks with their chain intact (diskstore.py).
    # Jobs always start at a sequence's block 0 (core._release_slot), so
    # parent_hashes derive as [None, seq_hashes[0], seq_hashes[1], ...].
    tokens_hashes: Optional[List[int]] = None


class KvOffloadEngine:
    """Asynchronous device→host write-back pump.

    The engine enqueues jobs when sequences finish (their full blocks hold
    valid KV); the pump batches jobs, gathers once on device, transfers once,
    and releases the device holds. Mirrors the role of the reference's
    CopyStream + offload path (kv/layer.rs CopyStream, manager.rs
    prepare_prefill_offload) with XLA DMA instead of CUDA streams.
    """

    def __init__(self, host_pool: HostKvPool, block_size: int,
                 get_kv: Callable[[], dict],
                 release_holds: Optional[Callable[[List[int]], None]] = None,
                 max_batch_blocks: int = 64,
                 simulated_gbps: Optional[float] = None,
                 on_store: Optional[Callable[[list], None]] = None,
                 max_queue_jobs: int = 512):
        self.host_pool = host_pool
        self.block_size = block_size
        self.get_kv = get_kv
        self.release_holds = release_holds
        # multihost: called with [(hash, slot, evicted_hash, device_block)]
        # after each committed batch, BEFORE the device holds are released
        # — so the dispatch stream orders the event ahead of any program
        # that could overwrite a reused block (engine/multihost.py)
        self.on_store = on_store
        self.max_batch_blocks = max_batch_blocks
        # injectable d2h link model (VERDICT r2 weak-3): when set, each
        # write-back batch is paced to `bytes / simulated_gbps` wall time,
        # so an e2e run on a FAST local link (CPU tests) measures the tier
        # under a realistic TPU-VM link instead of this rig's tunnel
        self.simulated_gbps = simulated_gbps
        # bounded write-back queue: saturation DROPS the job (with its
        # device holds released and a counter bumped) instead of letting
        # an unbounded backlog pin device blocks — losing a cache
        # write-back under pressure is strictly better than KV-pool
        # starvation. Previously the drop was impossible but the queue
        # was unbounded and silent.
        self.max_queue_jobs = max_queue_jobs
        self._queue: asyncio.Queue = asyncio.Queue()
        self._task: Optional[asyncio.Task] = None
        self.offloaded_blocks_total = 0
        self.dropped_jobs_total = 0
        self.simulated_wait_s = 0.0

    def enqueue(self, job: OffloadJob) -> None:
        if self._queue.qsize() >= self.max_queue_jobs:
            self.dropped_jobs_total += 1
            if self.release_holds is not None:
                self.release_holds(job.block_ids)
            return
        self._queue.put_nowait(job)
        self._ensure_task()

    def _ensure_task(self) -> None:
        if self._task is None or self._task.done():
            try:
                loop = asyncio.get_running_loop()
            except RuntimeError:
                return
            self._task = loop.create_task(self._run(), name="kv-offload")

    async def _run(self) -> None:
        while True:
            job: OffloadJob = await self._queue.get()
            jobs = [job]
            total = len(job.block_ids)
            while total < self.max_batch_blocks and not self._queue.empty():
                j = self._queue.get_nowait()
                jobs.append(j)
                total += len(j.block_ids)
            try:
                await self._process(jobs)
            except KvStoreEmitError:
                logger.critical(
                    "kv_store stream emission failed after the pool "
                    "committed — multihost mirrors are unprovable; "
                    "killing the pump (the broken stream stops serving)")
                raise
            except Exception:  # noqa: BLE001 — write-back is best-effort
                logger.exception("kv offload batch failed")
            finally:
                if self.release_holds is not None:
                    for j in jobs:
                        self.release_holds(j.block_ids)
                for _ in jobs:
                    self._queue.task_done()
            await asyncio.sleep(0)  # yield to the engine loop

    async def _process(self, jobs: List[OffloadJob]) -> None:
        from ...engine.block_copy import fetch_wire, gather_blocks_dispatch

        block_ids = [b for j in jobs for b in j.block_ids]
        seq_hashes = [h for j in jobs for h in j.seq_hashes]
        # chain meta per block: jobs start at block 0 of their sequence,
        # so parents are the preceding seq hash within the job
        tok_hashes = [th for j in jobs
                      for th in (j.tokens_hashes
                                 or [None] * len(j.seq_hashes))]
        parents = [p for j in jobs
                   for p in ([None] + list(j.seq_hashes[:-1]))]
        # skip blocks already resident on host (multi-turn re-offload)
        keep = [i for i, h in enumerate(seq_hashes)
                if not self.host_pool.contains(h)]
        if not keep:
            return
        ids = [block_ids[i] for i in keep]
        hashes = [seq_hashes[i] for i in keep]
        toks = [tok_hashes[i] for i in keep]
        pars = [parents[i] for i in keep]
        # dispatch the on-device gather HERE, on the loop thread: it orders
        # correctly against the engine's donated decode steps and returns a
        # fresh (never-donated) buffer
        n = len(ids)
        stacked = gather_blocks_dispatch(self.get_kv(), ids, self.block_size)
        # ...then do the blocking device→DRAM transfer off-thread so decode
        # keeps stepping during the DMA
        t0 = time.monotonic()
        values = await asyncio.to_thread(
            fetch_wire, stacked, n, self.host_pool.num_kv_heads)
        if self.simulated_gbps:
            nbytes = sum(v.nbytes for v in values.values()) \
                if isinstance(values, dict) else values.nbytes
            target = nbytes / (self.simulated_gbps * 1e9)
            wait = target - (time.monotonic() - t0)
            if wait > 0:
                self.simulated_wait_s += wait
                await asyncio.sleep(wait)
        decisions = self.host_pool.store(hashes, values,
                                         tokens_hashes=toks,
                                         parent_hashes=pars)
        self.offloaded_blocks_total += len(decisions)
        if self.on_store is not None and decisions:
            try:
                self.on_store([(h, slot, evicted, ids[i])
                               for i, (h, slot, evicted)
                               in enumerate(decisions)])
            except Exception as e:  # noqa: BLE001
                raise KvStoreEmitError(str(e)) from e

    async def drain(self) -> None:
        self._ensure_task()
        await self._queue.join()

    async def stop(self) -> None:
        """Flush pending write-backs, then cancel the pump."""
        try:
            await asyncio.wait_for(self.drain(), timeout=10)
        except asyncio.TimeoutError:
            logger.warning("kv offload drain timed out; dropping queue")
            while not self._queue.empty():
                job = self._queue.get_nowait()
                if self.release_holds is not None:
                    self.release_holds(job.block_ids)
                self._queue.task_done()
        if self._task is not None:
            self._task.cancel()
            self._task = None
