"""Stream utilities.

Reference: lib/runtime/src/utils/stream.rs:25-60 — ``until_deadline``
(DeadlineStream): pass items through until a deadline, then end the stream
cleanly (the remote side keeps its cancellation semantics; this only bounds
how long the consumer waits).
"""

from __future__ import annotations

import asyncio
from typing import AsyncIterator, TypeVar

T = TypeVar("T")

__all__ = ["until_deadline"]


async def until_deadline(stream: AsyncIterator[T],
                         deadline_s: float) -> AsyncIterator[T]:
    """Yield from ``stream`` until ``deadline_s`` seconds (monotonic, from
    now) elapse; stops cleanly at the deadline, mid-wait included."""
    loop = asyncio.get_running_loop()
    deadline = loop.time() + deadline_s
    it = stream.__aiter__()
    task = None
    try:
        while True:
            remaining = deadline - loop.time()
            if remaining <= 0:
                return
            task = asyncio.ensure_future(it.__anext__())
            try:
                yield await asyncio.wait_for(asyncio.shield(task), remaining)
            except asyncio.TimeoutError:
                task.cancel()
                try:
                    await task
                except (StopAsyncIteration, asyncio.CancelledError):
                    pass
                task = None
                return
            except StopAsyncIteration:
                task = None
                return
            task = None
    finally:
        # consumer break or cancellation mid-yield: the shielded __anext__
        # may still be pending — cancel it so the source stream doesn't run
        # detached. No await here: this may execute under GeneratorExit.
        if task is not None and not task.done():
            task.cancel()
