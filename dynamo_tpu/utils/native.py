"""Native (C++) component loader: builds csrc/ into shared libs on first use
and memoizes. Keeps the framework importable on machines without a toolchain
(callers fall back to pure-Python implementations when load fails)."""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import Optional

logger = logging.getLogger("dynamo_tpu.native")

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_CSRC = os.path.join(_REPO_ROOT, "csrc")
_BUILD_DIR = os.path.join(_CSRC, "build")
_LOCK = threading.Lock()
_CACHE: dict = {}


def _build(name: str, sources: list, extra_flags: Optional[list] = None) -> str:
    os.makedirs(_BUILD_DIR, exist_ok=True)
    out = os.path.join(_BUILD_DIR, f"lib{name}.so")
    srcs = [os.path.join(_CSRC, s) for s in sources]
    newest_src = max(os.path.getmtime(s) for s in srcs)
    if os.path.exists(out) and os.path.getmtime(out) >= newest_src:
        return out
    cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-o", out,
           *srcs, *(extra_flags or [])]
    logger.info("building native lib: %s", " ".join(cmd))
    subprocess.run(cmd, check=True, capture_output=True, text=True)
    return out


def load(name: str, sources: list,
         extra_flags: Optional[list] = None) -> Optional[ctypes.CDLL]:
    """Build (if stale) and dlopen csrc/<sources> as lib<name>.so.
    Returns None when the toolchain or build fails."""
    with _LOCK:
        if name in _CACHE:
            return _CACHE[name]
        try:
            path = _build(name, sources, extra_flags)
            lib = ctypes.CDLL(path)
        except (subprocess.CalledProcessError, OSError) as e:
            detail = getattr(e, "stderr", "") or str(e)
            logger.warning("native lib %s unavailable (%s); using Python "
                           "fallback", name, detail.strip()[:500])
            lib = None
        _CACHE[name] = lib
        return lib
