"""Native (C++) component loader: builds csrc/ into shared libs on first use
and memoizes. Keeps the framework importable on machines without a toolchain
(callers fall back to pure-Python implementations when load fails)."""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import Optional

logger = logging.getLogger("dynamo_tpu.native")

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_CSRC = os.path.join(_REPO_ROOT, "csrc")
_BUILD_DIR = os.path.join(_CSRC, "build")
_LOCK = threading.Lock()
_CACHE: dict = {}


# sanitizer build mode (csrc differential-fuzz hardening): the env knob
# DYN_NATIVE_SANITIZE selects instrumented builds — "asan", "ubsan", or
# "asan,ubsan". Sanitized objects land next to the normal ones under a
# distinct name (lib<name>.asan.so) so the two build flavors never
# clobber each other's mtime caching. NOTE: dlopen'ing an ASan build
# into a non-ASan python requires LD_PRELOAD of libasan — the sanitized
# smoke test (tests/test_native_sanitize.py) runs its fuzz round in a
# subprocess with the preload set; in-process load() of an asan build
# without the preload fails and falls back to Python cleanly.
_SAN_FLAGS = {
    "asan": ["-fsanitize=address", "-fno-omit-frame-pointer", "-g", "-O1"],
    "ubsan": ["-fsanitize=undefined", "-fno-sanitize-recover=undefined",
              "-g", "-O1"],
}


def sanitize_mode() -> Optional[str]:
    """Normalized DYN_NATIVE_SANITIZE value ("asan", "ubsan",
    "asan,ubsan") or None. Unknown tokens are rejected loudly — a typo'd
    knob silently building uninstrumented would defeat the fuzz ride."""
    raw = os.environ.get("DYN_NATIVE_SANITIZE", "").strip()
    if not raw or raw == "0":
        return None
    modes = sorted({m.strip() for m in raw.split(",") if m.strip()})
    for m in modes:
        if m not in _SAN_FLAGS:
            raise ValueError(
                f"DYN_NATIVE_SANITIZE={raw!r}: unknown sanitizer {m!r} "
                f"(supported: {sorted(_SAN_FLAGS)})")
    return ",".join(modes)


def _build(name: str, sources: list, extra_flags: Optional[list] = None,
           sanitize: Optional[str] = None) -> str:
    os.makedirs(_BUILD_DIR, exist_ok=True)
    tag = "" if not sanitize else "." + sanitize.replace(",", "-")
    out = os.path.join(_BUILD_DIR, f"lib{name}{tag}.so")
    srcs = [os.path.join(_CSRC, s) for s in sources]
    newest_src = max(os.path.getmtime(s) for s in srcs)
    if os.path.exists(out) and os.path.getmtime(out) >= newest_src:
        return out
    san_flags = [f for m in (sanitize.split(",") if sanitize else [])
                 for f in _SAN_FLAGS[m]]
    cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-o", out,
           *srcs, *san_flags, *(extra_flags or [])]
    logger.info("building native lib: %s", " ".join(cmd))
    subprocess.run(cmd, check=True, capture_output=True, text=True)
    return out


def build(name: str, sources: list,
          extra_flags: Optional[list] = None,
          sanitize: Optional[str] = None) -> Optional[str]:
    """Build without dlopen'ing (the sanitized-fuzz harness builds in the
    parent and loads in an LD_PRELOADed subprocess). Returns the .so path
    or None when the toolchain is missing/fails."""
    try:
        return _build(name, sources, extra_flags, sanitize=sanitize)
    except (subprocess.CalledProcessError, OSError) as e:
        detail = getattr(e, "stderr", "") or str(e)
        logger.warning("native build %s failed (%s)", name,
                       detail.strip()[:500])
        return None


def load(name: str, sources: list,
         extra_flags: Optional[list] = None) -> Optional[ctypes.CDLL]:
    """Build (if stale) and dlopen csrc/<sources> as lib<name>.so —
    instrumented per DYN_NATIVE_SANITIZE when set. Returns None when the
    toolchain or build fails."""
    sanitize = sanitize_mode()
    key = (name, sanitize)
    with _LOCK:
        if key in _CACHE:
            return _CACHE[key]
        try:
            path = _build(name, sources, extra_flags, sanitize=sanitize)
            lib = ctypes.CDLL(path)
        except (subprocess.CalledProcessError, OSError) as e:
            detail = getattr(e, "stderr", "") or str(e)
            logger.warning("native lib %s unavailable (%s); using Python "
                           "fallback", name, detail.strip()[:500])
            lib = None
        _CACHE[key] = lib
        return lib
