"""Async object pool with RAII-style return and priority reuse.

Reference: lib/runtime/src/utils/pool.rs:23-427 — `Returnable` items,
`PoolItem` (return-on-drop), `SharedPoolItem` (refcounted sharing), used for
KV blocks and copy streams. The Python analog returns items via context
manager or explicit release; a GC finalizer backstops forgotten items so a
leaked handle can't shrink the pool permanently.

Priority reuse: `acquire()` pops the most-recently-returned item (LIFO) so
hot items (warm caches, bound buffers) are reused first — the reference's
priority ordering with recency as the default priority.
"""

from __future__ import annotations

import asyncio
import weakref
from typing import Any, Generic, List, Optional, TypeVar

T = TypeVar("T")

__all__ = ["AsyncPool", "PoolItem", "SharedPoolItem"]


class PoolItem(Generic[T]):
    """A borrowed item. Use as an async context manager, or call
    ``release()``; either returns the value to the pool exactly once."""

    def __init__(self, pool: "AsyncPool[T]", value: T):
        self._pool = pool
        self.value = value
        self._released = False
        # GC backstop: if the holder drops the handle without releasing,
        # the finalizer puts the value back (reference: Drop impl).
        self._finalizer = weakref.finalize(
            self, AsyncPool._return_raw, pool, value)

    def release(self) -> None:
        if self._released:
            return
        self._released = True
        self._finalizer.detach()
        self._pool._return(self.value)

    def share(self) -> "SharedPoolItem[T]":
        """Convert to a refcounted shared handle (reference
        SharedPoolItem); this PoolItem becomes inert."""
        if self._released:
            raise RuntimeError("cannot share a released item")
        self._released = True
        self._finalizer.detach()
        return SharedPoolItem(_SharedState(self._pool, self.value))

    async def __aenter__(self) -> T:
        return self.value

    async def __aexit__(self, *exc) -> None:
        self.release()

    def __enter__(self) -> T:
        return self.value

    def __exit__(self, *exc) -> None:
        self.release()


class _SharedState(Generic[T]):
    """Refcount cell shared by every clone of one borrowed value."""

    def __init__(self, pool: "AsyncPool[T]", value: T):
        self.pool = pool
        self.value = value
        self.refs = 0


class SharedPoolItem(Generic[T]):
    """Refcounted shared borrow: ``clone()`` makes an independent handle,
    ``release()`` drops this handle's reference (idempotent per handle,
    like the reference's Arc clone/drop); the value returns to the pool
    when the last handle releases. Each handle carries its own GC
    finalizer, so a leaked clone can't shrink the pool."""

    def __init__(self, state: _SharedState):
        self._state = state
        self._released = False
        state.refs += 1
        self._finalizer = weakref.finalize(
            self, SharedPoolItem._drop_ref, state)

    @property
    def value(self) -> T:
        return self._state.value

    def clone(self) -> "SharedPoolItem[T]":
        if self._released:
            raise RuntimeError("clone of a released shared item")
        return SharedPoolItem(self._state)

    def release(self) -> None:
        if self._released:
            return
        self._released = True
        self._finalizer.detach()
        self._drop_ref(self._state)

    @staticmethod
    def _drop_ref(state: _SharedState) -> None:
        state.refs -= 1
        if state.refs == 0:
            state.pool._return(state.value)


class AsyncPool(Generic[T]):
    """Fixed population of reusable objects.

    ``on_return(value)`` (optional ctor arg) runs when a value re-enters
    the pool — the reference's ``Returnable::on_return`` reset hook.
    """

    def __init__(self, items: List[T], on_return=None):
        self._free: List[T] = list(items)          # LIFO: hot items on top
        self._capacity = len(items)
        self._on_return = on_return
        self._waiters: List[asyncio.Future] = []

    @property
    def available(self) -> int:
        return len(self._free)

    @property
    def capacity(self) -> int:
        return self._capacity

    def try_acquire(self) -> Optional[PoolItem[T]]:
        if not self._free:
            return None
        return PoolItem(self, self._free.pop())

    async def acquire(self, timeout: Optional[float] = None) -> PoolItem[T]:
        item = self.try_acquire()
        if item is not None:
            return item
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._waiters.append(fut)
        try:
            value = await (asyncio.wait_for(fut, timeout)
                           if timeout is not None else fut)
        except (asyncio.TimeoutError, asyncio.CancelledError):
            if not fut.done():
                fut.cancel()
            self._waiters = [w for w in self._waiters if w is not fut]
            if fut.done() and not fut.cancelled():
                # value was handed to us as we timed out — put it back
                self._return(fut.result())
            raise
        return PoolItem(self, value)

    # internal ------------------------------------------------------------
    def _return(self, value: T) -> None:
        if self._on_return is not None:
            self._on_return(value)
        while self._waiters:
            fut = self._waiters.pop(0)
            if not fut.done():
                fut.set_result(value)
                return
        self._free.append(value)

    @staticmethod
    def _return_raw(pool: "AsyncPool[Any]", value: Any) -> None:
        """Finalizer path — GC may run this on any thread, and
        Future.set_result is not thread-safe, so waiter wakeup is
        marshalled onto the waiter's loop; with no waiters a plain append
        suffices."""
        if pool._waiters:
            loop = pool._waiters[0].get_loop()
            loop.call_soon_threadsafe(pool._return, value)
        else:
            if pool._on_return is not None:
                pool._on_return(value)
            pool._free.append(value)
