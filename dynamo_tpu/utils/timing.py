"""Chained-dispatch slope timing for device-truth measurements.

Wall-clock over the axon tunnel pays ~131 ms per value fetch and
`block_until_ready` does not wait through the tunnel (KNOWN_ISSUES.md), so
per-step device time is measured as a SLOPE: time a short chain of m1
dispatches and a long chain of m2, each ending in ONE value fetch as the
barrier; (t2 - t1) / (m2 - m1) cancels the fetch cost and every constant
overhead. Tunnel jitter is one-sided (stalls only), so each point takes the
min over `reps` runs. The single home of this protocol — bench.py and
tools/decode_profile.py both use it.
"""

from __future__ import annotations

import time
from typing import Callable


def slope_per_unit(run: Callable[[int], float], m1: int, m2: int,
                   *, reps: int = 2, warmup: bool = True) -> float:
    """run(m) executes a chain of m units (ending in its own barrier fetch)
    and returns elapsed seconds. Returns per-unit seconds, clamped >= 0."""
    if warmup:
        run(m1)                       # settle compiles / queue state
    t1 = min(run(m1) for _ in range(reps))
    t2 = min(run(m2) for _ in range(reps))
    return max((t2 - t1) / (m2 - m1), 0.0)


def timed(fn: Callable[[], None]) -> float:
    """Elapsed seconds of fn() — the building block for run(m) closures."""
    t0 = time.monotonic()
    fn()
    return time.monotonic() - t0
