from .pool import AsyncPool, PoolItem, SharedPoolItem
from .stream import until_deadline

__all__ = ["AsyncPool", "PoolItem", "SharedPoolItem", "until_deadline"]
