"""TCP clients for the discovery/bus daemon (runtime/server.py): NetKvStore
implements the KvStore interface, NetBus the MessageBus interface, over the
daemon's length-prefixed JSON protocol.

These are the reference's etcd-client / async-nats analogs
(lib/runtime/src/transports/{etcd,nats}.rs): a single multiplexed connection
each, a demux reader matching ``rid`` replies and routing ``push`` frames
(watch events, bus messages) to their handles.
"""

from __future__ import annotations

import asyncio
import base64
import logging
import os
import random
from typing import Dict, List, Optional

from .bus import BusMessage, MessageBus, Subscription, WorkItem, WorkQueue
from .kvstore import (KvEntry, KvStore, Lease, PrefixWatcher, WatchEvent,
                      WatchEventType)
from .server import recv_msg, send_msg

logger = logging.getLogger("dynamo_tpu.runtime.netstore")

# process-wide retry counter across every daemon connection — the
# nv_llm_netstore_retries_total feed (a rising rate means the discovery
# daemon link is flapping; each worker's stats handler exports it via
# ForwardPassMetrics.netstore_retries_total)
_retries_total = 0
# process-wide deadline-exceeded counter (nv_llm_netstore_deadline_
# exceeded_total): calls that burned their whole per-call budget —
# rising means the daemon is partitioned/unresponsive, not just flapping
_deadline_exceeded_total = 0


class NetstoreDeadlineExceeded(ConnectionError):
    """A call()'s total per-call deadline elapsed — the typed signal
    that the daemon is partitioned (connected-but-unresponsive) rather
    than flapping. Subclasses ConnectionError so existing degradation
    paths (retry ladders, best-effort deregistration) keep engaging."""


def retries_total() -> int:
    return _retries_total


def deadline_exceeded_total() -> int:
    return _deadline_exceeded_total


def _count_retry() -> None:
    global _retries_total
    _retries_total += 1


def _count_deadline() -> None:
    global _deadline_exceeded_total
    _deadline_exceeded_total += 1


def _b64(b: bytes) -> str:
    return base64.b64encode(b).decode()


def _unb64(s: str) -> bytes:
    return base64.b64decode(s)


class _Conn:
    """One multiplexed daemon connection: request/reply + push routing,
    with transparent reconnection.

    Liveness contract (reference: transports/etcd/lease.rs — clients ride
    out etcd leader changes): if the daemon dies and comes back at the
    same address within RETRY_WINDOW, every pending/new call retries, and
    registered watches/subscriptions/served subjects are re-established on
    the fresh connection under their original client-allocated ids (the
    push-routing tables keep working untouched). Re-established prefix
    watches replay the server's CURRENT keys as synthetic PUTs — consumers
    are keyed/idempotent, so duplicates are harmless; keys whose owners
    died during the outage simply never reappear. Lease identity recovery
    lives in NetKvStore.lease_refresh (reclaim-by-id + leased-key replay).
    """

    RETRY_WINDOW = 30.0
    # bounded retry for one call(): whichever of the attempt budget and
    # the time window runs out first ends the retry loop — a partitioned
    # daemon fails callers in bounded time instead of spinning
    MAX_CALL_RETRIES = 8
    # TOTAL per-call deadline on top of the retry ladder: the window
    # above only binds BETWEEN attempts, so a connected-but-unresponsive
    # (partitioned) daemon could hold one attempt's reply future
    # forever. Every in-flight attempt is clipped to the remaining
    # budget and exhaustion raises NetstoreDeadlineExceeded (counted in
    # nv_llm_netstore_deadline_exceeded_total).
    CALL_DEADLINE = float(os.environ.get("DYN_NETSTORE_CALL_DEADLINE",
                                         "20.0"))
    # jitter factor range on every backoff sleep: N reconnecting clients
    # of a restarted daemon must not stampede it in lockstep
    RETRY_JITTER = (0.5, 1.5)

    def __init__(self, addr: str):
        self.addr = addr
        self.retries_total = 0
        self.reader: Optional[asyncio.StreamReader] = None
        self.writer: Optional[asyncio.StreamWriter] = None
        self._next_rid = 1
        # rid → (future, connection epoch the request was written on).
        # Epoch tagging closes a reconnect race: a future written on
        # connection N must be failed when N dies, even if connection N+1
        # is already up by the time N's read loop unwinds — otherwise the
        # caller awaits a reply that can never arrive.
        self._pending: Dict[int, tuple] = {}
        self._epoch = 0
        self._push_watch: Dict[int, PrefixWatcher] = {}
        self._push_sub: Dict[int, Subscription] = {}
        # replay registries: wid → prefix; sid → (op, kwargs)
        self._watch_reg: Dict[int, str] = {}
        self._sub_reg: Dict[int, tuple] = {}
        self._reader_task: Optional[asyncio.Task] = None
        self._write_lock = asyncio.Lock()
        self._conn_lock = asyncio.Lock()
        self._connected = False
        self.closed = False            # permanent, client-initiated
        self.reconnects = 0

    @classmethod
    async def open(cls, addr: str, timeout: float = 10.0) -> "_Conn":
        conn = cls(addr)
        await conn._establish(timeout)   # initial connect fails fast
        return conn

    async def _establish(self, timeout: float = 5.0) -> None:
        host, port = self.addr.rsplit(":", 1)
        reader, writer = await asyncio.wait_for(
            asyncio.open_connection(host, int(port)), timeout)
        old_task = self._reader_task
        self._epoch += 1
        self.reader, self.writer = reader, writer
        self._connected = True
        self._reader_task = asyncio.get_running_loop().create_task(
            self._read_loop(reader, self._epoch), name="netstore-demux")
        # requests written to the replaced socket can never be answered —
        # fail them now rather than waiting for the old read loop to unwind
        self._fail_pending_epochs(self._epoch - 1)
        if old_task is not None:
            old_task.cancel()

    def _fail_pending_epochs(self, max_epoch: int) -> None:
        stale = [rid for rid, (_f, ep) in self._pending.items()
                 if ep <= max_epoch]
        for rid in stale:
            fut, _ep = self._pending.pop(rid)
            if not fut.done():
                fut.set_exception(ConnectionError("daemon connection lost"))

    async def _read_loop(self, reader: asyncio.StreamReader,
                         epoch: int) -> None:
        try:
            while True:
                msg = await recv_msg(reader)
                if msg is None:
                    break
                if "push" in msg:
                    self._route_push(msg)
                    continue
                entry = self._pending.pop(msg.get("rid"), None)
                if entry is not None and not entry[0].done():
                    entry[0].set_result(msg)
        except (ConnectionError, ValueError):
            pass
        finally:
            # fail exactly the requests written on THIS connection (or an
            # older one) — futures tagged with a newer epoch belong to the
            # replacement connection (replay calls) and must survive
            self._fail_pending_epochs(epoch)
            if reader is self.reader:    # a stale loop must not clobber a
                self._connected = False  # newer connection's state
                if not self.closed and (self._watch_reg or self._sub_reg):
                    # push consumers (watches/subscriptions) make no calls
                    # of their own — reconnect eagerly on their behalf
                    asyncio.get_running_loop().create_task(
                        self._auto_reconnect(), name="netstore-reconnect")

    async def _auto_reconnect(self) -> None:
        try:
            await self._ensure_connected()
        except ConnectionError:
            logger.warning("auto-reconnect to %s gave up after %.0fs; "
                           "watch/subscription streams stay dark until the "
                           "next call", self.addr, self.RETRY_WINDOW)

    def _route_push(self, msg: dict) -> None:
        if msg["push"] == "watch":
            w = self._push_watch.get(msg["wid"])
            if w is not None:
                typ = (WatchEventType.PUT if msg["type"] == "put"
                       else WatchEventType.DELETE)
                w._push(WatchEvent(typ, KvEntry(
                    msg["key"], _unb64(msg["value"]), msg.get("lease", 0))))
        elif msg["push"] == "msg":
            s = self._push_sub.get(msg["sid"])
            if s is not None:
                s._push(BusMessage(msg["subject"], _unb64(msg["payload"])))

    async def _ensure_connected(self) -> None:
        if self.closed:
            raise ConnectionError("connection closed")
        if self._connected:
            return
        async with self._conn_lock:
            if self._connected or self.closed:
                return
            loop = asyncio.get_running_loop()
            deadline = loop.time() + self.RETRY_WINDOW
            delay = 0.05
            while True:
                try:
                    await self._establish()
                    break
                except (OSError, asyncio.TimeoutError):
                    if self.closed or loop.time() + delay > deadline:
                        raise ConnectionError(
                            f"daemon unreachable at {self.addr}")
                    # jittered like call(): a fleet reconnecting to a
                    # restarted daemon must not arrive in lockstep
                    await asyncio.sleep(delay * random.uniform(
                        *self.RETRY_JITTER))
                    delay = min(delay * 2, 1.0)
            self.reconnects += 1
            logger.info("reconnected to daemon %s (attempt %d); replaying "
                        "%d watches, %d subscriptions", self.addr,
                        self.reconnects, len(self._watch_reg),
                        len(self._sub_reg))
            for wid, prefix in list(self._watch_reg.items()):
                await self._call_once("watch_prefix", prefix=prefix, wid=wid)
            for sid, (op, kw) in list(self._sub_reg.items()):
                await self._call_once(op, sid=sid, **kw)

    async def _call_once(self, op: str, **kwargs) -> dict:
        rid = self._next_rid
        self._next_rid += 1
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        try:
            async with self._write_lock:
                # snapshot writer+epoch with no await in between so the
                # future is tagged with the connection it is written on
                writer, epoch = self.writer, self._epoch
                self._pending[rid] = (fut, epoch)
                await send_msg(writer, {"rid": rid, "op": op, **kwargs})
        except (OSError, ConnectionError) as e:
            self._pending.pop(rid, None)
            if fut.done():
                fut.exception()   # consume — a racing epoch-fail set it
            self._connected = False
            raise ConnectionError(str(e))
        reply = await fut
        if not reply.get("ok"):
            raise RuntimeError(reply.get("error", f"{op} failed"))
        return reply

    async def call(self, op: str, **kwargs) -> dict:
        """One logical request with bounded, jittered retry: a transient
        daemon hiccup (restart, dropped socket) retries up to
        MAX_CALL_RETRIES times inside RETRY_WINDOW with exponential
        backoff × uniform jitter, counting each retry
        (``retries_total`` per connection + the module counter feeding
        nv_llm_netstore_retries_total) — instead of surfacing the first
        flap as a hard error to the caller.

        When a request trace is ambient (runtime/tracing.py) the call is
        recorded as a ``netstore.{op}`` span — control-plane RPCs issued
        on a request's critical path (discovery lookups, lease work)
        show up in the fleet trace instead of hiding in the daemon.

        A TOTAL per-call deadline (CALL_DEADLINE) rides on top: each
        attempt's reply wait is clipped to the remaining budget, so a
        partitioned daemon — connected but never answering — fails the
        caller in bounded time with :class:`NetstoreDeadlineExceeded`
        instead of holding it for the full jittered retry ladder."""
        from . import faults
        from .tracing import span as _span
        loop = asyncio.get_running_loop()
        deadline = loop.time() + self.RETRY_WINDOW
        call_deadline = loop.time() + self.CALL_DEADLINE
        delay = 0.05
        attempts = 0
        while True:
            remaining = call_deadline - loop.time()
            if remaining <= 0:
                _count_deadline()
                raise NetstoreDeadlineExceeded(
                    f"netstore call {op!r} exceeded its "
                    f"{self.CALL_DEADLINE:.0f}s deadline after "
                    f"{attempts} retries")
            try:
                await faults.hit_async("netstore.call",
                                       exc=ConnectionError)
                await self._ensure_connected()
                with _span(f"netstore.{op}"):
                    return await asyncio.wait_for(
                        self._call_once(op, **kwargs), remaining)
            except (asyncio.TimeoutError, TimeoutError):
                _count_deadline()
                raise NetstoreDeadlineExceeded(
                    f"netstore call {op!r} exceeded its "
                    f"{self.CALL_DEADLINE:.0f}s deadline mid-attempt "
                    f"(daemon partitioned?)") from None
            except ConnectionError:
                attempts += 1
                if (self.closed or loop.time() >= deadline
                        or attempts >= self.MAX_CALL_RETRIES):
                    raise
                self.retries_total += 1
                _count_retry()
                await asyncio.sleep(min(
                    delay * random.uniform(*self.RETRY_JITTER),
                    max(call_deadline - loop.time(), 0.001)))
                delay = min(delay * 2, 1.0)

    async def close(self) -> None:
        self.closed = True
        self._connected = False
        if self._reader_task is not None:
            self._reader_task.cancel()
        if self.writer is not None and not self.writer.is_closing():
            self.writer.close()


class NetKvStore(KvStore):
    def __init__(self, conn: _Conn):
        self._conn = conn
        # lease-identity recovery state: ttl per lease + the keys written
        # under it, replayed after a daemon restart (lease_refresh)
        self._lease_ttl: Dict[int, float] = {}
        self._leased_keys: Dict[int, Dict[str, bytes]] = {}

    @classmethod
    async def connect(cls, addr: str) -> "NetKvStore":
        return cls(await _Conn.open(addr))

    def _record(self, key: str, value: bytes, lease_id: int) -> None:
        if lease_id:
            self._leased_keys.setdefault(lease_id, {})[key] = value

    async def kv_create(self, key: str, value: bytes, lease_id: int = 0) -> bool:
        r = await self._conn.call("kv_create", key=key, value=_b64(value),
                                  lease=lease_id)
        if r["result"]:
            self._record(key, value, lease_id)
        return bool(r["result"])

    async def kv_create_or_validate(self, key: str, value: bytes,
                                    lease_id: int = 0) -> bool:
        r = await self._conn.call("kv_create_or_validate", key=key,
                                  value=_b64(value), lease=lease_id)
        if r["result"]:
            self._record(key, value, lease_id)
        return bool(r["result"])

    async def kv_put(self, key: str, value: bytes, lease_id: int = 0) -> None:
        await self._conn.call("kv_put", key=key, value=_b64(value),
                              lease=lease_id)
        self._record(key, value, lease_id)

    async def kv_cas(self, key: str, expected, value: bytes,
                     lease_id: int = 0) -> bool:
        r = await self._conn.call(
            "kv_cas", key=key,
            expected=None if expected is None else _b64(expected),
            value=_b64(value), lease=lease_id)
        if r["result"]:
            self._record(key, value, lease_id)
        return bool(r["result"])

    async def kv_get(self, key: str) -> Optional[KvEntry]:
        r = await self._conn.call("kv_get", key=key)
        e = r.get("entry")
        if e is None:
            return None
        return KvEntry(e["key"], _unb64(e["value"]), e.get("lease", 0))

    async def kv_get_prefix(self, prefix: str) -> List[KvEntry]:
        r = await self._conn.call("kv_get_prefix", prefix=prefix)
        return [KvEntry(e["key"], _unb64(e["value"]), e.get("lease", 0))
                for e in r["entries"]]

    async def kv_delete(self, key: str) -> bool:
        r = await self._conn.call("kv_delete", key=key)
        for keys in self._leased_keys.values():
            keys.pop(key, None)
        return bool(r["result"])

    async def watch_prefix(self, prefix: str) -> PrefixWatcher:
        # client-allocated handle, registered BEFORE the call so pushes that
        # race the reply are never dropped
        wid = self._conn._next_rid + 1_000_000

        def unsub(_w: PrefixWatcher) -> None:
            self._conn._push_watch.pop(wid, None)
            self._conn._watch_reg.pop(wid, None)
            if not self._conn.closed:
                asyncio.get_running_loop().create_task(
                    self._safe_call("watch_close", wid=wid))

        w = PrefixWatcher(prefix, [], unsub)
        self._conn._push_watch[wid] = w
        self._conn._watch_reg[wid] = prefix   # re-established on reconnect
        try:
            await self._conn.call("watch_prefix", prefix=prefix, wid=wid)
        except Exception:
            self._conn._push_watch.pop(wid, None)
            self._conn._watch_reg.pop(wid, None)
            raise
        return w

    async def _safe_call(self, op: str, **kw) -> None:
        try:
            await self._conn.call(op, **kw)
        except Exception:
            pass

    async def lease_create(self, ttl: float, want_id: int = 0) -> Lease:
        r = await self._conn.call("lease_create", ttl=ttl, want_id=want_id)
        self._lease_ttl[r["lease_id"]] = ttl
        return Lease(self, r["lease_id"], ttl)

    async def lease_refresh(self, lease_id: int) -> bool:
        r = await self._conn.call("lease_refresh", lease_id=lease_id)
        if r["result"]:
            return True
        # unknown lease: either it expired (we were gone too long) or the
        # daemon restarted with empty state. Reclaim the SAME id — it is
        # the worker's identity (subjects, discovery keys) — and replay
        # the keys registered under it, so routing recovers without the
        # worker noticing (reference liveness: transports/etcd/lease.rs).
        ttl = self._lease_ttl.get(lease_id)
        if ttl is None:
            return False
        try:
            await self._conn.call("lease_create", ttl=ttl, want_id=lease_id)
        except RuntimeError:
            return False       # id taken by someone else — truly lost
        for key, value in self._leased_keys.get(lease_id, {}).items():
            await self._conn.call("kv_put", key=key, value=_b64(value),
                                  lease=lease_id)
        logger.info("lease %x reclaimed after daemon restart (%d keys "
                    "replayed)", lease_id,
                    len(self._leased_keys.get(lease_id, {})))
        # derived state (router radix index of this worker's blocks) was
        # wiped by the expiry's DELETE events and is NOT in our key replay
        # — let the owner re-announce it (KNOWN_ISSUES kv-router staleness)
        if self.on_lease_reclaimed is not None:
            try:
                self.on_lease_reclaimed(lease_id)
            except Exception:  # noqa: BLE001 — observer must not kill
                logger.exception("on_lease_reclaimed hook failed")
        return True

    async def lease_revoke(self, lease_id: int) -> None:
        self._lease_ttl.pop(lease_id, None)
        self._leased_keys.pop(lease_id, None)
        await self._conn.call("lease_revoke", lease_id=lease_id)

    async def close(self) -> None:
        await self._conn.close()


class _NetWorkQueue(WorkQueue):
    def __init__(self, conn: _Conn, name: str):
        self._conn = conn
        self.name = name

    async def enqueue(self, payload: bytes) -> int:
        r = await self._conn.call("wq_enqueue", queue=self.name,
                                  payload=_b64(payload))
        return r["id"]

    async def dequeue(self, timeout: Optional[float] = None,
                      ack_deadline: float = 30.0) -> Optional[WorkItem]:
        r = await self._conn.call("wq_dequeue", queue=self.name,
                                  timeout=timeout, ack_deadline=ack_deadline)
        item = r.get("item")
        if item is None:
            return None
        return WorkItem(item["id"], _unb64(item["payload"]),
                        item.get("deliveries", 1))

    async def ack(self, item_id: int) -> None:
        await self._conn.call("wq_ack", queue=self.name, id=item_id)

    async def nack(self, item_id: int) -> None:
        await self._conn.call("wq_nack", queue=self.name, id=item_id)

    async def depth(self) -> int:
        r = await self._conn.call("wq_depth", queue=self.name)
        return r["depth"]


class NetBus(MessageBus):
    def __init__(self, conn: _Conn):
        self._conn = conn
        self._served: Dict[str, int] = {}

    @classmethod
    async def connect(cls, addr: str) -> "NetBus":
        return cls(await _Conn.open(addr))

    async def publish(self, subject: str, payload: bytes) -> int:
        r = await self._conn.call("publish", subject=subject,
                                  payload=_b64(payload))
        return int(r.get("receivers", 0))

    async def _make_sub(self, op: str, **kw) -> Subscription:
        sid = self._conn._next_rid + 2_000_000  # client-allocated (see watch)

        def unsub(_s: Subscription) -> None:
            self._conn._push_sub.pop(sid, None)
            self._conn._sub_reg.pop(sid, None)
            if not self._conn.closed:
                asyncio.get_running_loop().create_task(
                    self._safe_call("sub_close", sid=sid))

        sub = Subscription(kw.get("pattern") or kw.get("subject", ""), unsub)
        self._conn._push_sub[sid] = sub
        self._conn._sub_reg[sid] = (op, dict(kw))  # replayed on reconnect
        try:
            await self._conn.call(op, sid=sid, **kw)
        except Exception:
            self._conn._push_sub.pop(sid, None)
            self._conn._sub_reg.pop(sid, None)
            raise
        return sub, sid

    async def subscribe(self, pattern: str) -> Subscription:
        sub, _sid = await self._make_sub("subscribe", pattern=pattern)
        return sub

    async def serve(self, subject: str) -> Subscription:
        sub, sid = await self._make_sub("serve", subject=subject)
        self._served[subject] = sid
        return sub

    async def unserve(self, subject: str) -> None:
        sid = self._served.pop(subject, None)
        if sid is not None:
            self._conn._sub_reg.pop(sid, None)
        await self._conn.call("unserve", subject=subject)

    async def work_queue(self, name: str) -> WorkQueue:
        return _NetWorkQueue(self._conn, name)

    async def _safe_call(self, op: str, **kw) -> None:
        try:
            await self._conn.call(op, **kw)
        except Exception:
            pass

    async def close(self) -> None:
        await self._conn.close()
