"""TCP clients for the discovery/bus daemon (runtime/server.py): NetKvStore
implements the KvStore interface, NetBus the MessageBus interface, over the
daemon's length-prefixed JSON protocol.

These are the reference's etcd-client / async-nats analogs
(lib/runtime/src/transports/{etcd,nats}.rs): a single multiplexed connection
each, a demux reader matching ``rid`` replies and routing ``push`` frames
(watch events, bus messages) to their handles.
"""

from __future__ import annotations

import asyncio
import base64
import logging
from typing import Dict, List, Optional

from .bus import BusMessage, MessageBus, Subscription, WorkItem, WorkQueue
from .kvstore import (KvEntry, KvStore, Lease, PrefixWatcher, WatchEvent,
                      WatchEventType)
from .server import recv_msg, send_msg

logger = logging.getLogger("dynamo_tpu.runtime.netstore")


def _b64(b: bytes) -> str:
    return base64.b64encode(b).decode()


def _unb64(s: str) -> bytes:
    return base64.b64decode(s)


class _Conn:
    """One multiplexed daemon connection: request/reply + push routing."""

    def __init__(self, reader: asyncio.StreamReader,
                 writer: asyncio.StreamWriter):
        self.reader = reader
        self.writer = writer
        self._next_rid = 1
        self._pending: Dict[int, asyncio.Future] = {}
        self._push_watch: Dict[int, PrefixWatcher] = {}
        self._push_sub: Dict[int, Subscription] = {}
        self._reader_task: Optional[asyncio.Task] = None
        self._write_lock = asyncio.Lock()
        self.closed = False

    @classmethod
    async def open(cls, addr: str, timeout: float = 10.0) -> "_Conn":
        host, port = addr.rsplit(":", 1)
        reader, writer = await asyncio.wait_for(
            asyncio.open_connection(host, int(port)), timeout)
        conn = cls(reader, writer)
        conn._reader_task = asyncio.get_running_loop().create_task(
            conn._read_loop(), name="netstore-demux")
        return conn

    async def _read_loop(self) -> None:
        try:
            while True:
                msg = await recv_msg(self.reader)
                if msg is None:
                    break
                if "push" in msg:
                    self._route_push(msg)
                    continue
                fut = self._pending.pop(msg.get("rid"), None)
                if fut is not None and not fut.done():
                    fut.set_result(msg)
        except (ConnectionError, ValueError):
            pass
        finally:
            self.closed = True
            for fut in self._pending.values():
                if not fut.done():
                    fut.set_exception(ConnectionError("daemon connection lost"))
            self._pending.clear()

    def _route_push(self, msg: dict) -> None:
        if msg["push"] == "watch":
            w = self._push_watch.get(msg["wid"])
            if w is not None:
                typ = (WatchEventType.PUT if msg["type"] == "put"
                       else WatchEventType.DELETE)
                w._push(WatchEvent(typ, KvEntry(
                    msg["key"], _unb64(msg["value"]), msg.get("lease", 0))))
        elif msg["push"] == "msg":
            s = self._push_sub.get(msg["sid"])
            if s is not None:
                s._push(BusMessage(msg["subject"], _unb64(msg["payload"])))

    async def call(self, op: str, **kwargs) -> dict:
        if self.closed:
            raise ConnectionError("daemon connection lost")
        rid = self._next_rid
        self._next_rid += 1
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[rid] = fut
        async with self._write_lock:
            await send_msg(self.writer, {"rid": rid, "op": op, **kwargs})
        reply = await fut
        if not reply.get("ok"):
            raise RuntimeError(reply.get("error", f"{op} failed"))
        return reply

    async def close(self) -> None:
        self.closed = True
        if self._reader_task is not None:
            self._reader_task.cancel()
        if not self.writer.is_closing():
            self.writer.close()


class NetKvStore(KvStore):
    def __init__(self, conn: _Conn):
        self._conn = conn

    @classmethod
    async def connect(cls, addr: str) -> "NetKvStore":
        return cls(await _Conn.open(addr))

    async def kv_create(self, key: str, value: bytes, lease_id: int = 0) -> bool:
        r = await self._conn.call("kv_create", key=key, value=_b64(value),
                                  lease=lease_id)
        return bool(r["result"])

    async def kv_create_or_validate(self, key: str, value: bytes,
                                    lease_id: int = 0) -> bool:
        r = await self._conn.call("kv_create_or_validate", key=key,
                                  value=_b64(value), lease=lease_id)
        return bool(r["result"])

    async def kv_put(self, key: str, value: bytes, lease_id: int = 0) -> None:
        await self._conn.call("kv_put", key=key, value=_b64(value),
                              lease=lease_id)

    async def kv_get(self, key: str) -> Optional[KvEntry]:
        r = await self._conn.call("kv_get", key=key)
        e = r.get("entry")
        if e is None:
            return None
        return KvEntry(e["key"], _unb64(e["value"]), e.get("lease", 0))

    async def kv_get_prefix(self, prefix: str) -> List[KvEntry]:
        r = await self._conn.call("kv_get_prefix", prefix=prefix)
        return [KvEntry(e["key"], _unb64(e["value"]), e.get("lease", 0))
                for e in r["entries"]]

    async def kv_delete(self, key: str) -> bool:
        r = await self._conn.call("kv_delete", key=key)
        return bool(r["result"])

    async def watch_prefix(self, prefix: str) -> PrefixWatcher:
        # client-allocated handle, registered BEFORE the call so pushes that
        # race the reply are never dropped
        wid = self._conn._next_rid + 1_000_000

        def unsub(_w: PrefixWatcher) -> None:
            self._conn._push_watch.pop(wid, None)
            if not self._conn.closed:
                asyncio.get_running_loop().create_task(
                    self._safe_call("watch_close", wid=wid))

        w = PrefixWatcher(prefix, [], unsub)
        self._conn._push_watch[wid] = w
        try:
            await self._conn.call("watch_prefix", prefix=prefix, wid=wid)
        except Exception:
            self._conn._push_watch.pop(wid, None)
            raise
        return w

    async def _safe_call(self, op: str, **kw) -> None:
        try:
            await self._conn.call(op, **kw)
        except Exception:
            pass

    async def lease_create(self, ttl: float) -> Lease:
        r = await self._conn.call("lease_create", ttl=ttl)
        return Lease(self, r["lease_id"], ttl)

    async def lease_refresh(self, lease_id: int) -> bool:
        r = await self._conn.call("lease_refresh", lease_id=lease_id)
        return bool(r["result"])

    async def lease_revoke(self, lease_id: int) -> None:
        await self._conn.call("lease_revoke", lease_id=lease_id)

    async def close(self) -> None:
        await self._conn.close()


class _NetWorkQueue(WorkQueue):
    def __init__(self, conn: _Conn, name: str):
        self._conn = conn
        self.name = name

    async def enqueue(self, payload: bytes) -> int:
        r = await self._conn.call("wq_enqueue", queue=self.name,
                                  payload=_b64(payload))
        return r["id"]

    async def dequeue(self, timeout: Optional[float] = None,
                      ack_deadline: float = 30.0) -> Optional[WorkItem]:
        r = await self._conn.call("wq_dequeue", queue=self.name,
                                  timeout=timeout, ack_deadline=ack_deadline)
        item = r.get("item")
        if item is None:
            return None
        return WorkItem(item["id"], _unb64(item["payload"]),
                        item.get("deliveries", 1))

    async def ack(self, item_id: int) -> None:
        await self._conn.call("wq_ack", queue=self.name, id=item_id)

    async def nack(self, item_id: int) -> None:
        await self._conn.call("wq_nack", queue=self.name, id=item_id)

    async def depth(self) -> int:
        r = await self._conn.call("wq_depth", queue=self.name)
        return r["depth"]


class NetBus(MessageBus):
    def __init__(self, conn: _Conn):
        self._conn = conn
        self._served: Dict[str, int] = {}

    @classmethod
    async def connect(cls, addr: str) -> "NetBus":
        return cls(await _Conn.open(addr))

    async def publish(self, subject: str, payload: bytes) -> None:
        await self._conn.call("publish", subject=subject, payload=_b64(payload))

    async def _make_sub(self, op: str, **kw) -> Subscription:
        sid = self._conn._next_rid + 2_000_000  # client-allocated (see watch)

        def unsub(_s: Subscription) -> None:
            self._conn._push_sub.pop(sid, None)
            if not self._conn.closed:
                asyncio.get_running_loop().create_task(
                    self._safe_call("sub_close", sid=sid))

        sub = Subscription(kw.get("pattern") or kw.get("subject", ""), unsub)
        self._conn._push_sub[sid] = sub
        try:
            await self._conn.call(op, sid=sid, **kw)
        except Exception:
            self._conn._push_sub.pop(sid, None)
            raise
        return sub, sid

    async def subscribe(self, pattern: str) -> Subscription:
        sub, _sid = await self._make_sub("subscribe", pattern=pattern)
        return sub

    async def serve(self, subject: str) -> Subscription:
        sub, sid = await self._make_sub("serve", subject=subject)
        self._served[subject] = sid
        return sub

    async def unserve(self, subject: str) -> None:
        self._served.pop(subject, None)
        await self._conn.call("unserve", subject=subject)

    async def work_queue(self, name: str) -> WorkQueue:
        return _NetWorkQueue(self._conn, name)

    async def _safe_call(self, op: str, **kw) -> None:
        try:
            await self._conn.call(op, **kw)
        except Exception:
            pass

    async def close(self) -> None:
        await self._conn.close()
