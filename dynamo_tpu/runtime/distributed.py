"""Distributed runtime: Namespace → Component → Endpoint naming, discovery
via the KV store, a bus request plane, and a TCP response plane.

This is the TPU build's analog of the reference's ``DistributedRuntime``
(lib/runtime/src/distributed.rs) and component model
(lib/runtime/src/component.rs, component/{endpoint,client,service}.rs):

- Every process owns one ``DistributedRuntime``: a KV store client (etcd
  analog), a message-bus client (NATS analog), and a lazily-started TCP
  stream server for the response plane.
- Serving an endpoint = claim bus subject ``{ns}|{comp}.{ep}-{lease:x}`` and
  write discovery key ``{ns}/components/{comp}/{ep}:{lease:x}`` under the
  process's primary lease (component/endpoint.rs:110-137). Lease expiry
  deletes the key → clients drop the instance (SURVEY.md §5.3).
- Calling an endpoint = watch the discovery prefix for live instances,
  pick one (random / round-robin / direct, component/client.rs:181-244),
  register a local response stream, push the two-part request over the bus,
  and await the worker's TCP dial-back (egress/push.rs:88-156).

Requests/responses are serialized with pluggable serde callables so the LLM
protocol layer (dataclasses) and tests (plain dicts) share the same plane.
"""

from __future__ import annotations

import asyncio
import collections
import dataclasses
import itertools
import json
import logging
import os
import random
import time
import uuid
from typing import Any, AsyncIterator, Callable, Dict, List, Optional

from .bus import MemoryBus, MessageBus
from .codec import (ConnectionInfo, ControlMessage, Frame, FrameKind,
                    RequestControlMessage, decode_two_part, encode_two_part)
from .engine import AsyncEngine, Context, ManyOut, ResponseStream, SingleIn
from .kvstore import (KvStore, Lease, MemoryKvStore, WatchEventType)
from .tcp import StreamSender, TcpStreamServer, open_stream_sender

logger = logging.getLogger("dynamo_tpu.runtime.distributed")

__all__ = [
    "DistributedRuntime",
    "Namespace",
    "Component",
    "Endpoint",
    "EndpointServer",
    "Client",
    "json_serde",
]


def _default_encode(obj: Any) -> bytes:
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        obj = dataclasses.asdict(obj)
    elif hasattr(obj, "to_dict"):
        obj = obj.to_dict()
    return json.dumps(obj).encode()


def json_serde(cls: Optional[type] = None):
    """(encode, decode) pair: dataclass/dict → JSON bytes and back.
    ``cls`` may define ``from_dict`` or be a dataclass for typed decode."""

    def decode(raw: bytes) -> Any:
        d = json.loads(raw)
        if cls is None:
            return d
        if hasattr(cls, "from_dict"):
            return cls.from_dict(d)
        if dataclasses.is_dataclass(cls):
            return cls(**d)
        return d

    return _default_encode, decode


@dataclasses.dataclass
class ComponentEndpointInfo:
    """Discovery record one serving endpoint writes.
    Reference: ``ComponentEndpointInfo`` (component.rs:90-97)."""

    subject: str
    worker_id: int
    component: str
    endpoint: str
    namespace: str

    def to_json(self) -> bytes:
        return json.dumps(dataclasses.asdict(self)).encode()

    @classmethod
    def from_json(cls, raw: bytes) -> "ComponentEndpointInfo":
        return cls(**json.loads(raw))


class DistributedRuntime:
    """One per process. Owns transports + the primary lease."""

    # etcd-style liveness TTL; generous enough that long XLA compiles on the
    # same event loop can't starve the keepalive (refresh runs every TTL/3)
    LEASE_TTL = float(os.environ.get("DYN_LEASE_TTL", "10.0"))

    def __init__(self, store: KvStore, bus: MessageBus,
                 tcp_host: str = "127.0.0.1",
                 advertise: Optional[str] = None):
        self.store = store
        self.bus = bus
        self.tcp = TcpStreamServer(tcp_host, advertise)
        self.worker_uuid = uuid.uuid4().hex
        self._primary_lease: Optional[Lease] = None
        self._servers: List["EndpointServer"] = []
        self.on_lease_lost: Optional[Callable[[], None]] = None
        self._closed = False

    @classmethod
    def in_process(cls) -> "DistributedRuntime":
        """Single-process runtime: memory store + bus (the test/devel mode;
        also what a one-host aggregated deployment uses)."""
        return cls(MemoryKvStore(), MemoryBus())

    @classmethod
    async def connect(cls, server_addr: str,
                      advertise: Optional[str] = None) -> "DistributedRuntime":
        """Multi-process runtime: TCP clients to the discovery/bus daemon
        (runtime/server.py)."""
        from .netstore import NetBus, NetKvStore
        store = await NetKvStore.connect(server_addr)
        bus = await NetBus.connect(server_addr)
        return cls(store, bus, advertise=advertise)

    async def primary_lease(self) -> Lease:
        if self._primary_lease is None:
            lease = await self.store.lease_create(self.LEASE_TTL)
            lease.on_lost = self._lease_lost
            lease.start_keepalive()
            self._primary_lease = lease
        return self._primary_lease

    def _lease_lost(self) -> None:
        logger.error("primary lease lost — shutting down runtime")
        if self.on_lease_lost is not None:
            self.on_lease_lost()

    @property
    def worker_id(self) -> int:
        """Numeric instance id = primary lease id (the reference uses the
        etcd lease id as the instance identity everywhere)."""
        if self._primary_lease is None:
            raise RuntimeError("no primary lease yet (serve an endpoint first)")
        return self._primary_lease.id

    def namespace(self, name: str) -> "Namespace":
        return Namespace(self, name)

    async def shutdown(self) -> None:
        if self._closed:
            return
        self._closed = True
        for srv in list(self._servers):
            await srv.stop()
        if self._primary_lease is not None:
            await self._primary_lease.revoke()
            self._primary_lease = None
        await self.tcp.close()
        await self.bus.close()
        await self.store.close()


@dataclasses.dataclass
class Namespace:
    runtime: DistributedRuntime
    name: str

    def component(self, name: str) -> "Component":
        return Component(self.runtime, self.name, name)

    # -- event plane (reference traits/events.rs: namespace-scoped pub/sub)
    def event_subject(self, topic: str) -> str:
        return f"evt.{self.name}.{topic}"

    async def publish_event(self, topic: str, payload: Any) -> None:
        await self.runtime.bus.publish(self.event_subject(topic),
                                       _default_encode(payload))

    async def subscribe_event(self, topic: str):
        return await self.runtime.bus.subscribe(self.event_subject(topic))


@dataclasses.dataclass
class Component:
    runtime: DistributedRuntime
    namespace: str
    name: str

    def endpoint(self, name: str) -> "Endpoint":
        return Endpoint(self.runtime, self.namespace, self.name, name)

    def event_subject(self, topic: str) -> str:
        return f"evt.{self.namespace}.{self.name}.{topic}"

    async def publish_event(self, topic: str, payload: Any) -> None:
        await self.runtime.bus.publish(self.event_subject(topic),
                                       _default_encode(payload))

    async def subscribe_event(self, topic: str):
        return await self.runtime.bus.subscribe(self.event_subject(topic))


@dataclasses.dataclass
class Endpoint:
    runtime: DistributedRuntime
    namespace: str
    component: str
    name: str

    def parent_component(self) -> Component:
        return Component(self.runtime, self.namespace, self.component)

    # naming (reference component.rs:246-257 / component/endpoint.rs:110-137)
    def discovery_prefix(self) -> str:
        return f"{self.namespace}/components/{self.component}/{self.name}:"

    def discovery_key(self, lease_id: int) -> str:
        return f"{self.discovery_prefix()}{lease_id:x}"

    def subject(self, lease_id: int) -> str:
        return f"{self.namespace}|{self.component}.{self.name}-{lease_id:x}"

    def stats_key(self, lease_id: int) -> str:
        return (f"{self.namespace}/stats/{self.component}/"
                f"{self.name}:{lease_id:x}")

    @property
    def path(self) -> str:
        return f"dyn://{self.namespace}/{self.component}/{self.name}"

    def __post_init__(self) -> None:
        # structure characters (| . - : /) in names would corrupt subjects
        # and discovery keys (reference slug.rs; component.rs:323-339 TODO)
        from .slug import validate_name
        validate_name(self.namespace, "namespace")
        validate_name(self.component, "component")
        validate_name(self.name, "endpoint")

    @classmethod
    def parse_path(cls, runtime: DistributedRuntime, path: str) -> "Endpoint":
        """Parse ``dyn://ns/comp/ep`` or ``ns.comp.ep`` (reference
        protocols.rs:33-200)."""
        p = path
        if p.startswith("dyn://"):
            p = p[len("dyn://"):]
        parts = p.replace(".", "/").split("/")
        if len(parts) != 3 or not all(parts):
            raise ValueError(f"invalid endpoint path: {path!r}")
        return cls(runtime, *parts)

    async def serve(self, engine: AsyncEngine,
                    decode_req: Optional[Callable[[bytes], Any]] = None,
                    encode_resp: Optional[Callable[[Any], bytes]] = None,
                    stats_handler: Optional[Callable[[], Any]] = None,
                    stats_interval: float = 1.0) -> "EndpointServer":
        """Register + start serving. Returns the running server handle."""
        server = EndpointServer(self, engine,
                                decode_req or json_serde()[1],
                                encode_resp or _default_encode,
                                stats_handler, stats_interval)
        await server.start()
        self.runtime._servers.append(server)
        return server

    def client(self, decode_resp: Optional[Callable[[bytes], Any]] = None,
               encode_req: Optional[Callable[[Any], bytes]] = None) -> "Client":
        return Client(self, encode_req or _default_encode,
                      decode_resp or json_serde()[1])


class EndpointServer:
    """Serving side: bus inbox loop → engine → TCP dial-back stream.
    Reference: ``PushEndpoint`` (ingress/push_endpoint.rs:36-84) +
    ``Ingress`` (network.rs:51-325)."""

    def __init__(self, endpoint: Endpoint, engine: AsyncEngine,
                 decode_req: Callable[[bytes], Any],
                 encode_resp: Callable[[Any], bytes],
                 stats_handler: Optional[Callable[[], Any]] = None,
                 stats_interval: float = 1.0):
        self.endpoint = endpoint
        self.engine = engine
        self.decode_req = decode_req
        self.encode_resp = encode_resp
        self.stats_handler = stats_handler
        self.stats_interval = stats_interval
        self.lease: Optional[Lease] = None
        self._inbox = None
        self._loop_task: Optional[asyncio.Task] = None
        self._stats_task: Optional[asyncio.Task] = None
        self._inflight: set = set()
        self._stopping = False
        # fire-and-forget dedup window (ADVICE r2): the client's dispatch
        # retry is at-least-once; for streaming requests duplicates are
        # harmless (the client consumes only the last dialed-back stream),
        # but a request WITHOUT connection info has no stream to
        # disambiguate and real side effects — drop repeats of its id.
        self._recent_ff_ids: "collections.OrderedDict[str, float]" = \
            collections.OrderedDict()

    RECENT_ID_WINDOW = 60.0
    RECENT_ID_MAX = 4096

    def _ff_duplicate(self, rid: str) -> bool:
        """Record rid; True if it was already accepted inside the window."""
        now = time.monotonic()
        while self._recent_ff_ids:     # expire by age BEFORE the check, so
            oldest_id, t = next(iter(self._recent_ff_ids.items()))
            if now - t <= self.RECENT_ID_WINDOW:
                break
            del self._recent_ff_ids[oldest_id]
        if rid in self._recent_ff_ids:
            return True
        self._recent_ff_ids[rid] = now
        while len(self._recent_ff_ids) > self.RECENT_ID_MAX:
            # capacity-evict AFTER inserting — evicting first could evict
            # rid's own prior entry and accept the duplicate as new
            self._recent_ff_ids.popitem(last=False)
        return False

    def _ff_forget(self, rid: str) -> None:
        """The request did NOT execute — let a redelivery run it (recording
        at accept time and forgetting on failure keeps concurrent in-flight
        duplicates deduped without turning transient failures into drops)."""
        self._recent_ff_ids.pop(rid, None)

    @property
    def lease_id(self) -> int:
        assert self.lease is not None
        return self.lease.id

    async def start(self) -> None:
        rt = self.endpoint.runtime
        await rt.tcp.start()
        self.lease = await rt.primary_lease()
        subject = self.endpoint.subject(self.lease.id)
        self._inbox = await rt.bus.serve(subject)
        info = ComponentEndpointInfo(
            subject=subject, worker_id=self.lease.id,
            component=self.endpoint.component, endpoint=self.endpoint.name,
            namespace=self.endpoint.namespace)
        created = await rt.store.kv_create(
            self.endpoint.discovery_key(self.lease.id), info.to_json(),
            lease_id=self.lease.id)
        if not created:
            raise RuntimeError(
                f"endpoint already registered: {self.endpoint.path}")
        self._loop_task = asyncio.get_running_loop().create_task(
            self._serve_loop(), name=f"endpoint-{self.endpoint.name}")
        if self.stats_handler is not None:
            self._stats_task = asyncio.get_running_loop().create_task(
                self._stats_loop(), name=f"stats-{self.endpoint.name}")
        logger.info("serving %s as instance %x", self.endpoint.path,
                    self.lease.id)

    async def _serve_loop(self) -> None:
        while not self._stopping:
            msg = await self._inbox.next(timeout=0.5)
            if msg is None:
                continue
            task = asyncio.get_running_loop().create_task(
                self._handle(msg.payload))
            self._inflight.add(task)
            task.add_done_callback(self._inflight.discard)

    async def _handle(self, payload: bytes) -> None:
        try:
            ctrl, body = decode_two_part(payload)
        except Exception:
            logger.exception("undecodable request envelope")
            return
        info = ctrl.connection_info
        if info is None and self._ff_duplicate(ctrl.id):
            logger.warning("dropping duplicate fire-and-forget request %s "
                           "(at-least-once re-dispatch)", ctrl.id)
            return
        sender: Optional[StreamSender] = None
        try:
            request = self.decode_req(body)
        except Exception as e:
            if info is not None:
                sender = await open_stream_sender(info, error=str(e))
                await sender.finish()
            else:
                self._ff_forget(ctrl.id)
            return
        from .engine import EngineContext
        from .tracing import Trace, span, use_trace
        ctx = Context(request, ctx=EngineContext(ctrl.id))
        # worker-side trace under the SAME request id the frontend logged
        # (ingress prologue → engine → first frame → stream end)
        with use_trace(Trace(ctrl.id, role="worker")) as trace:
            with span("engine.accept"):
                try:
                    stream = await self.engine.generate(ctx)
                except Exception as e:
                    logger.exception("engine rejected request %s", ctrl.id)
                    if info is not None:
                        sender = await open_stream_sender(info, error=str(e))
                        await sender.finish()
                    else:
                        self._ff_forget(ctrl.id)
                    return
            if info is None:
                try:
                    async for _ in stream:   # fire-and-forget request type
                        pass
                except Exception:
                    self._ff_forget(ctrl.id)
                    raise
                return
            with span("dial_back"):
                sender = await open_stream_sender(info)
            sender.on_stop = ctx.ctx.stop_generating
            sender.on_kill = ctx.ctx.kill
            try:
                with span("respond") as resp_span:
                    first = True
                    async for item in stream:
                        if sender.killed:
                            break
                        await sender.send(self.encode_resp(item))
                        if first:
                            first = False
                            trace.event("first_response")
                    await sender.finish()
            except (ConnectionError, OSError):
                ctx.ctx.kill()
            except Exception as e:
                logger.exception("stream failed for %s", ctrl.id)
                await sender.finish(error=str(e))

    async def _stats_loop(self) -> None:
        rt = self.endpoint.runtime
        key = self.endpoint.stats_key(self.lease.id)
        while not self._stopping:
            try:
                data = self.stats_handler()
                await rt.store.kv_put(key, _default_encode(data),
                                      lease_id=self.lease.id)
            except Exception:
                logger.exception("stats publish failed")
            await asyncio.sleep(self.stats_interval)

    async def stop(self) -> None:
        self._stopping = True
        rt = self.endpoint.runtime
        if self._loop_task is not None:
            self._loop_task.cancel()
        if self._stats_task is not None:
            self._stats_task.cancel()
        for t in list(self._inflight):
            t.cancel()
        if self.lease is not None:
            # best-effort, bounded deregistration: if the daemon is gone,
            # lease expiry cleans these up anyway — shutdown must never
            # hang in the netstore reconnect window
            try:
                async with asyncio.timeout(2.0):
                    await rt.bus.unserve(
                        self.endpoint.subject(self.lease.id))
                    await rt.store.kv_delete(
                        self.endpoint.discovery_key(self.lease.id))
                    if self._stats_task is not None:
                        await rt.store.kv_delete(
                            self.endpoint.stats_key(self.lease.id))
            except (TimeoutError, ConnectionError, OSError):
                logger.warning("endpoint %s deregistration skipped (daemon "
                               "unreachable); lease expiry will clean up",
                               self.endpoint.path)
        if self in rt._servers:
            rt._servers.remove(self)


class _RemoteStream(ResponseStream):
    """Client-side view of a worker's TCP response stream; forwards
    stop/kill from the local context as upstream control frames."""

    def __init__(self, ctx, rx, decode_resp, server: TcpStreamServer):
        self._rx = rx
        self._decode = decode_resp
        self._server = server
        self._ctx = ctx
        super().__init__(self._gen(), ctx)

    def _gen(self) -> AsyncIterator[Any]:
        async def gen():
            try:
                while True:
                    if self._ctx.is_killed:
                        await self._rx.send_control(ControlMessage.kill())
                        return
                    if self._ctx.is_stopped:
                        await self._rx.send_control(ControlMessage.stop())
                    f = await self._rx.next_frame(timeout=0.5)
                    if f is None:
                        continue
                    if f.kind == FrameKind.DATA:
                        yield self._decode(f.data)
                    elif f.kind == FrameKind.SENTINEL:
                        return
                    elif f.kind == FrameKind.ERROR:
                        err = f.header_json().get("error", "stream error")
                        raise RuntimeError(f"remote stream error: {err}")
            finally:
                self._rx.close()
                self._server.unregister(self._rx.stream_id)
        return gen()


class Client(AsyncEngine):
    """Watches discovery, routes requests. Reference ``Client<T,U>``
    (component/client.rs:52-256); default routing is random, like the
    reference's AsyncEngine impl for Client."""

    def __init__(self, endpoint: Endpoint,
                 encode_req: Callable[[Any], bytes],
                 decode_resp: Callable[[bytes], Any]):
        self.endpoint = endpoint
        self.encode_req = encode_req
        self.decode_resp = decode_resp
        self.instances: Dict[int, ComponentEndpointInfo] = {}
        self._watcher = None
        self._watch_task: Optional[asyncio.Task] = None
        self._rr = itertools.count()
        self._instances_event = asyncio.Event()
        self.on_instances_changed: Optional[Callable[[set], None]] = None

    async def start(self) -> "Client":
        rt = self.endpoint.runtime
        await rt.tcp.start()
        self._watcher = await rt.store.watch_prefix(
            self.endpoint.discovery_prefix())
        self._watch_task = asyncio.get_running_loop().create_task(
            self._watch_loop(), name=f"client-watch-{self.endpoint.name}")
        return self

    async def _watch_loop(self) -> None:
        async for ev in self._watcher:
            key = ev.entry.key
            lease_hex = key.rsplit(":", 1)[-1]
            try:
                lease_id = int(lease_hex, 16)
            except ValueError:
                continue
            if ev.type == WatchEventType.PUT:
                try:
                    self.instances[lease_id] = ComponentEndpointInfo.from_json(
                        ev.entry.value)
                except Exception:
                    continue
            else:
                self.instances.pop(lease_id, None)
            self._instances_event.set()
            if self.on_instances_changed is not None:
                self.on_instances_changed(set(self.instances))

    def instance_ids(self) -> List[int]:
        return sorted(self.instances)

    async def wait_for_instances(self, timeout: float = 30.0) -> List[int]:
        deadline = asyncio.get_running_loop().time() + timeout
        while not self.instances:
            remaining = deadline - asyncio.get_running_loop().time()
            if remaining <= 0:
                raise TimeoutError(
                    f"no instances for {self.endpoint.path} after {timeout}s")
            self._instances_event.clear()
            try:
                await asyncio.wait_for(self._instances_event.wait(),
                                       min(remaining, 1.0))
            except asyncio.TimeoutError:
                pass
        return self.instance_ids()

    # --------------------------------------------------------------- routes
    async def generate(self, request: SingleIn) -> ManyOut:
        return await self.random(request)

    async def random(self, request: SingleIn) -> ManyOut:
        ids = self.instance_ids()
        if not ids:
            raise RuntimeError(f"no instances for {self.endpoint.path}")
        return await self.direct(request, random.choice(ids))

    async def round_robin(self, request: SingleIn) -> ManyOut:
        ids = self.instance_ids()
        if not ids:
            raise RuntimeError(f"no instances for {self.endpoint.path}")
        return await self.direct(request, ids[next(self._rr) % len(ids)])

    async def direct(self, request: SingleIn, instance_id: int) -> ManyOut:
        """The push-router send path (egress/push.rs:88-156): register a
        response stream, publish the two-part request, await dial-back."""
        info = self.instances.get(instance_id)
        if info is None:
            raise RuntimeError(
                f"unknown instance {instance_id:x} for {self.endpoint.path}")
        rt = self.endpoint.runtime
        ctx = request if isinstance(request, Context) else Context(request)
        rx = rt.tcp.register()
        try:
            # egress span (reference egress/push.rs:134-151): publish +
            # dial-back wait, tagged with the target instance
            from .tracing import span as _span
            with _span("egress", instance=f"{instance_id:x}",
                       path=self.endpoint.path):
                rx, prologue = await self._dispatch_with_retry(
                    rt, rx, ctx, info, instance_id)
        except Exception:
            rt.tcp.unregister(rx.stream_id)
            raise
        if prologue.error is not None:
            rt.tcp.unregister(rx.stream_id)
            raise RuntimeError(f"remote rejected request: {prologue.error}")
        return _RemoteStream(ctx.ctx, rx, self.decode_resp, rt.tcp)

    DIAL_BACK_TIMEOUT = 10.0
    DISPATCH_ATTEMPTS = 3

    async def _dispatch_with_retry(self, rt, rx, ctx, info, instance_id):
        """Publish the two-part request and await the worker's dial-back,
        retrying the failure modes a daemon restart creates:

        - publish reaches ZERO receivers (the worker's serve subscription
          is mid-re-establishment) — NATS "no responders" semantics;
        - publish reached a receiver that died before dialing back (the
          message sat in a killed session's queue) — dial-back timeout,
          re-dispatch on a fresh stream.

        Re-dispatch is at-least-once: a slow-but-alive worker could end up
        serving the request twice, with the client consuming only the last
        stream — the same contract as the reference's NATS request plane."""
        loop = asyncio.get_running_loop()
        last_err: Exception = RuntimeError("dispatch failed")
        for attempt in range(self.DISPATCH_ATTEMPTS):
            conn = rt.tcp.connection_info(rx)
            ctrl = RequestControlMessage(id=ctx.id, connection_info=conn)
            payload = encode_two_part(ctrl, self.encode_req(ctx.data))
            deadline = loop.time() + self.DIAL_BACK_TIMEOUT
            delay = 0.05
            try:
                while True:   # no-responders backoff within this attempt
                    n = await rt.bus.publish(info.subject, payload)
                    if n is None or n > 0:  # None: bus without counts
                        break
                    if loop.time() >= deadline:
                        raise RuntimeError(
                            f"no responders on {info.subject} "
                            f"(instance {instance_id:x})")
                    await asyncio.sleep(delay)
                    delay = min(delay * 2, 0.5)
                prologue = await rx.wait_connected(
                    timeout=max(deadline - loop.time(), 1.0))
                return rx, prologue
            except (TimeoutError, asyncio.TimeoutError, RuntimeError) as e:
                last_err = e
                if attempt + 1 >= self.DISPATCH_ATTEMPTS:
                    # the caller's cleanup unregisters ITS original rx —
                    # the retry streams registered here must not leak
                    # (unregister is idempotent, double-pop is fine)
                    rt.tcp.unregister(rx.stream_id)
                    raise
                logger.warning(
                    "dispatch to %s attempt %d failed (%s); retrying on a "
                    "fresh stream", self.endpoint.path, attempt + 1, e)
                rt.tcp.unregister(rx.stream_id)
                rx = rt.tcp.register()
        raise last_err

    # -------------------------------------------------------------- scrape
    async def collect_stats(self) -> Dict[int, Any]:
        """Scrape per-instance stats records (reference ServiceClient
        ``collect_services`` via NATS $SRV.STATS; ours ride the KV store —
        same data, discovery-backed transport)."""
        rt = self.endpoint.runtime
        prefix = (f"{self.endpoint.namespace}/stats/"
                  f"{self.endpoint.component}/{self.endpoint.name}:")
        out: Dict[int, Any] = {}
        for e in await rt.store.kv_get_prefix(prefix):
            try:
                out[int(e.key.rsplit(":", 1)[-1], 16)] = json.loads(e.value)
            except Exception:
                continue
        return out

    async def close(self) -> None:
        if self._watch_task is not None:
            self._watch_task.cancel()
        if self._watcher is not None:
            self._watcher.close()
