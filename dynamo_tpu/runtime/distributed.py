"""Distributed runtime: Namespace → Component → Endpoint naming, discovery
via the KV store, a bus request plane, and a TCP response plane.

This is the TPU build's analog of the reference's ``DistributedRuntime``
(lib/runtime/src/distributed.rs) and component model
(lib/runtime/src/component.rs, component/{endpoint,client,service}.rs):

- Every process owns one ``DistributedRuntime``: a KV store client (etcd
  analog), a message-bus client (NATS analog), and a lazily-started TCP
  stream server for the response plane.
- Serving an endpoint = claim bus subject ``{ns}|{comp}.{ep}-{lease:x}`` and
  write discovery key ``{ns}/components/{comp}/{ep}:{lease:x}`` under the
  process's primary lease (component/endpoint.rs:110-137). Lease expiry
  deletes the key → clients drop the instance (SURVEY.md §5.3).
- Calling an endpoint = watch the discovery prefix for live instances,
  pick one (random / round-robin / direct, component/client.rs:181-244),
  register a local response stream, push the two-part request over the bus,
  and await the worker's TCP dial-back (egress/push.rs:88-156).

Requests/responses are serialized with pluggable serde callables so the LLM
protocol layer (dataclasses) and tests (plain dicts) share the same plane.

Module layout (round 3 — split mirroring the reference's component/*.rs):
naming + discovery records in :mod:`.component`, the serving side in
:mod:`.ingress`, the calling side in :mod:`.egress`. This module holds the
per-process runtime and re-exports the public surface, so existing imports
keep working.
"""

from __future__ import annotations

import asyncio
import logging
import os
import uuid
from typing import Callable, List, Optional

from .bus import MemoryBus, MessageBus
from .component import (Component, ComponentEndpointInfo, Endpoint,
                        Namespace, json_serde)
from .egress import Client
from .ingress import EndpointServer
from .kvstore import KvStore, Lease, MemoryKvStore
from .tcp import TcpStreamServer

logger = logging.getLogger("dynamo_tpu.runtime.distributed")

__all__ = [
    "DistributedRuntime",
    "Namespace",
    "Component",
    "Endpoint",
    "EndpointServer",
    "Client",
    "ComponentEndpointInfo",
    "json_serde",
]


class DistributedRuntime:
    """One per process. Owns transports + the primary lease."""

    # etcd-style liveness TTL; generous enough that long XLA compiles on the
    # same event loop can't starve the keepalive (refresh runs every TTL/3)
    LEASE_TTL = float(os.environ.get("DYN_LEASE_TTL", "10.0"))

    def __init__(self, store: KvStore, bus: MessageBus,
                 tcp_host: str = "127.0.0.1",
                 advertise: Optional[str] = None):
        self.store = store
        self.bus = bus
        self.tcp = TcpStreamServer(tcp_host, advertise)
        self.worker_uuid = uuid.uuid4().hex
        self._primary_lease: Optional[Lease] = None
        self._lease_lock = asyncio.Lock()
        self._servers: List[EndpointServer] = []
        self.on_lease_lost: Optional[Callable[[], None]] = None
        self._closed = False

    @classmethod
    def in_process(cls) -> "DistributedRuntime":
        """Single-process runtime: memory store + bus (the test/devel mode;
        also what a one-host aggregated deployment uses)."""
        return cls(MemoryKvStore(), MemoryBus())

    @classmethod
    async def connect(cls, server_addr: str,
                      advertise: Optional[str] = None) -> "DistributedRuntime":
        """Multi-process runtime: TCP clients to the discovery/bus daemon
        (runtime/server.py)."""
        from .netstore import NetBus, NetKvStore
        store = await NetKvStore.connect(server_addr)
        bus = await NetBus.connect(server_addr)
        return cls(store, bus, advertise=advertise)

    async def primary_lease(self) -> Lease:
        # double-checked lock (DL008): two concurrent first callers would
        # otherwise BOTH mint a lease — one becomes an orphan with a live
        # keepalive and the worker's identity is whichever won the write
        if self._primary_lease is None:
            async with self._lease_lock:
                if self._primary_lease is None:
                    lease = await self.store.lease_create(self.LEASE_TTL)
                    lease.on_lost = self._lease_lost
                    lease.start_keepalive()
                    self._primary_lease = lease
        return self._primary_lease

    def _lease_lost(self) -> None:
        logger.error("primary lease lost — shutting down runtime")
        if self.on_lease_lost is not None:
            self.on_lease_lost()

    @property
    def worker_id(self) -> int:
        """Numeric instance id = primary lease id (the reference uses the
        etcd lease id as the instance identity everywhere)."""
        if self._primary_lease is None:
            raise RuntimeError("no primary lease yet (serve an endpoint first)")
        return self._primary_lease.id

    def namespace(self, name: str) -> Namespace:
        return Namespace(self, name)

    async def shutdown(self) -> None:
        if self._closed:
            return
        self._closed = True
        for srv in list(self._servers):
            await srv.stop()
        lease, self._primary_lease = self._primary_lease, None
        if lease is not None:   # claimed before the await (DL008)
            await lease.revoke()
        await self.tcp.close()
        await self.bus.close()
        await self.store.close()
