"""NativeStreamSender: response-plane egress over the C++ data plane.

Same interface as tcp.StreamSender (connect / send / finish / on_stop /
on_kill / killed), but framing and socket writes happen on a dedicated C++
thread (csrc/data_plane.cpp) instead of the asyncio loop — per-token frame
sends become one lock-protected enqueue, and the worker's event loop never
blocks in drain(). STOP/KILL control frames from the receiver surface as
atomic flags; a lightweight asyncio task polls them into the same
``on_stop``/``on_kill`` callbacks the Python sender fires (step-granular
cancellation is the engine's contract anyway — reference
AsyncEngineContext, lib/runtime/src/engine.rs:47-168).
"""

from __future__ import annotations

import asyncio
import ctypes
import json
from typing import Callable, Optional

from ..utils import native
from .codec import ConnectionInfo, FrameKind

__all__ = ["NativeStreamSender", "load_data_plane_lib"]

_CTRL_STOP = 1
_CTRL_KILL = 2
_CTRL_PEER_CLOSED = 4
_HIGH_WATER = 8 * 1024 * 1024     # backpressure threshold (queued bytes)
_POLL_S = 0.02                    # control-flag poll cadence


def load_data_plane_lib() -> Optional[ctypes.CDLL]:
    lib = native.load("data_plane", ["data_plane.cpp"], ["-pthread"])
    if lib is None or getattr(lib, "_dp_ready", False):
        return lib
    u8p = ctypes.POINTER(ctypes.c_uint8)
    lib.dp_connect.restype = ctypes.c_int
    lib.dp_connect.argtypes = [ctypes.c_char_p, ctypes.c_int, ctypes.c_int]
    lib.dpsend_create.restype = ctypes.c_void_p
    lib.dpsend_create.argtypes = [ctypes.c_int]
    lib.dpsend_send.restype = ctypes.c_int
    lib.dpsend_send.argtypes = [ctypes.c_void_p, ctypes.c_uint8, u8p,
                                ctypes.c_int64, u8p, ctypes.c_int64]
    lib.dpsend_queued_bytes.restype = ctypes.c_int64
    lib.dpsend_queued_bytes.argtypes = [ctypes.c_void_p]
    lib.dpsend_flush.restype = ctypes.c_int
    lib.dpsend_flush.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.dpsend_ctrl.restype = ctypes.c_uint32
    lib.dpsend_ctrl.argtypes = [ctypes.c_void_p]
    lib.dpsend_error.restype = ctypes.c_int
    lib.dpsend_error.argtypes = [ctypes.c_void_p]
    lib.dpsend_abort.argtypes = [ctypes.c_void_p]
    lib.dpsend_close.argtypes = [ctypes.c_void_p]
    lib._dp_ready = True
    return lib


def _buf(b: bytes):
    return (ctypes.c_uint8 * len(b)).from_buffer_copy(b) if b else None


class NativeStreamSender:
    """Worker-side response stream over the native data plane."""

    def __init__(self, lib: ctypes.CDLL, handle: int):
        self._lib = lib
        self._h = handle
        self._poll_task: Optional[asyncio.Task] = None
        self._fired = 0
        self.on_stop: Optional[Callable[[], None]] = None
        self.on_kill: Optional[Callable[[], None]] = None
        self.killed = False

    @classmethod
    async def connect(cls, info: ConnectionInfo,
                      error: Optional[str] = None,
                      timeout: float = 10.0) -> "NativeStreamSender":
        # first call may g++-compile the data plane — off the loop
        # (memoized afterwards; tcp.open_stream_sender does the same)
        lib = await asyncio.to_thread(load_data_plane_lib)
        if lib is None:
            raise RuntimeError("native data plane unavailable")
        host, port = info.address.rsplit(":", 1)
        loop = asyncio.get_running_loop()
        fd = await loop.run_in_executor(
            None, lib.dp_connect, host.encode(), int(port),
            int(timeout * 1000))
        if fd < 0:
            raise ConnectionError(f"dp_connect {info.address}: errno {-fd}")
        sender = cls(lib, lib.dpsend_create(fd))
        hdr = json.dumps({"stream_id": info.stream_id,
                          "error": error}).encode()
        sender._raw_send(FrameKind.PROLOGUE, hdr, b"")
        sender._poll_task = loop.create_task(
            sender._poll_ctrl(), name=f"dp-ctl-{info.stream_id[:8]}")
        return sender

    def _raw_send(self, kind: FrameKind, header: bytes, data: bytes) -> None:
        rc = self._lib.dpsend_send(self._h, int(kind), _buf(header),
                                   len(header), _buf(data), len(data))
        if rc != 0:
            raise ConnectionError("native stream sender closed")

    def _check_ctrl(self) -> int:
        """Read the C++ control flags and fire callbacks exactly once."""
        flags = self._lib.dpsend_ctrl(self._h)
        if flags & _CTRL_KILL and not self._fired & _CTRL_KILL:
            self._fired |= _CTRL_KILL
            self.killed = True
            if self.on_kill is not None:
                self.on_kill()
        if flags & _CTRL_STOP and not self._fired & _CTRL_STOP:
            self._fired |= _CTRL_STOP
            if self.on_stop is not None:
                self.on_stop()
        return flags

    async def _poll_ctrl(self) -> None:
        while True:
            if self._check_ctrl() & _CTRL_PEER_CLOSED:
                return
            await asyncio.sleep(_POLL_S)

    async def send(self, data: bytes, header: bytes = b"") -> None:
        # synchronous flag check keeps kill observation at send granularity
        # (the Python sender's reader task fires before the next send; the
        # 20ms poll alone would lose that race and surface a spurious
        # ConnectionError instead of a cooperative stop)
        self._check_ctrl()
        if self.killed:
            return                     # dead stream: drop, like the fallback
        try:
            self._raw_send(FrameKind.DATA, header, data)
        except ConnectionError:
            self._check_ctrl()
            if self.killed:
                return
            raise
        # backpressure: yield until the C++ queue drains below the mark
        while (self._lib.dpsend_queued_bytes(self._h) > _HIGH_WATER
               and self._lib.dpsend_error(self._h) == 0):
            await asyncio.sleep(0.001)

    async def finish(self, error: Optional[str] = None) -> None:
        try:
            if error is not None:
                self._raw_send(FrameKind.ERROR,
                               json.dumps({"error": error}).encode(), b"")
            else:
                self._raw_send(FrameKind.SENTINEL, b"", b"")
        except ConnectionError:
            pass
        finally:
            loop = asyncio.get_running_loop()
            rc = await loop.run_in_executor(
                None, self._lib.dpsend_flush, self._h, 10_000)
            if rc != 0:
                self._lib.dpsend_abort(self._h)
            if self._poll_task is not None:
                self._poll_task.cancel()
            h, self._h = self._h, None
            await loop.run_in_executor(None, self._lib.dpsend_close, h)
