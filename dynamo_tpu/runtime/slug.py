"""Name sanitization for discovery keys and bus subjects.

Reference: lib/runtime/src/slug.rs:25-163 — canonical slugging so user
strings can't produce invalid NATS subjects / etcd keys (the reference's
component.rs:323-339 carries a TODO for char validation; the slug type is
its answer). Our subjects use ``|``/``.``/``-``/``:`` as structure
characters, so component parts must never contain them.
"""

from __future__ import annotations

import re

__all__ = ["slugify", "validate_name"]

_VALID = re.compile(r"^[A-Za-z0-9_-]+$")
_INVALID_CHARS = re.compile(r"[^A-Za-z0-9_-]+")


def slugify(text: str) -> str:
    """Canonical slug: lowercase, invalid runs → single ``-``, trimmed.
    ``slugify("Hello World/v2") == "hello-world-v2"``."""
    out = _INVALID_CHARS.sub("-", text.strip().lower()).strip("-")
    return out or "x"


def validate_name(name: str, what: str = "name") -> str:
    """Reject names that would corrupt subjects/keys instead of silently
    rewriting them (explicit beats implicit for addressing)."""
    if not _VALID.match(name or ""):
        raise ValueError(
            f"invalid {what} {name!r}: use [A-Za-z0-9_-] only "
            f"(try slugify() → {slugify(name or '')!r})")
    return name
