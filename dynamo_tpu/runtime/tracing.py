"""Per-request tracing spans across frontend → router → worker.

Reference: the request plane instruments ingress/egress with request-id
spans (lib/runtime/src/pipeline/network/egress/push.rs:134-151 — a
tracing span wrapping publish + dial-back, carrying the request id). The
TPU runtime's analog is dependency-free: a per-request :class:`Trace`
collects named spans with wall-clock durations, a process-global
:class:`Tracer` keeps a ring buffer of recent traces and emits one
structured log line per completed trace (request id + stage latencies),
and a contextvar propagates the current trace through the async call
chain so operators don't thread it explicitly.

Cross-process correlation is BY REQUEST ID: the control message already
carries it (codec.RequestControlMessage.id), so the worker side opens its
own trace under the same id and log aggregation joins the two — the same
scheme the reference uses (no span-context wire format).
"""

from __future__ import annotations

import contextlib
import contextvars
import dataclasses
import logging
import time
from collections import deque
from typing import Dict, List, Optional

logger = logging.getLogger("dynamo_tpu.trace")

__all__ = ["Span", "Trace", "Tracer", "tracer", "current_trace",
           "use_trace", "span"]


@dataclasses.dataclass
class Span:
    name: str
    start: float
    end: float = 0.0
    attrs: Dict[str, object] = dataclasses.field(default_factory=dict)

    @property
    def ms(self) -> float:
        return 1e3 * (self.end - self.start)


class Trace:
    """All spans of one request on one process ("role" tags which side)."""

    def __init__(self, request_id: str, role: str = ""):
        self.request_id = request_id
        self.role = role
        self.start = time.monotonic()
        self.finished: Optional[float] = None   # set by Tracer.finish
        self.spans: List[Span] = []

    @contextlib.contextmanager
    def span(self, name: str, **attrs):
        s = Span(name=name, start=time.monotonic(), attrs=attrs)
        self.spans.append(s)
        try:
            yield s
        finally:
            s.end = time.monotonic()

    def event(self, name: str, **attrs) -> None:
        """Zero-duration marker (e.g. first_token)."""
        t = time.monotonic()
        self.spans.append(Span(name=name, start=t, end=t, attrs=attrs))

    def to_dict(self) -> dict:
        end = self.finished if self.finished is not None else time.monotonic()
        return {
            "request_id": self.request_id,
            "role": self.role,
            "total_ms": round(1e3 * (end - self.start), 2),
            "spans": [{"name": s.name, "ms": round(s.ms, 2),
                       "at_ms": round(1e3 * (s.start - self.start), 2),
                       **({"attrs": s.attrs} if s.attrs else {})}
                      for s in self.spans],
        }


class Tracer:
    """Process-global registry: ring buffer + per-trace log line."""

    def __init__(self, keep: int = 256):
        self._recent: deque = deque(maxlen=keep)
        self.completed = 0

    def finish(self, trace: Trace) -> None:
        # store the Trace OBJECT and serialize lazily: code holding a
        # captured reference (e.g. the engine's stream_response) may append
        # events after use_trace exits, and those must still show up in
        # /traces (ADVICE r2). total_ms freezes here, not at read time.
        trace.finished = time.monotonic()
        self._recent.append(trace)
        self.completed += 1
        d = trace.to_dict()
        logger.info("trace %s [%s] %.1fms: %s", trace.request_id,
                    trace.role, d["total_ms"],
                    " ".join(f"{s['name']}={s['ms']}ms" for s in d["spans"]))

    def recent(self, n: int = 32) -> List[dict]:
        return [t.to_dict() for t in list(self._recent)[-n:]]

    def find(self, request_id: str) -> List[dict]:
        return [t.to_dict() for t in self._recent
                if t.request_id == request_id]


tracer = Tracer()

_current: contextvars.ContextVar[Optional[Trace]] = contextvars.ContextVar(
    "dynamo_tpu_trace", default=None)


def current_trace() -> Optional[Trace]:
    return _current.get()


@contextlib.contextmanager
def use_trace(trace: Trace, finish: bool = True):
    """Bind `trace` as the ambient trace for the enclosed async chain."""
    token = _current.set(trace)
    try:
        yield trace
    finally:
        _current.reset(token)
        if finish:
            tracer.finish(trace)


@contextlib.contextmanager
def span(name: str, **attrs):
    """Span on the ambient trace; no-op when none is bound."""
    t = _current.get()
    if t is None:
        yield None
    else:
        with t.span(name, **attrs) as s:
            yield s
