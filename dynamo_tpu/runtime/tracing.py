"""Fleet-wide distributed tracing: per-request spans with ON-WIRE context
propagation across frontend → router → worker → KV-fabric peers.

Reference: the request plane instruments ingress/egress with request-id
spans (lib/runtime/src/pipeline/network/egress/push.rs:134-151 — a
tracing span wrapping publish + dial-back, carrying the request id). The
TPU runtime goes further than the reference's log-join scheme: a
:class:`TraceContext` ``(trace_id, parent_span, origin_ts)`` rides the
request-plane control message (runtime/codec.py), the disagg prefill
handoff, and kv_fabric peer fetches, so every downstream process opens a
CHILD trace of the originating frontend trace instead of a disjoint one.
A collector (components/trace_collector.py) subscribes the completed
trace dicts workers publish over the event plane and stitches the
per-request fleet tree, exportable as Chrome-trace-event/Perfetto JSON.

Pieces in this module (dependency-free; asyncio only):

- :class:`Trace` — one process's spans for one request, with a stable
  ``span_id`` (its root span identity), an optional ``parent_span``
  linking it into a fleet tree, and wall-clock anchors (``start_epoch``,
  ``origin_ts``) so cross-process offsets are computable.
- :class:`Tracer` — the process-global registry: ring buffer, sampled
  per-trace log line (every Nth + always-on-slow/error — at fleet QPS an
  unconditional INFO per request is log-spam), ``on_finish`` hooks for
  publication, and the ``dropped_log_lines`` counter behind
  ``nv_llm_trace_dropped_log_lines_total``.
- :class:`TracePublisher` — bounded async queue draining finished trace
  dicts into a transport sink (the event plane in production, a list in
  tests) without ever blocking the finishing code path.
"""

from __future__ import annotations

import contextlib
import contextvars
import dataclasses
import logging
import os
import secrets
import time
from collections import deque
from typing import Callable, Dict, List, Optional

logger = logging.getLogger("dynamo_tpu.trace")

__all__ = ["Span", "Trace", "TraceContext", "Tracer", "TracePublisher",
           "tracer", "current_trace", "current_wire_context", "use_trace",
           "span", "TRACE_EVENTS_SUBJECT"]

# event-plane topic completed trace dicts are published on (same pattern
# as the router's kv_events; components/trace_collector.py subscribes)
TRACE_EVENTS_SUBJECT = "trace_events"


def _new_id(nbytes: int = 8) -> str:
    return secrets.token_hex(nbytes)


@dataclasses.dataclass
class TraceContext:
    """The minimal on-wire propagation record: enough for the receiver to
    open a child trace of the sender's, nothing more. ``origin_ts`` is the
    ORIGINATING frontend's wall clock at root-trace start — every member
    of a fleet tree carries it, so the collector can place all spans on
    one timeline without trusting any single hop's clock twice."""

    trace_id: str
    parent_span: str
    origin_ts: float

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> Optional["TraceContext"]:
        if not d or not d.get("trace_id"):
            return None
        return cls(trace_id=str(d["trace_id"]),
                   parent_span=str(d.get("parent_span", "")),
                   origin_ts=float(d.get("origin_ts", 0.0) or 0.0))


@dataclasses.dataclass
class Span:
    name: str
    start: float
    end: float = 0.0
    attrs: Dict[str, object] = dataclasses.field(default_factory=dict)

    @property
    def ms(self) -> float:
        return 1e3 * (self.end - self.start)


class Trace:
    """All spans of one request on one process ("role" tags which side).

    Identity: ``trace_id`` names the whole fleet tree (minted at the
    origin, inherited by children), ``span_id`` names THIS trace's root
    span, and ``parent_span`` (when set) is the span_id of the trace one
    hop upstream — the edges the collector stitches on."""

    def __init__(self, request_id: str, role: str = "",
                 trace_id: Optional[str] = None,
                 parent_span: Optional[str] = None,
                 origin_ts: Optional[float] = None):
        self.request_id = request_id
        self.role = role
        self.trace_id = trace_id or _new_id()
        self.span_id = _new_id(6)
        self.parent_span = parent_span
        self.start = time.monotonic()
        self.start_epoch = time.time()
        # origin_ts: wall clock at the ORIGIN root's start; roots anchor
        # themselves, children inherit the wire value
        self.origin_ts = self.start_epoch if origin_ts is None else origin_ts
        self.finished: Optional[float] = None   # set by Tracer.finish
        self.error: Optional[str] = None
        self.spans: List[Span] = []

    # ------------------------------------------------------------ wire hops
    def wire_context(self) -> dict:
        """The dict to embed in an outgoing control message: the receiver
        opens a child of THIS trace."""
        return TraceContext(trace_id=self.trace_id,
                            parent_span=self.span_id,
                            origin_ts=self.origin_ts).to_dict()

    @classmethod
    def from_wire(cls, ctx, request_id: str, role: str = "") -> "Trace":
        """Open a child trace from a propagated context (dict or
        :class:`TraceContext`). Falls back to a fresh root when the
        context is absent/malformed — propagation is best-effort and must
        never fail a request."""
        if isinstance(ctx, dict):
            ctx = TraceContext.from_dict(ctx)
        if ctx is None:
            return cls(request_id, role=role)
        return cls(request_id, role=role, trace_id=ctx.trace_id,
                   parent_span=ctx.parent_span or None,
                   origin_ts=ctx.origin_ts or None)

    # --------------------------------------------------------------- spans
    @contextlib.contextmanager
    def span(self, name: str, **attrs):
        s = Span(name=name, start=time.monotonic(), attrs=attrs)
        self.spans.append(s)
        try:
            yield s
        finally:
            s.end = time.monotonic()

    def add_span(self, name: str, start: float, end: float, **attrs) -> Span:
        """Record a completed span from explicit monotonic timestamps —
        the non-contextmanager path used by off-thread work (KV onboard
        prep, fabric fetches) that can't hold a contextvar."""
        s = Span(name=name, start=start, end=end, attrs=attrs)
        self.spans.append(s)
        return s

    def event(self, name: str, **attrs) -> None:
        """Zero-duration marker (e.g. first_token)."""
        t = time.monotonic()
        self.spans.append(Span(name=name, start=t, end=t, attrs=attrs))

    def set_error(self, message: str) -> None:
        """Mark the trace errored (tail-based retention keeps these)."""
        self.error = str(message)[:512]

    def to_dict(self) -> dict:
        end = self.finished if self.finished is not None else time.monotonic()
        return {
            "request_id": self.request_id,
            "role": self.role,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_span": self.parent_span,
            "origin_ts": self.origin_ts,
            "start_epoch": self.start_epoch,
            # offset of this trace's start on the ORIGIN's timeline (ms)
            "origin_offset_ms": round(
                1e3 * (self.start_epoch - self.origin_ts), 3),
            "total_ms": round(1e3 * (end - self.start), 2),
            **({"error": self.error} if self.error else {}),
            "spans": [{"name": s.name, "ms": round(s.ms, 2),
                       "at_ms": round(1e3 * (s.start - self.start), 2),
                       **({"attrs": s.attrs} if s.attrs else {})}
                      for s in self.spans],
        }


class Tracer:
    """Process-global registry: ring buffer + SAMPLED per-trace log line
    + finish hooks (the publication path).

    Log sampling (fleet-QPS hygiene): ``log_every=N`` logs every Nth
    completed trace; traces slower than ``slow_ms`` or carrying an error
    ALWAYS log. Skipped lines are counted in ``dropped_log_lines``
    (exported as ``nv_llm_trace_dropped_log_lines_total``). Defaults come
    from ``DYN_TRACE_LOG_EVERY`` / ``DYN_TRACE_LOG_SLOW_MS`` (default:
    log everything — the single-process debugging posture)."""

    def __init__(self, keep: int = 256, log_every: Optional[int] = None,
                 slow_ms: Optional[float] = None):
        self._recent: deque = deque(maxlen=keep)
        self.completed = 0
        if log_every is None:
            log_every = int(os.environ.get("DYN_TRACE_LOG_EVERY", "1"))
        if slow_ms is None:
            raw = os.environ.get("DYN_TRACE_LOG_SLOW_MS")
            slow_ms = float(raw) if raw else None
        self.log_every = max(int(log_every), 0)   # 0 = never (still slow/err)
        self.slow_ms = slow_ms
        self.dropped_log_lines = 0
        self._since_logged = 0
        # finish hooks receive the serialized trace dict (publication,
        # embedded collectors); exceptions are swallowed — observability
        # must never fail the serving path
        self.on_finish: List[Callable[[dict], None]] = []

    def configure(self, log_every: Optional[int] = None,
                  slow_ms: Optional[float] = None) -> None:
        if log_every is not None:
            self.log_every = max(int(log_every), 0)
        if slow_ms is not None:
            self.slow_ms = float(slow_ms) if slow_ms > 0 else None

    def _should_log(self, d: dict) -> bool:
        if d.get("error"):
            return True
        if self.slow_ms is not None and d["total_ms"] >= self.slow_ms:
            return True
        if self.log_every <= 0:
            return False
        self._since_logged += 1
        if self._since_logged >= self.log_every:
            self._since_logged = 0
            return True
        return False

    def finish(self, trace: Trace) -> None:
        # store the Trace OBJECT and serialize lazily: code holding a
        # captured reference (e.g. the engine's stream_response) may append
        # events after use_trace exits, and those must still show up in
        # /traces (ADVICE r2). total_ms freezes here, not at read time.
        trace.finished = time.monotonic()
        self._recent.append(trace)
        self.completed += 1
        d = trace.to_dict()
        if self._should_log(d):
            logger.info("trace %s [%s] %.1fms: %s", trace.request_id,
                        trace.role, d["total_ms"],
                        " ".join(f"{s['name']}={s['ms']}ms"
                                 for s in d["spans"]))
        else:
            self.dropped_log_lines += 1
        for cb in list(self.on_finish):
            try:
                cb(d)
            except Exception:  # noqa: BLE001 — hooks must never fail finish
                logger.exception("trace finish hook failed")

    def recent(self, n: int = 32) -> List[dict]:
        return [t.to_dict() for t in list(self._recent)[-n:]]

    def find(self, request_id: str) -> List[dict]:
        return [t.to_dict() for t in self._recent
                if t.request_id == request_id]

    def stats(self) -> dict:
        return {"completed": self.completed,
                "dropped_log_lines": self.dropped_log_lines,
                "log_every": self.log_every,
                "slow_ms": self.slow_ms,
                "ring": len(self._recent)}


class TracePublisher:
    """Drains finished trace dicts into an async ``sink`` (the event
    plane) through a bounded queue — the finishing code path never blocks
    on the network, saturation drops with a counter (the KvEventPublisher
    contract applied to traces)."""

    def __init__(self, sink, max_buffer: int = 2048,
                 tracer_: Optional["Tracer"] = None):
        import asyncio
        self.sink = sink
        self._queue: "asyncio.Queue" = asyncio.Queue(maxsize=max_buffer)
        self._task = None
        self.dropped = 0
        self.published = 0
        self._tracer = tracer_
        if tracer_ is not None:
            tracer_.on_finish.append(self.enqueue)

    def enqueue(self, trace_dict: dict) -> None:
        import asyncio
        try:
            self._queue.put_nowait(trace_dict)
        except asyncio.QueueFull:
            self.dropped += 1
            return
        self._ensure_task()

    def _ensure_task(self) -> None:
        import asyncio
        if self._task is None or self._task.done():
            try:
                loop = asyncio.get_running_loop()
            except RuntimeError:
                return  # no loop (sync context); drains on next enqueue
            self._task = loop.create_task(self._run(), name="trace-pub")

    async def _run(self) -> None:
        while True:
            d = await self._queue.get()
            try:
                await self.sink(d)
                self.published += 1
            except Exception:  # noqa: BLE001 — transport boundary
                logger.exception("trace publish failed (trace dropped)")
            finally:
                self._queue.task_done()

    async def drain(self) -> None:
        self._ensure_task()
        await self._queue.join()

    def close(self) -> None:
        """Detach from the tracer and stop the pump (test hygiene: the
        process tracer is a singleton; a dangling hook would keep
        publishing another test's traces)."""
        if self._tracer is not None:
            try:
                self._tracer.on_finish.remove(self.enqueue)
            except ValueError:
                pass
        if self._task is not None:
            self._task.cancel()


tracer = Tracer()

_current: contextvars.ContextVar[Optional[Trace]] = contextvars.ContextVar(
    "dynamo_tpu_trace", default=None)


def current_trace() -> Optional[Trace]:
    return _current.get()


def current_wire_context() -> Optional[dict]:
    """The ambient trace's propagation dict, or None — what egress embeds
    in the outgoing control message."""
    t = _current.get()
    return t.wire_context() if t is not None else None


def detach_trace() -> None:
    """Clear the ambient trace in THIS context. Long-lived background
    tasks (the engine loop) are created from whatever request context
    first started them and would otherwise inherit that request's trace
    forever — every task they spawn (onboard preps, fabric RPCs) would
    mis-attach to the first request's tree. Such tasks detach at entry;
    per-request identity travels explicitly (EngineRequest.trace,
    trace_ctx parameters)."""
    _current.set(None)


@contextlib.contextmanager
def use_trace(trace: Trace, finish: bool = True):
    """Bind `trace` as the ambient trace for the enclosed async chain."""
    token = _current.set(trace)
    try:
        yield trace
    finally:
        _current.reset(token)
        if finish:
            tracer.finish(trace)


@contextlib.contextmanager
def span(name: str, **attrs):
    """Span on the ambient trace; no-op when none is bound."""
    t = _current.get()
    if t is None:
        yield None
    else:
        with t.span(name, **attrs) as s:
            yield s
