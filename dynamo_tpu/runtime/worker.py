"""Worker main-wrapper: signal handling + graceful-shutdown timeout.

Reference: lib/runtime/src/worker.rs:35-211 — ``Worker::from_settings()
.execute(app)`` builds the runtime, traps SIGINT/SIGTERM, cancels the root
token, and force-exits with code 911 if shutdown overruns
``DYN_WORKER_GRACEFUL_SHUTDOWN_TIMEOUT`` seconds. Same contract here, on
asyncio: the app is an ``async fn(runtime)``; first signal cancels, second
signal (or timeout overrun) force-exits.
"""

from __future__ import annotations

import asyncio
import logging
import os
import signal
import sys
from typing import Awaitable, Callable, Optional

from .distributed import DistributedRuntime

logger = logging.getLogger("dynamo_tpu.runtime.worker")

GRACEFUL_EXIT_OVERRUN_CODE = 911  # matches the reference's worker.rs


class Worker:
    """Run an async app against a DistributedRuntime with UNIX-signal
    lifecycle management."""

    def __init__(self, runtime: Optional[DistributedRuntime] = None,
                 graceful_timeout: Optional[float] = None):
        self._config = None
        self.runtime = runtime
        self.graceful_timeout = (self.config.graceful_shutdown_timeout
                                 if graceful_timeout is None
                                 else graceful_timeout)

    @property
    def config(self):
        """Lazily loaded layered WorkerConfig — embedders that pass both
        runtime and timeout never touch the filesystem."""
        if self._config is None:
            from .config import load_worker_config
            self._config = load_worker_config()
        return self._config

    @classmethod
    def from_settings(cls) -> "Worker":
        """Build from layered config (runtime/config.py): discovery_addr
        set (env ``DYN_DISCOVERY_ADDR`` / ``DYN_WORKER_DISCOVERY_ADDR`` or
        TOML) selects the networked runtime; unset means in-process. Also
        installs the DYN_LOG/DYN_LOGGING_JSONL logging setup."""
        from .log import setup_logging
        setup_logging()
        return cls()

    async def _build_runtime(self) -> DistributedRuntime:
        if self.runtime is not None:
            return self.runtime
        if self.config.discovery_addr:
            self.runtime = await DistributedRuntime.connect(
                self.config.discovery_addr,
                advertise=self.config.advertise_host)
        else:
            self.runtime = DistributedRuntime.in_process()
        return self.runtime

    def execute(self, app: Callable[[DistributedRuntime], Awaitable]) -> None:
        try:
            asyncio.run(self._execute(app))
        except KeyboardInterrupt:
            pass

    async def _execute(self, app) -> None:
        runtime = await self._build_runtime()
        stop = asyncio.Event()
        hits = {"n": 0}

        def on_signal() -> None:
            hits["n"] += 1
            if hits["n"] >= 2:
                logger.error("second signal — force exit")
                os._exit(GRACEFUL_EXIT_OVERRUN_CODE)
            logger.info("shutdown signal received")
            stop.set()

        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            try:
                loop.add_signal_handler(sig, on_signal)
            except (NotImplementedError, RuntimeError):
                pass
        runtime.on_lease_lost = stop.set

        app_task = loop.create_task(app(runtime), name="worker-app")
        stop_task = loop.create_task(stop.wait(), name="worker-stop")
        done, _ = await asyncio.wait({app_task, stop_task},
                                     return_when=asyncio.FIRST_COMPLETED)
        if app_task in done:
            stop_task.cancel()
            exc = app_task.exception()
            if exc is not None:
                await runtime.shutdown()
                raise exc
        else:
            app_task.cancel()
        try:
            await asyncio.wait_for(runtime.shutdown(), self.graceful_timeout)
        except asyncio.TimeoutError:
            logger.error("graceful shutdown overran %.0fs — force exit %d",
                         self.graceful_timeout, GRACEFUL_EXIT_OVERRUN_CODE)
            sys.stderr.flush()
            os._exit(GRACEFUL_EXIT_OVERRUN_CODE)
