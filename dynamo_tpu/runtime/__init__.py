"""Distributed runtime layer (reference: lib/runtime, the dynamo-runtime crate)."""

from .engine import (AsyncEngine, Context, EngineContext, EngineFn, ManyOut,
                     ResponseStream, SingleIn, engine_from_fn)
from .pipeline import Operator, ServiceFrontend, link

__all__ = [
    "AsyncEngine", "Context", "EngineContext", "EngineFn", "ManyOut",
    "ResponseStream", "SingleIn", "engine_from_fn",
    "Operator", "ServiceFrontend", "link",
]
