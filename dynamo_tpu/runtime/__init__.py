"""Distributed runtime layer (reference: lib/runtime, the dynamo-runtime crate)."""

from .engine import (AsyncEngine, Context, EngineContext, EngineFn, ManyOut,
                     ResponseStream, SingleIn, engine_from_fn)
from .pipeline import Operator, ServiceFrontend, link

__all__ = [
    "AsyncEngine", "Context", "EngineContext", "EngineFn", "ManyOut",
    "ResponseStream", "SingleIn", "engine_from_fn",
    "Operator", "ServiceFrontend", "link",
    # distributed layer (imported lazily by most callers)
    "DistributedRuntime", "Namespace", "Component", "Endpoint", "Client",
    "Worker",
]


def __getattr__(name):  # lazy: keep `import dynamo_tpu.runtime` light
    if name in ("DistributedRuntime", "Namespace", "Component", "Endpoint",
                "EndpointServer", "Client", "json_serde"):
        from . import distributed
        return getattr(distributed, name)
    if name == "Worker":
        from .worker import Worker
        return Worker
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
