"""Core async-engine abstraction: the TPU-native analog of the reference's
``AsyncEngine`` trait (reference: lib/runtime/src/engine.rs:47-168).

Everything that produces a stream of responses from a single request — a model
engine, a remote client, a whole pipeline — implements :class:`AsyncEngine`.
Requests travel wrapped in a :class:`Context` (reference ``Context<T>``,
lib/runtime/src/pipeline/context.rs) that carries a request id, metadata and a
cancellation handle (:class:`EngineContext`, reference ``AsyncEngineContext``).

Design notes (TPU-first): cancellation must be *step-granular* — an XLA
computation cannot be interrupted mid-dispatch, so engines are required to poll
``ctx.is_stopped`` between decode steps rather than rely on task cancellation.
"""

from __future__ import annotations

import abc
import asyncio
import uuid
from typing import (Any, AsyncIterator, Awaitable, Callable, Dict, Generic,
                    Optional, TypeVar)

T = TypeVar("T")
U = TypeVar("U")

__all__ = [
    "EngineContext",
    "Context",
    "SingleIn",
    "ManyOut",
    "ResponseStream",
    "AsyncEngine",
    "EngineFn",
    "engine_from_fn",
]


class EngineContext:
    """Cancellation + identity handle shared by a request and all streams
    derived from it.

    Mirrors the semantics of the reference's ``AsyncEngineContext``
    (lib/runtime/src/engine.rs:47-100):

    - ``stop_generating()`` — graceful: the engine should finish the current
      step, emit what it has, and stop issuing new work.
    - ``kill()`` — hard: downstream should drop the stream as soon as possible
      (used by the HTTP layer when a client disconnects mid-SSE).
    - ``deadline_s`` — optional absolute end-to-end deadline
      (``time.monotonic()`` clock). Set at the frontend from the
      request's ``deadline_ms`` budget, propagated on the wire as the
      REMAINING budget (codec.RequestControlMessage.deadline_ms), and
      polled by engines between steps exactly like cancellation — a
      request whose client stopped caring vacates its slot instead of
      burning capacity.
    - ``tenant`` / ``qos`` — multi-tenant identity (llm/tenancy.py):
      set at the frontend from ``nvext.tenant``/``nvext.priority`` and
      propagated on the wire (codec.RequestControlMessage tenant /
      priority) so routers and workers price per-tenant fair share and
      KV quotas without re-parsing the payload.
    """

    __slots__ = ("_id", "_stopped", "_killed", "_stop_event", "deadline_s",
                 "tenant", "qos")

    def __init__(self, request_id: Optional[str] = None,
                 deadline_ms: Optional[float] = None,
                 tenant: Optional[str] = None,
                 qos: Optional[str] = None):
        self._id = request_id or uuid.uuid4().hex
        self._stopped = False
        self._killed = False
        self._stop_event: Optional[asyncio.Event] = None
        self.deadline_s: Optional[float] = None
        self.tenant = tenant
        self.qos = qos
        if deadline_ms is not None:
            self.set_deadline_ms(deadline_ms)

    @property
    def id(self) -> str:
        return self._id

    def stop_generating(self) -> None:
        self._stopped = True
        if self._stop_event is not None:
            self._stop_event.set()

    def kill(self) -> None:
        self._killed = True
        self.stop_generating()

    @property
    def is_stopped(self) -> bool:
        return self._stopped

    @property
    def is_killed(self) -> bool:
        return self._killed

    async def stopped(self) -> None:
        """Await until stop_generating()/kill() is called."""
        if self._stop_event is None:
            self._stop_event = asyncio.Event()
            if self._stopped:
                self._stop_event.set()
        await self._stop_event.wait()

    # ----------------------------------------------------------- deadline
    def set_deadline_ms(self, budget_ms: float) -> None:
        """Arm (or tighten) the end-to-end deadline ``budget_ms`` from
        now. A second call never LOOSENS an armed deadline — each hop
        may only shrink the remaining budget."""
        import time
        d = time.monotonic() + max(float(budget_ms), 0.0) / 1e3
        if self.deadline_s is None or d < self.deadline_s:
            self.deadline_s = d

    def remaining_ms(self) -> Optional[float]:
        """Remaining budget in ms (clamped at 0), or None when no
        deadline is armed — what egress puts on the wire so the serving
        side re-anchors to its own clock."""
        if self.deadline_s is None:
            return None
        import time
        return max(self.deadline_s - time.monotonic(), 0.0) * 1e3

    @property
    def deadline_exceeded(self) -> bool:
        if self.deadline_s is None:
            return False
        import time
        return time.monotonic() >= self.deadline_s


class Context(Generic[T]):
    """A request payload plus its engine context and metadata.

    Reference ``Context<T>`` / ``SingleIn<T>``
    (lib/runtime/src/pipeline/context.rs, pipeline.rs:41-68). ``map`` derives a
    new payload while keeping id/cancellation; ``transfer`` swaps the payload
    entirely (used at operator boundaries where the type changes).
    """

    __slots__ = ("data", "ctx", "metadata")

    def __init__(self, data: T, ctx: Optional[EngineContext] = None,
                 metadata: Optional[Dict[str, Any]] = None):
        self.data = data
        self.ctx = ctx or EngineContext()
        self.metadata: Dict[str, Any] = metadata if metadata is not None else {}

    @property
    def id(self) -> str:
        return self.ctx.id

    def map(self, fn: Callable[[T], U]) -> "Context[U]":
        return self.transfer(fn(self.data))

    def transfer(self, data: U) -> "Context[U]":
        return Context(data, self.ctx, self.metadata)


# Type aliases matching the reference's pipeline vocabulary
# (lib/runtime/src/pipeline.rs:41-68).
SingleIn = Context


class ResponseStream(Generic[U]):
    """An async stream of responses bound to an :class:`EngineContext`.

    Reference ``ResponseStream`` / ``ManyOut`` (lib/runtime/src/engine.rs:120-168).
    Iteration stops early if the context is killed (not merely stopped: a
    graceful stop lets the engine flush its tail).
    """

    def __init__(self, stream: AsyncIterator[U], ctx: EngineContext):
        self._stream = stream
        self.ctx = ctx

    def __aiter__(self) -> AsyncIterator[U]:
        return self._iter()

    async def _iter(self) -> AsyncIterator[U]:
        async for item in self._stream:
            if self.ctx.is_killed:
                break
            yield item

    async def collect(self) -> list:
        return [item async for item in self]

    def map(self, fn: Callable[[U], T]) -> "ResponseStream[T]":
        async def gen() -> AsyncIterator[T]:
            async for item in self._stream:
                yield fn(item)

        return ResponseStream(gen(), self.ctx)

    @staticmethod
    def from_iterable(items, ctx: EngineContext) -> "ResponseStream":
        async def gen():
            for item in items:
                yield item

        return ResponseStream(gen(), ctx)


ManyOut = ResponseStream


class AsyncEngine(abc.ABC, Generic[T, U]):
    """The one core interface: ``generate(SingleIn[T]) -> ManyOut[U]``.

    Reference trait ``AsyncEngine<Req, Resp, Err>`` (lib/runtime/src/engine.rs:104-118).
    """

    @abc.abstractmethod
    async def generate(self, request: SingleIn[T]) -> ManyOut[U]:
        ...


class EngineFn(AsyncEngine[T, U]):
    """Adapter: build an engine from ``async fn(Context[T]) -> AsyncIterator[U]``
    (the closure-engine pattern used throughout the reference's tests,
    lib/runtime/tests/common/engines.rs)."""

    def __init__(self, fn: Callable[[SingleIn[T]], Any]):
        self._fn = fn

    async def generate(self, request: SingleIn[T]) -> ManyOut[U]:
        result = self._fn(request)
        if isinstance(result, Awaitable):
            result = await result
        if isinstance(result, ResponseStream):
            return result
        return ResponseStream(result, request.ctx)


def engine_from_fn(fn: Callable[[SingleIn[T]], Any]) -> EngineFn:
    return EngineFn(fn)
