"""Logging setup: level filters and JSONL output.

Reference: lib/runtime/src/logging.rs:54-170 — tracing-subscriber driven by
``DYN_LOG`` (a level or ``target=level`` comma list) with an optional
custom JSONL formatter under ``DYN_LOGGING_JSONL``. Python analog over the
stdlib logging tree:

    DYN_LOG="info"                      # root level
    DYN_LOG="info,dynamo_tpu.kv=debug"  # per-module overrides
    DYN_LOGGING_JSONL=1                 # one JSON object per line

``setup_logging()`` is called by the worker wrapper, the daemon, and every
module CLI; calling it twice is a no-op unless ``force=True``.
"""

from __future__ import annotations

import json
import logging
import os
import sys
import time
from typing import Optional

__all__ = ["setup_logging", "JsonlFormatter"]

_configured = False


class JsonlFormatter(logging.Formatter):
    """One JSON object per line: ts, level, target (logger name), message,
    plus exception text when present (reference custom JSONL formatter)."""

    def format(self, record: logging.LogRecord) -> str:
        out = {
            "ts": round(record.created, 6),
            "iso": time.strftime("%Y-%m-%dT%H:%M:%S",
                                 time.gmtime(record.created))
                   + f".{int(record.msecs):03d}Z",
            "level": record.levelname,
            "target": record.name,
            "message": record.getMessage(),
        }
        if record.exc_info and record.exc_info[0] is not None:
            out["exception"] = self.formatException(record.exc_info)
        return json.dumps(out, ensure_ascii=False)


# logging.getLevelNamesMapping is 3.11+; build the same name→level map
_LEVEL_NAMES = {name: lvl for lvl, name in logging._levelToName.items()}
_LEVEL_NAMES["WARN"] = logging.WARNING
_LEVEL_NAMES["FATAL"] = logging.CRITICAL


def _parse_dyn_log(spec: str) -> tuple:
    """"info,foo.bar=debug" → (root_level, {module: level})."""
    root = logging.INFO
    per_module = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" in part:
            mod, _, lvl = part.partition("=")
            per_module[mod.strip()] = _LEVEL_NAMES.get(
                lvl.strip().upper(), logging.INFO)
        else:
            root = _LEVEL_NAMES.get(part.upper(), logging.INFO)
    return root, per_module


def setup_logging(level: Optional[str] = None, force: bool = False) -> None:
    global _configured
    if _configured and not force:
        return
    _configured = True
    spec = level or os.environ.get("DYN_LOG", "info")
    root_level, per_module = _parse_dyn_log(spec)
    handler = logging.StreamHandler(sys.stderr)
    if os.environ.get("DYN_LOGGING_JSONL", "") not in ("", "0", "false"):
        handler.setFormatter(JsonlFormatter())
    else:
        handler.setFormatter(logging.Formatter(
            "%(asctime)s %(name)s %(levelname)s %(message)s"))
    root = logging.getLogger()
    if force:
        root.handlers.clear()
    root.addHandler(handler)
    root.setLevel(root_level)
    for mod, lvl in per_module.items():
        logging.getLogger(mod).setLevel(lvl)
