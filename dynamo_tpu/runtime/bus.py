"""Message bus: NATS-shaped interface (core pub/sub + queue-group request
plane + persistent work queue) with an in-process implementation.

The reference's request plane is a NATS service endpoint per worker instance
(requests pushed to subject ``{ns}|{comp}.{ep}-{lease:x}``,
lib/runtime/src/component.rs:246-257), its event plane is NATS pub/sub
(traits/events.rs), and its prefill queue is a JetStream work-queue stream
(examples/llm/utils/nats_queue.py). This module keeps those three roles —

- ``publish/subscribe``: broadcast events (every subscriber sees every msg);
- ``serve``: exactly-one delivery to a subject's single server (each worker
  instance serves its own unique subject, so "queue group" degenerates to
  per-instance subjects, as in the reference);
- ``WorkQueue``: at-least-once pull queue with ack/nack + redelivery;

— behind an interface with a memory backend here and a TCP client backend in
runtime/netstore.py.
"""

from __future__ import annotations

import abc
import asyncio
import dataclasses
import fnmatch
import time
from typing import AsyncIterator, Callable, Dict, List, Optional, Tuple

__all__ = ["BusMessage", "Subscription", "WorkItem", "WorkQueue",
           "MessageBus", "MemoryBus"]


@dataclasses.dataclass
class BusMessage:
    subject: str
    payload: bytes


class Subscription:
    """Broadcast subscription handle (supports ``*`` fnmatch wildcards)."""

    def __init__(self, pattern: str, unsubscribe: Callable):
        self.pattern = pattern
        self._queue: asyncio.Queue = asyncio.Queue()
        self._unsubscribe = unsubscribe
        self._closed = False

    def _push(self, msg: BusMessage) -> None:
        if not self._closed:
            self._queue.put_nowait(msg)

    async def next(self, timeout: Optional[float] = None) -> Optional[BusMessage]:
        try:
            if timeout is None:
                return await self._queue.get()
            return await asyncio.wait_for(self._queue.get(), timeout)
        except asyncio.TimeoutError:
            return None

    def __aiter__(self) -> AsyncIterator[BusMessage]:
        return self

    async def __anext__(self) -> BusMessage:
        if self._closed and self._queue.empty():
            raise StopAsyncIteration
        return await self._queue.get()

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            self._unsubscribe(self)


@dataclasses.dataclass
class WorkItem:
    id: int
    payload: bytes
    deliveries: int = 1


class WorkQueue(abc.ABC):
    """At-least-once pull work queue (JetStream work-queue stream analog)."""

    @abc.abstractmethod
    async def enqueue(self, payload: bytes) -> int: ...

    @abc.abstractmethod
    async def dequeue(self, timeout: Optional[float] = None,
                      ack_deadline: float = 30.0) -> Optional[WorkItem]:
        """Next unclaimed item; it must be ``ack``ed before *ack_deadline*
        or it is redelivered."""

    @abc.abstractmethod
    async def ack(self, item_id: int) -> None: ...

    @abc.abstractmethod
    async def nack(self, item_id: int) -> None:
        """Immediately return the item for redelivery."""

    @abc.abstractmethod
    async def depth(self) -> int: ...


class MessageBus(abc.ABC):
    @abc.abstractmethod
    async def publish(self, subject: str, payload: bytes) -> int:
        """Returns how many receivers got the message (0 = no responders,
        the NATS-style signal the request plane retries on)."""

    @abc.abstractmethod
    async def subscribe(self, pattern: str) -> Subscription: ...

    @abc.abstractmethod
    async def serve(self, subject: str) -> Subscription:
        """Claim *subject* as this instance's request inbox. Exactly one
        server per subject; messages published there go only to it."""

    @abc.abstractmethod
    async def unserve(self, subject: str) -> None: ...

    @abc.abstractmethod
    async def work_queue(self, name: str) -> WorkQueue: ...

    async def close(self) -> None:
        pass


class _MemoryWorkQueue(WorkQueue):
    def __init__(self) -> None:
        self._next_id = 1
        self._ready: List[WorkItem] = []
        self._pending: Dict[int, Tuple[WorkItem, float]] = {}  # id → (item, deadline)
        self._restored: set = set()   # ids recovery put back as pending
        self._event = asyncio.Event()

    def _redeliver_due(self) -> None:
        now = time.monotonic()
        due = [iid for iid, (_, dl) in self._pending.items() if dl <= now]
        for iid in due:
            item, _ = self._pending.pop(iid)
            item.deliveries += 1
            self._ready.append(item)
        if due:
            self._event.set()

    async def enqueue(self, payload: bytes) -> int:
        item = WorkItem(self._next_id, payload)
        self._next_id += 1
        self._ready.append(item)
        self._event.set()
        return item.id

    async def dequeue(self, timeout: Optional[float] = None,
                      ack_deadline: float = 30.0) -> Optional[WorkItem]:
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            self._redeliver_due()
            if self._ready:
                item = self._ready.pop(0)
                self._pending[item.id] = (item, time.monotonic() + ack_deadline)
                return item
            self._event.clear()
            wait = 0.1
            if deadline is not None:
                wait = min(wait, deadline - time.monotonic())
                if wait <= 0:
                    return None
            try:
                await asyncio.wait_for(self._event.wait(), wait)
            except asyncio.TimeoutError:
                pass

    async def ack(self, item_id: int) -> None:
        self._pending.pop(item_id, None)
        # after a daemon restart a consumer may ack an item the recovery
        # path restored as PENDING — the ack must still retire it or the
        # completed item would be redelivered. Only restored ids can be
        # acked out of _ready, so the O(depth) scrub is restart-only and
        # steady-state acks stay O(1).
        if item_id in self._restored:
            self._restored.discard(item_id)
            self._ready = [it for it in self._ready if it.id != item_id]

    async def nack(self, item_id: int) -> None:
        got = self._pending.pop(item_id, None)
        if got is not None:
            item, _ = got
            item.deliveries += 1
            self._ready.insert(0, item)
            self._event.set()

    async def depth(self) -> int:
        self._redeliver_due()
        return len(self._ready)

    # ---------------------------------------------- durability (wal.py)
    def restore_item(self, iid: int, payload: bytes,
                     deliveries: int = 1) -> None:
        """Re-materialize a persisted item as PENDING with its original id
        (so later wq_ack WAL records and consumer-side dedup still match).
        Delivered-but-unacked items come back this way too — at-least-once
        redelivery, the JetStream work-queue semantic. ``deliveries``
        defaults to 1 to match a fresh enqueue (the WAL replay path cannot
        know the true count; under-reporting 0 would let a poison item
        dodge consumers' MAX_DELIVERIES guards across restart cycles)."""
        self._ready.append(WorkItem(iid, payload, deliveries))
        self._restored.add(iid)
        self._next_id = max(self._next_id, iid + 1)
        self._event.set()

    def dump_items(self) -> list:
        """Pending + in-flight items (in-flight fold back to pending)."""
        import base64
        self._redeliver_due()
        items = list(self._ready) + [it for it, _ in self._pending.values()]
        return [[it.id, base64.b64encode(it.payload).decode(), it.deliveries]
                for it in items]


class MemoryBus(MessageBus):
    """Single-process bus (also the server-side state of the network bus)."""

    def __init__(self) -> None:
        self._subs: List[Subscription] = []
        self._servers: Dict[str, Subscription] = {}
        self._queues: Dict[str, _MemoryWorkQueue] = {}

    async def publish(self, subject: str, payload: bytes) -> int:
        """Returns the receiver count — 0 is NATS's "no responders"
        signal; the request plane (Client.direct) retries on it so a
        request published while its server's subscription is being
        re-established (daemon restart) is never silently dropped."""
        msg = BusMessage(subject, payload)
        n = 0
        srv = self._servers.get(subject)
        if srv is not None:
            srv._push(msg)
            n += 1
        for sub in list(self._subs):
            if sub.pattern == subject or fnmatch.fnmatchcase(subject, sub.pattern):
                sub._push(msg)
                n += 1
        return n

    async def subscribe(self, pattern: str) -> Subscription:
        sub = Subscription(pattern, self._unsub)
        self._subs.append(sub)
        return sub

    def _unsub(self, sub: Subscription) -> None:
        self._subs = [s for s in self._subs if s is not sub]
        for subj, srv in list(self._servers.items()):
            if srv is sub:
                del self._servers[subj]

    async def serve(self, subject: str) -> Subscription:
        if subject in self._servers:
            raise RuntimeError(f"subject already served: {subject}")
        srv = Subscription(subject, self._unsub)
        self._servers[subject] = srv
        return srv

    async def unserve(self, subject: str) -> None:
        srv = self._servers.pop(subject, None)
        if srv is not None:
            srv.close()

    def served_subjects(self) -> List[str]:
        return sorted(self._servers)

    async def work_queue(self, name: str) -> WorkQueue:
        q = self._queues.get(name)
        if q is None:
            q = self._queues[name] = _MemoryWorkQueue()
        return q

    # ---------------------------------------------- durability (wal.py)
    def dump_state(self) -> dict:
        """JSON-able snapshot of the work queues (the bus's only durable
        state — pub/sub and served subjects are connection-scoped)."""
        return {"queues": {name: q.dump_items()
                           for name, q in self._queues.items()}}

    async def restore_state(self, state: dict) -> None:
        import base64
        for name, items in state.get("queues", {}).items():
            q = await self.work_queue(name)
            for iid, payload, deliveries in items:
                q.restore_item(int(iid), base64.b64decode(payload),
                               int(deliveries))
