"""Message bus: NATS-shaped interface (core pub/sub + queue-group request
plane + persistent work queue) with an in-process implementation.

The reference's request plane is a NATS service endpoint per worker instance
(requests pushed to subject ``{ns}|{comp}.{ep}-{lease:x}``,
lib/runtime/src/component.rs:246-257), its event plane is NATS pub/sub
(traits/events.rs), and its prefill queue is a JetStream work-queue stream
(examples/llm/utils/nats_queue.py). This module keeps those three roles —

- ``publish/subscribe``: broadcast events (every subscriber sees every msg);
- ``serve``: exactly-one delivery to a subject's single server (each worker
  instance serves its own unique subject, so "queue group" degenerates to
  per-instance subjects, as in the reference);
- ``WorkQueue``: at-least-once pull queue with ack/nack + redelivery;

— behind an interface with a memory backend here and a TCP client backend in
runtime/netstore.py.
"""

from __future__ import annotations

import abc
import asyncio
import dataclasses
import fnmatch
import time
from typing import AsyncIterator, Callable, Dict, List, Optional, Tuple

__all__ = ["BusMessage", "Subscription", "WorkItem", "WorkQueue",
           "MessageBus", "MemoryBus"]


@dataclasses.dataclass
class BusMessage:
    subject: str
    payload: bytes


class Subscription:
    """Broadcast subscription handle (supports ``*`` fnmatch wildcards)."""

    def __init__(self, pattern: str, unsubscribe: Callable):
        self.pattern = pattern
        self._queue: asyncio.Queue = asyncio.Queue()
        self._unsubscribe = unsubscribe
        self._closed = False

    def _push(self, msg: BusMessage) -> None:
        if not self._closed:
            self._queue.put_nowait(msg)

    async def next(self, timeout: Optional[float] = None) -> Optional[BusMessage]:
        try:
            if timeout is None:
                return await self._queue.get()
            return await asyncio.wait_for(self._queue.get(), timeout)
        except asyncio.TimeoutError:
            return None

    def __aiter__(self) -> AsyncIterator[BusMessage]:
        return self

    async def __anext__(self) -> BusMessage:
        if self._closed and self._queue.empty():
            raise StopAsyncIteration
        return await self._queue.get()

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            self._unsubscribe(self)


@dataclasses.dataclass
class WorkItem:
    id: int
    payload: bytes
    deliveries: int = 1


class WorkQueue(abc.ABC):
    """At-least-once pull work queue (JetStream work-queue stream analog)."""

    @abc.abstractmethod
    async def enqueue(self, payload: bytes) -> int: ...

    @abc.abstractmethod
    async def dequeue(self, timeout: Optional[float] = None,
                      ack_deadline: float = 30.0) -> Optional[WorkItem]:
        """Next unclaimed item; it must be ``ack``ed before *ack_deadline*
        or it is redelivered."""

    @abc.abstractmethod
    async def ack(self, item_id: int) -> None: ...

    @abc.abstractmethod
    async def nack(self, item_id: int) -> None:
        """Immediately return the item for redelivery."""

    @abc.abstractmethod
    async def depth(self) -> int: ...


class MessageBus(abc.ABC):
    @abc.abstractmethod
    async def publish(self, subject: str, payload: bytes) -> int:
        """Returns how many receivers got the message (0 = no responders,
        the NATS-style signal the request plane retries on)."""

    @abc.abstractmethod
    async def subscribe(self, pattern: str) -> Subscription: ...

    @abc.abstractmethod
    async def serve(self, subject: str) -> Subscription:
        """Claim *subject* as this instance's request inbox. Exactly one
        server per subject; messages published there go only to it."""

    @abc.abstractmethod
    async def unserve(self, subject: str) -> None: ...

    @abc.abstractmethod
    async def work_queue(self, name: str) -> WorkQueue: ...

    async def close(self) -> None:
        pass


class _MemoryWorkQueue(WorkQueue):
    def __init__(self) -> None:
        self._next_id = 1
        self._ready: List[WorkItem] = []
        self._pending: Dict[int, Tuple[WorkItem, float]] = {}  # id → (item, deadline)
        self._event = asyncio.Event()

    def _redeliver_due(self) -> None:
        now = time.monotonic()
        due = [iid for iid, (_, dl) in self._pending.items() if dl <= now]
        for iid in due:
            item, _ = self._pending.pop(iid)
            item.deliveries += 1
            self._ready.append(item)
        if due:
            self._event.set()

    async def enqueue(self, payload: bytes) -> int:
        item = WorkItem(self._next_id, payload)
        self._next_id += 1
        self._ready.append(item)
        self._event.set()
        return item.id

    async def dequeue(self, timeout: Optional[float] = None,
                      ack_deadline: float = 30.0) -> Optional[WorkItem]:
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            self._redeliver_due()
            if self._ready:
                item = self._ready.pop(0)
                self._pending[item.id] = (item, time.monotonic() + ack_deadline)
                return item
            self._event.clear()
            wait = 0.1
            if deadline is not None:
                wait = min(wait, deadline - time.monotonic())
                if wait <= 0:
                    return None
            try:
                await asyncio.wait_for(self._event.wait(), wait)
            except asyncio.TimeoutError:
                pass

    async def ack(self, item_id: int) -> None:
        self._pending.pop(item_id, None)

    async def nack(self, item_id: int) -> None:
        got = self._pending.pop(item_id, None)
        if got is not None:
            item, _ = got
            item.deliveries += 1
            self._ready.insert(0, item)
            self._event.set()

    async def depth(self) -> int:
        self._redeliver_due()
        return len(self._ready)


class MemoryBus(MessageBus):
    """Single-process bus (also the server-side state of the network bus)."""

    def __init__(self) -> None:
        self._subs: List[Subscription] = []
        self._servers: Dict[str, Subscription] = {}
        self._queues: Dict[str, _MemoryWorkQueue] = {}

    async def publish(self, subject: str, payload: bytes) -> int:
        """Returns the receiver count — 0 is NATS's "no responders"
        signal; the request plane (Client.direct) retries on it so a
        request published while its server's subscription is being
        re-established (daemon restart) is never silently dropped."""
        msg = BusMessage(subject, payload)
        n = 0
        srv = self._servers.get(subject)
        if srv is not None:
            srv._push(msg)
            n += 1
        for sub in list(self._subs):
            if sub.pattern == subject or fnmatch.fnmatchcase(subject, sub.pattern):
                sub._push(msg)
                n += 1
        return n

    async def subscribe(self, pattern: str) -> Subscription:
        sub = Subscription(pattern, self._unsub)
        self._subs.append(sub)
        return sub

    def _unsub(self, sub: Subscription) -> None:
        self._subs = [s for s in self._subs if s is not sub]
        for subj, srv in list(self._servers.items()):
            if srv is sub:
                del self._servers[subj]

    async def serve(self, subject: str) -> Subscription:
        if subject in self._servers:
            raise RuntimeError(f"subject already served: {subject}")
        srv = Subscription(subject, self._unsub)
        self._servers[subject] = srv
        return srv

    async def unserve(self, subject: str) -> None:
        srv = self._servers.pop(subject, None)
        if srv is not None:
            srv.close()

    def served_subjects(self) -> List[str]:
        return sorted(self._servers)

    async def work_queue(self, name: str) -> WorkQueue:
        q = self._queues.get(name)
        if q is None:
            q = self._queues[name] = _MemoryWorkQueue()
        return q
