"""Serving side of the request plane: bus inbox → engine → TCP dial-back.

Reference: ``PushEndpoint`` (lib/runtime/src/pipeline/network/ingress/
push_endpoint.rs:36-84) + ``Ingress`` (network.rs:51-325). Split out of
distributed.py (round 3); naming lives in runtime/component.py, the
calling side in runtime/egress.py.
"""

from __future__ import annotations

import asyncio
import collections
import logging
import time
from typing import Any, Callable, Optional

from .codec import decode_two_part
from .component import ComponentEndpointInfo, _default_encode
from .engine import AsyncEngine, Context
from .kvstore import Lease
from .tcp import StreamSender, open_stream_sender

logger = logging.getLogger("dynamo_tpu.runtime.distributed")

__all__ = ["EndpointServer"]


class EndpointServer:
    """Serving side: bus inbox loop → engine → TCP dial-back stream.
    Reference: ``PushEndpoint`` (ingress/push_endpoint.rs:36-84) +
    ``Ingress`` (network.rs:51-325)."""

    def __init__(self, endpoint, engine: AsyncEngine,
                 decode_req: Callable[[bytes], Any],
                 encode_resp: Callable[[Any], bytes],
                 stats_handler: Optional[Callable[[], Any]] = None,
                 stats_interval: float = 1.0):
        self.endpoint = endpoint
        self.engine = engine
        self.decode_req = decode_req
        self.encode_resp = encode_resp
        self.stats_handler = stats_handler
        self.stats_interval = stats_interval
        self.lease: Optional[Lease] = None
        self._inbox = None
        self._loop_task: Optional[asyncio.Task] = None
        self._stats_task: Optional[asyncio.Task] = None
        self._drain_task: Optional[asyncio.Task] = None
        self._drain_watcher = None
        self._inflight: set = set()
        self._stopping = False
        # planner drain protocol (docs/planner.md): once draining, the
        # discovery entry carries draining=true (routers stop admitting),
        # in-flight requests run to completion, and `on_drained` fires the
        # moment the server is both draining and idle — the supervisor's
        # cue that the process can stop with zero dropped requests.
        self.draining = False
        self.on_drained: Optional[Callable[[], None]] = None
        # fire-and-forget dedup window (ADVICE r2): the client's dispatch
        # retry is at-least-once; for streaming requests duplicates are
        # harmless (the client consumes only the last dialed-back stream),
        # but a request WITHOUT connection info has no stream to
        # disambiguate and real side effects — drop repeats of its id.
        self._recent_ff_ids: "collections.OrderedDict[str, float]" = \
            collections.OrderedDict()

    RECENT_ID_WINDOW = 60.0
    RECENT_ID_MAX = 4096

    def _ff_duplicate(self, rid: str) -> bool:
        """Record rid; True if it was already accepted inside the window."""
        now = time.monotonic()
        while self._recent_ff_ids:     # expire by age BEFORE the check, so
            oldest_id, t = next(iter(self._recent_ff_ids.items()))
            if now - t <= self.RECENT_ID_WINDOW:
                break
            del self._recent_ff_ids[oldest_id]
        if rid in self._recent_ff_ids:
            return True
        self._recent_ff_ids[rid] = now
        while len(self._recent_ff_ids) > self.RECENT_ID_MAX:
            # capacity-evict AFTER inserting — evicting first could evict
            # rid's own prior entry and accept the duplicate as new
            self._recent_ff_ids.popitem(last=False)
        return False

    def _ff_forget(self, rid: str) -> None:
        """The request did NOT execute — let a redelivery run it (recording
        at accept time and forgetting on failure keeps concurrent in-flight
        duplicates deduped without turning transient failures into drops)."""
        self._recent_ff_ids.pop(rid, None)

    @property
    def lease_id(self) -> int:
        assert self.lease is not None
        return self.lease.id

    async def start(self) -> None:
        rt = self.endpoint.runtime
        await rt.tcp.start()
        self.lease = await rt.primary_lease()
        subject = self.endpoint.subject(self.lease.id)
        self._inbox = await rt.bus.serve(subject)
        self._info = ComponentEndpointInfo(
            subject=subject, worker_id=self.lease.id,
            component=self.endpoint.component, endpoint=self.endpoint.name,
            namespace=self.endpoint.namespace)
        created = await rt.store.kv_create(
            self.endpoint.discovery_key(self.lease.id), self._info.to_json(),
            lease_id=self.lease.id)
        if not created:
            raise RuntimeError(
                f"endpoint already registered: {self.endpoint.path}")
        self._drain_watcher = await rt.store.watch_prefix(
            self.endpoint.drain_key(self.lease.id))
        self._drain_task = asyncio.get_running_loop().create_task(
            self._drain_watch_loop(), name=f"drain-{self.endpoint.name}")
        self._loop_task = asyncio.get_running_loop().create_task(
            self._serve_loop(), name=f"endpoint-{self.endpoint.name}")
        if self.stats_handler is not None:
            self._stats_task = asyncio.get_running_loop().create_task(
                self._stats_loop(), name=f"stats-{self.endpoint.name}")
        logger.info("serving %s as instance %x", self.endpoint.path,
                    self.lease.id)

    async def _drain_watch_loop(self) -> None:
        from .kvstore import WatchEventType
        async for ev in self._drain_watcher:
            if ev.type == WatchEventType.PUT and not self.draining:
                await self.set_draining(True)

    async def set_draining(self, flag: bool) -> None:
        """Flip the discovery entry's draining flag (re-put under our own
        lease, so liveness semantics are untouched). Requests already in
        flight — and any that race in before routers see the update — are
        still served; only NEW router admissions stop."""
        if self.lease is None or self.draining == flag:
            return
        self.draining = flag
        self._info.draining = flag
        await self.endpoint.runtime.store.kv_put(
            self.endpoint.discovery_key(self.lease.id), self._info.to_json(),
            lease_id=self.lease.id)
        logger.info("endpoint %s instance %x draining=%s (%d in flight)",
                    self.endpoint.path, self.lease.id, flag,
                    len(self._inflight))
        self._maybe_drained()

    @property
    def idle(self) -> bool:
        return not self._inflight

    def _maybe_drained(self) -> None:
        # a message can race into the inbox before routers see the
        # draining flag — count it as in-flight, not as idle
        inbox_empty = (self._inbox is None
                       or getattr(self._inbox, "_queue", None) is None
                       or self._inbox._queue.empty())
        if (self.draining and self.idle and inbox_empty
                and self.on_drained is not None):
            self.on_drained()

    async def _serve_loop(self) -> None:
        while not self._stopping:
            msg = await self._inbox.next(timeout=0.5)
            if msg is None:
                self._maybe_drained()
                continue
            task = asyncio.get_running_loop().create_task(
                self._handle(msg.payload))
            self._inflight.add(task)
            task.add_done_callback(self._request_done)

    def _request_done(self, task: asyncio.Task) -> None:
        self._inflight.discard(task)
        self._maybe_drained()

    async def _handle(self, payload: bytes) -> None:
        try:
            ctrl, body = decode_two_part(payload)
        except Exception:
            logger.exception("undecodable request envelope")
            return
        info = ctrl.connection_info
        if info is None and self._ff_duplicate(ctrl.id):
            logger.warning("dropping duplicate fire-and-forget request %s "
                           "(at-least-once re-dispatch)", ctrl.id)
            return
        sender: Optional[StreamSender] = None
        try:
            request = self.decode_req(body)
        except Exception as e:
            if info is not None:
                sender = await open_stream_sender(info, error=str(e))
                await sender.finish()
            else:
                self._ff_forget(ctrl.id)
            return
        from .engine import EngineContext
        from .faults import hit_async as _fault
        from .tracing import Trace, span, use_trace
        # deadline re-anchoring: the wire carries the REMAINING budget;
        # binding it to this side's monotonic clock here means engines
        # poll one absolute deadline with no cross-host clock coupling
        ctx = Context(request, ctx=EngineContext(
            ctrl.id, deadline_ms=ctrl.deadline_ms,
            tenant=ctrl.tenant, qos=ctrl.priority))
        # worker-side trace under the SAME request id the frontend logged
        # (ingress prologue → engine → first frame → stream end). When the
        # control message carries a propagated TraceContext this becomes a
        # CHILD of the caller's trace — the collector stitches the edge;
        # without one it stays a root (old senders, direct dispatch).
        with use_trace(Trace.from_wire(ctrl.trace, ctrl.id,
                                       role="worker")) as trace:
            with span("engine.accept"):
                try:
                    await _fault("request.ingress")
                    stream = await self.engine.generate(ctx)
                except Exception as e:
                    trace.set_error(str(e))
                    logger.exception("engine rejected request %s", ctrl.id)
                    if info is not None:
                        sender = await open_stream_sender(info, error=str(e))
                        await sender.finish()
                    else:
                        self._ff_forget(ctrl.id)
                    return
            if info is None:
                try:
                    async for _ in stream:   # fire-and-forget request type
                        pass
                except Exception:
                    self._ff_forget(ctrl.id)
                    raise
                return
            with span("dial_back"):
                sender = await open_stream_sender(info)
            sender.on_stop = ctx.ctx.stop_generating
            sender.on_kill = ctx.ctx.kill
            try:
                with span("respond"):
                    first = True
                    async for item in stream:
                        if sender.killed:
                            break
                        await sender.send(self.encode_resp(item))
                        if first:
                            first = False
                            trace.event("first_response")
                    await sender.finish()
            except (ConnectionError, OSError) as e:
                trace.set_error(f"connection lost: {e}")
                ctx.ctx.kill()
            except Exception as e:
                trace.set_error(str(e))
                logger.exception("stream failed for %s", ctrl.id)
                await sender.finish(error=str(e))

    async def _stats_loop(self) -> None:
        # long-lived task spawned from serve(): detach the caller's
        # ambient trace so the periodic kv_put's netstore spans never
        # attach to whatever request started the server (DL002)
        from .tracing import detach_trace
        detach_trace()
        rt = self.endpoint.runtime
        key = self.endpoint.stats_key(self.lease.id)
        while not self._stopping:
            try:
                data = self.stats_handler()
                await rt.store.kv_put(key, _default_encode(data),
                                      lease_id=self.lease.id)
            except Exception:
                logger.exception("stats publish failed")
            await asyncio.sleep(self.stats_interval)

    async def stop(self) -> None:
        self._stopping = True
        rt = self.endpoint.runtime
        if self._loop_task is not None:
            self._loop_task.cancel()
        if self._stats_task is not None:
            self._stats_task.cancel()
        if self._drain_task is not None:
            self._drain_task.cancel()
        if self._drain_watcher is not None:
            self._drain_watcher.close()
        for t in list(self._inflight):
            t.cancel()
        if self.lease is not None:
            # best-effort, bounded deregistration: if the daemon is gone,
            # lease expiry cleans these up anyway — shutdown must never
            # hang in the netstore reconnect window
            async def _deregister() -> None:
                await rt.bus.unserve(
                    self.endpoint.subject(self.lease.id))
                await rt.store.kv_delete(
                    self.endpoint.discovery_key(self.lease.id))
                if self._stats_task is not None:
                    await rt.store.kv_delete(
                        self.endpoint.stats_key(self.lease.id))

            try:
                # wait_for, not asyncio.timeout: 3.10-compatible
                await asyncio.wait_for(_deregister(), timeout=2.0)
            except (asyncio.TimeoutError, TimeoutError, ConnectionError,
                    OSError):
                logger.warning("endpoint %s deregistration skipped (daemon "
                               "unreachable); lease expiry will clean up",
                               self.endpoint.path)
        if self in rt._servers:
            rt._servers.remove(self)
