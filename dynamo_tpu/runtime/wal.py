"""Write-ahead log + snapshot for the discovery/bus daemon.

VERDICT r3 missing #2 / weak #6: the reference's discovery store is etcd —
raft-replicated and crash-DURABLE (lib/runtime/src/transports/etcd.rs:38-360)
— and its prefill queue is a JetStream *durable* consumer on a work-queue
stream (examples/llm/utils/nats_queue.py:89-99): an acknowledged enqueue
survives a broker crash, and a delivered-but-unacked item is redelivered.
Our daemon held everything in memory, so a crash with queue depth > 0
silently dropped accepted remote-prefill requests.

This module gives the daemon the same contract:

- every mutating op is appended to ``wal.jsonl`` and **fsync'd before the
  client sees the reply** — acknowledged therefore means durable, exactly
  the etcd-fsync / JetStream-publish-ack semantic;
- a ``snapshot.json`` is written (atomic tmp+rename) every
  ``snapshot_every`` records and on graceful close, after which the WAL is
  truncated — recovery cost stays bounded;
- recovery = load snapshot, replay WAL on top.

What is deliberately NOT persisted (matching the reference):
- pub/sub subscriptions and served subjects — connection-scoped; clients
  re-register on reconnect (NATS core is fire-and-forget too);
- queue in-flight state — a delivered-but-unacked item reverts to pending
  on restart and is REDELIVERED (at-least-once, the JetStream work-queue
  semantic; consumers dedup by request id);
- lease deadlines — a restored lease gets a fresh TTL window; a client
  that died while the daemon was down simply fails to refresh and the
  lease expires one TTL later (etcd restores lease TTLs the same way).
"""

from __future__ import annotations

import json
import logging
import os
from typing import Iterator, Optional, Tuple

__all__ = ["Wal"]

logger = logging.getLogger("dynamo_tpu.runtime.wal")


def _fsync_dir(path: str) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)  # dynalint: ok DL001 directory-entry durability for the atomic snapshot rename
    finally:
        os.close(fd)


class Wal:
    """Append-only JSONL WAL with a sidecar snapshot, in ``data_dir``."""

    def __init__(self, data_dir: str, *, snapshot_every: int = 1000,
                 fsync: bool = True):
        self.data_dir = data_dir
        self.snapshot_path = os.path.join(data_dir, "snapshot.json")
        self.wal_path = os.path.join(data_dir, "wal.jsonl")
        self.snapshot_every = snapshot_every
        self.fsync = fsync
        self._since_snapshot = 0
        os.makedirs(data_dir, exist_ok=True)
        self._f = None

    # ------------------------------------------------------------ recovery
    def load(self) -> Tuple[Optional[dict], Iterator[dict]]:
        """(snapshot or None, iterator of WAL records). A torn final WAL
        line (crash mid-append) is skipped — it was never acknowledged."""
        snap = None
        try:
            with open(self.snapshot_path) as f:
                snap = json.load(f)
        except (OSError, ValueError):
            snap = None

        def records():
            try:
                with open(self.wal_path) as f:
                    lines = f.readlines()
            except OSError:
                return
            for i, line in enumerate(lines):
                line = line.strip()
                if not line:
                    continue
                try:
                    yield json.loads(line)
                except ValueError:
                    if i + 1 < len(lines):
                        # a torn FINAL line is the expected crash shape
                        # (never acknowledged); corruption mid-file means
                        # acknowledged records after it are being dropped
                        # — recovery proceeds but must say so
                        logger.warning(
                            "WAL %s corrupt at line %d of %d; %d later "
                            "records are unrecoverable", self.wal_path,
                            i + 1, len(lines), len(lines) - i - 1)
                    return

        return snap, records()

    # ------------------------------------------------------------- logging
    def _file(self):
        if self._f is None:
            self._f = open(self.wal_path, "a")  # dynalint: ok DL001 first-append open of the durable WAL (acknowledged-is-durable trade)
        return self._f

    def append(self, rec: dict) -> None:
        """Durably append one record; returns only once it is on disk.
        Raises OSError (e.g. ENOSPC) when the disk refuses — the daemon
        fails THAT op to its caller instead of acknowledging an append
        that never became durable."""
        from .faults import hit as _fault
        _fault("wal.append")                 # enospc/delay chaos site
        f = self._file()
        f.write(json.dumps(rec) + "\n")
        f.flush()
        if self.fsync:
            # dynalint: ok DL001 fsync-per-commit IS the durability contract (etcd semantics; wal.py module docstring)
            os.fsync(f.fileno())
        self._since_snapshot += 1

    def due_for_snapshot(self) -> bool:
        return self._since_snapshot >= self.snapshot_every

    def write_snapshot(self, state: dict) -> None:
        """Atomically replace the snapshot, then truncate the WAL (its
        records are now folded into the snapshot)."""
        tmp = self.snapshot_path + ".tmp"
        # dynalint: ok DL001 snapshot fold rides the same acknowledged-is-durable trade as append
        with open(tmp, "w") as f:
            json.dump(state, f)
            f.flush()
            os.fsync(f.fileno())  # dynalint: ok DL001 snapshot durability before the rename publishes it
        os.replace(tmp, self.snapshot_path)
        _fsync_dir(self.data_dir)
        if self._f is not None:
            self._f.close()
            self._f = None
        # dynalint: ok DL001 WAL truncate must be durable before appends resume
        with open(self.wal_path, "w") as f:
            f.flush()
            os.fsync(f.fileno())  # dynalint: ok DL001 truncation durability (records are folded into the snapshot)
        self._since_snapshot = 0

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None
