"""Layered runtime configuration.

Reference: lib/runtime/src/config.rs:24-170 — figment layering: built-in
defaults → ``/opt/dynamo/defaults/runtime.toml`` → ``/opt/dynamo/etc/
runtime.toml`` → env ``DYN_RUNTIME_*`` / ``DYN_WORKER_*``, producing
``RuntimeConfig{num_worker_threads, max_blocking_threads}`` and
``WorkerConfig``. Python analog with the same precedence:

    defaults → DYN_RUNTIME_CONFIG_PATH toml (or /opt/dynamo_tpu/etc/
    runtime.toml when present) → DYN_RUNTIME_* / DYN_WORKER_* env

Field name mapping: env keys are upper-snake of the field, e.g.
``DYN_RUNTIME_LEASE_TTL=5`` or ``DYN_WORKER_GRACEFUL_SHUTDOWN_TIMEOUT=10``.
"""

from __future__ import annotations

import dataclasses
import logging
import os

try:
    import tomllib
except ModuleNotFoundError:          # Python < 3.11
    import tomli as tomllib
from typing import Any, Optional

logger = logging.getLogger("dynamo_tpu.runtime.config")

_DEFAULT_TOML_PATHS = ("/opt/dynamo_tpu/defaults/runtime.toml",
                       "/opt/dynamo_tpu/etc/runtime.toml")

__all__ = ["RuntimeConfig", "WorkerConfig", "load_runtime_config",
           "load_worker_config"]


@dataclasses.dataclass
class RuntimeConfig:
    """Process-wide runtime knobs (reference RuntimeConfig)."""

    lease_ttl: float = 10.0            # discovery lease TTL seconds
    tcp_host: str = "127.0.0.1"        # response-plane bind host
    native_dataplane: bool = True      # C++ sender when buildable
    native_kvpool: bool = True         # C++ reuse pool when buildable
    max_blocking_threads: int = 64     # asyncio default-executor cap


@dataclasses.dataclass
class WorkerConfig:
    """Worker main-wrapper knobs (reference WorkerConfig, worker.rs)."""

    graceful_shutdown_timeout: float = 30.0
    discovery_addr: str = ""
    advertise_host: Optional[str] = None


def _coerce(value: str, type_name: str) -> Any:
    """Env string → the field's declared type (annotations are strings
    under `from __future__ import annotations`)."""
    if type_name == "bool":
        return value.strip().lower() not in ("0", "false", "no", "")
    if type_name == "float":
        return float(value)
    if type_name == "int":
        return int(value)
    if type_name.startswith("Optional"):
        return value or None
    return value


def _layer(cls, section: str, env_prefix: str):
    """defaults → toml [section] → env ``{env_prefix}_FIELD``."""
    values: dict = {}
    # toml layer
    paths = [p for p in _DEFAULT_TOML_PATHS if os.path.exists(p)]
    explicit = os.environ.get("DYN_RUNTIME_CONFIG_PATH")
    if explicit:
        paths.append(explicit)
    for path in paths:
        try:
            with open(path, "rb") as f:
                data = tomllib.load(f)
        except (OSError, tomllib.TOMLDecodeError) as e:
            logger.warning("skipping config file %s: %s", path, e)
            continue
        values.update(data.get(section, {}))
    # env layer (highest precedence)
    fields = {f.name: f for f in dataclasses.fields(cls)}
    kwargs: dict = {}
    for name, f in fields.items():
        if name in values:
            kwargs[name] = values[name]
        env_key = f"{env_prefix}_{name.upper()}"
        if env_key in os.environ:
            kwargs[name] = _coerce(os.environ[env_key], str(f.type))
    unknown = set(values) - set(fields)
    if unknown:
        logger.warning("unknown %s config keys ignored: %s", section,
                       sorted(unknown))
    return cls(**kwargs)


def load_runtime_config() -> RuntimeConfig:
    return _layer(RuntimeConfig, "runtime", "DYN_RUNTIME")


def load_worker_config() -> WorkerConfig:
    cfg = _layer(WorkerConfig, "worker", "DYN_WORKER")
    # legacy/primary env names used elsewhere in the runtime keep working
    if "DYN_DISCOVERY_ADDR" in os.environ:
        cfg.discovery_addr = os.environ["DYN_DISCOVERY_ADDR"]
    if "DYN_ADVERTISE_HOST" in os.environ:
        cfg.advertise_host = os.environ["DYN_ADVERTISE_HOST"]
    return cfg
