"""Self-hosted discovery + message-bus daemon.

The reference delegates discovery to etcd and the request/event planes to
NATS (docker-compose externals, SURVEY.md layer 0). Neither exists in this
image, so the TPU build ships its own daemon speaking a small length-prefixed
JSON protocol; the server-side state machine *is* the in-memory store/bus
(runtime/kvstore.py, runtime/bus.py), so semantics are identical between the
single-process and networked deployments — the reference gets the same
property from testing against real etcd/NATS in one process (SURVEY.md §4).

Run: ``python -m dynamo_tpu.runtime.server --host 0.0.0.0 --port 6510``

Wire format: ``[u32 len][json]`` both ways. Client→server messages carry
``rid`` (request id) and ``op``; server replies ``{"rid", "ok", ...}`` and
pushes unsolicited events as ``{"push": "watch"|"msg", ...}``. Bytes travel
base64 (values, payloads).
"""

from __future__ import annotations

import argparse
import asyncio
import base64
import json
import logging
import struct
from typing import Dict, Optional

from .bus import MemoryBus
from .kvstore import MemoryKvStore, WatchEventType
from .wal import Wal

logger = logging.getLogger("dynamo_tpu.runtime.server")

_LEN = struct.Struct(">I")
MAX_MSG = 256 * 1024 * 1024


def _b64(b: bytes) -> str:
    return base64.b64encode(b).decode()


def _unb64(s: str) -> bytes:
    return base64.b64decode(s)


async def send_msg(writer: asyncio.StreamWriter, msg: dict) -> None:
    raw = json.dumps(msg).encode()
    writer.write(_LEN.pack(len(raw)) + raw)
    await writer.drain()


async def recv_msg(reader: asyncio.StreamReader) -> Optional[dict]:
    try:
        hdr = await reader.readexactly(_LEN.size)
    except (asyncio.IncompleteReadError, ConnectionResetError):
        return None
    (n,) = _LEN.unpack(hdr)
    if n > MAX_MSG:
        raise ValueError(f"message too large: {n}")
    raw = await reader.readexactly(n)
    return json.loads(raw)


class _ClientSession:
    """One connected client: demuxes ops onto the shared store/bus, tracks
    its watchers/subscriptions/served subjects for cleanup on disconnect."""

    def __init__(self, server: "DiscoveryServer",
                 reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        self.server = server
        self.reader = reader
        self.writer = writer
        self.watchers: Dict[int, object] = {}
        self.subs: Dict[int, object] = {}
        self.served: Dict[int, str] = {}
        self._next_handle = 1
        self._tasks: set = set()
        self._write_lock = asyncio.Lock()

    async def send(self, msg: dict) -> None:
        async with self._write_lock:
            try:
                await send_msg(self.writer, msg)
            except (ConnectionError, OSError):
                pass

    def _spawn(self, coro) -> None:
        t = asyncio.get_running_loop().create_task(coro)
        self._tasks.add(t)
        t.add_done_callback(self._tasks.discard)

    async def run(self) -> None:
        try:
            while True:
                msg = await recv_msg(self.reader)
                if msg is None:
                    return
                # each op handled in its own task → a blocking dequeue never
                # stalls the connection; rid-matched replies may interleave
                self._spawn(self._dispatch(msg))
        except (ConnectionError, ValueError):
            pass
        finally:
            await self._cleanup()

    async def _dispatch(self, msg: dict) -> None:
        rid = msg.get("rid")
        op = msg.get("op", "")
        store, bus = self.server.store, self.server.bus
        # WAL discipline: log IMMEDIATELY after the (synchronous-body)
        # store/bus mutation with no await in between, and BEFORE the
        # reply — so WAL order matches mutation order and an acknowledged
        # op is already on disk (wal.py module docstring)
        log = self.server.wal_append
        try:
            if op == "kv_create":
                ok = await store.kv_create(msg["key"], _unb64(msg["value"]),
                                           msg.get("lease", 0))
                if ok:
                    log({"op": "kv_put", "key": msg["key"],
                         "value": msg["value"],
                         "lease": msg.get("lease", 0)})
                await self.send({"rid": rid, "ok": True, "result": ok})
            elif op == "kv_create_or_validate":
                existed = await store.kv_get(msg["key"]) is not None
                ok = await store.kv_create_or_validate(
                    msg["key"], _unb64(msg["value"]), msg.get("lease", 0))
                if ok and not existed:
                    # log only the actual CREATE: the validated-equal case
                    # mutates nothing, and logging it would re-home the
                    # key to the second caller's lease on replay
                    log({"op": "kv_put", "key": msg["key"],
                         "value": msg["value"],
                         "lease": msg.get("lease", 0)})
                await self.send({"rid": rid, "ok": True, "result": ok})
            elif op == "kv_put":
                await store.kv_put(msg["key"], _unb64(msg["value"]),
                                   msg.get("lease", 0))
                log({"op": "kv_put", "key": msg["key"],
                     "value": msg["value"], "lease": msg.get("lease", 0)})
                await self.send({"rid": rid, "ok": True})
            elif op == "kv_cas":
                exp = msg.get("expected")
                ok = await store.kv_cas(
                    msg["key"], _unb64(exp) if exp is not None else None,
                    _unb64(msg["value"]), msg.get("lease", 0))
                if ok:
                    log({"op": "kv_put", "key": msg["key"],
                         "value": msg["value"],
                         "lease": msg.get("lease", 0)})
                await self.send({"rid": rid, "ok": True, "result": ok})
            elif op == "kv_get":
                e = await store.kv_get(msg["key"])
                await self.send({
                    "rid": rid, "ok": True,
                    "entry": None if e is None else
                    {"key": e.key, "value": _b64(e.value), "lease": e.lease_id}})
            elif op == "kv_get_prefix":
                es = await store.kv_get_prefix(msg["prefix"])
                await self.send({
                    "rid": rid, "ok": True,
                    "entries": [{"key": e.key, "value": _b64(e.value),
                                 "lease": e.lease_id} for e in es]})
            elif op == "kv_delete":
                ok = await store.kv_delete(msg["key"])
                if ok:
                    log({"op": "kv_delete", "key": msg["key"]})
                await self.send({"rid": rid, "ok": True, "result": ok})
            elif op == "watch_prefix":
                wid = msg["wid"]      # client-allocated: pushes are routable
                watcher = await store.watch_prefix(msg["prefix"])
                self.watchers[wid] = watcher
                await self.send({"rid": rid, "ok": True, "wid": wid})
                self._spawn(self._pump_watch(wid, watcher))
            elif op == "watch_close":
                w = self.watchers.pop(msg["wid"], None)
                if w is not None:
                    w.close()
                await self.send({"rid": rid, "ok": True})
            elif op == "lease_create":
                lease = await store.lease_create(msg["ttl"],
                                                 want_id=msg.get("want_id", 0))
                log({"op": "lease", "id": lease.id, "ttl": msg["ttl"]})
                await self.send({"rid": rid, "ok": True, "lease_id": lease.id})
            elif op == "lease_refresh":
                # NOT logged: liveness is runtime state; a restored lease
                # gets a fresh TTL window (wal.py)
                ok = await store.lease_refresh(msg["lease_id"])
                await self.send({"rid": rid, "ok": True, "result": ok})
            elif op == "lease_revoke":
                # logged via the store's on_lease_drop hook (shared with
                # TTL expiry, which must also reach the WAL)
                await store.lease_revoke(msg["lease_id"])
                await self.send({"rid": rid, "ok": True})
            elif op == "publish":
                n = await bus.publish(msg["subject"], _unb64(msg["payload"]))
                await self.send({"rid": rid, "ok": True, "receivers": n})
            elif op == "subscribe":
                sid = msg["sid"]
                sub = await bus.subscribe(msg["pattern"])
                self.subs[sid] = sub
                await self.send({"rid": rid, "ok": True, "sid": sid})
                self._spawn(self._pump_sub(sid, sub))
            elif op == "serve":
                sid = msg["sid"]
                sub = await bus.serve(msg["subject"])
                self.subs[sid] = sub
                self.served[sid] = msg["subject"]
                await self.send({"rid": rid, "ok": True, "sid": sid})
                self._spawn(self._pump_sub(sid, sub))
            elif op == "unserve":
                await bus.unserve(msg["subject"])
                gone = [sid for sid, s in self.served.items()
                        if s == msg["subject"]]
                for sid in gone:
                    self.served.pop(sid, None)
                    self.subs.pop(sid, None)
                await self.send({"rid": rid, "ok": True})
            elif op == "sub_close":
                sub = self.subs.pop(msg["sid"], None)
                if sub is not None:
                    sub.close()
                self.served.pop(msg["sid"], None)
                await self.send({"rid": rid, "ok": True})
            elif op == "wq_enqueue":
                q = await bus.work_queue(msg["queue"])
                iid = await q.enqueue(_unb64(msg["payload"]))
                log({"op": "wq_enqueue", "queue": msg["queue"],
                     "id": iid, "payload": msg["payload"]})
                await self.send({"rid": rid, "ok": True, "id": iid})
            elif op == "wq_dequeue":
                q = await bus.work_queue(msg["queue"])
                item = await q.dequeue(timeout=msg.get("timeout"),
                                       ack_deadline=msg.get("ack_deadline", 30.0))
                await self.send({
                    "rid": rid, "ok": True,
                    "item": None if item is None else
                    {"id": item.id, "payload": _b64(item.payload),
                     "deliveries": item.deliveries}})
            elif op == "wq_ack":
                q = await bus.work_queue(msg["queue"])
                await q.ack(msg["id"])
                log({"op": "wq_ack", "queue": msg["queue"],
                     "id": msg["id"]})
                await self.send({"rid": rid, "ok": True})
            elif op == "wq_nack":
                q = await bus.work_queue(msg["queue"])
                await q.nack(msg["id"])
                await self.send({"rid": rid, "ok": True})
            elif op == "wq_depth":
                q = await bus.work_queue(msg["queue"])
                await self.send({"rid": rid, "ok": True,
                                 "depth": await q.depth()})
            elif op == "ping":
                await self.send({"rid": rid, "ok": True})
            else:
                await self.send({"rid": rid, "ok": False,
                                 "error": f"unknown op {op!r}"})
        except Exception as e:  # noqa: BLE001 — protocol boundary
            logger.exception("op %s failed", op)
            await self.send({"rid": rid, "ok": False, "error": str(e)})

    async def _pump_watch(self, wid: int, watcher) -> None:
        async for ev in watcher:
            await self.send({
                "push": "watch", "wid": wid,
                "type": "put" if ev.type == WatchEventType.PUT else "delete",
                "key": ev.entry.key, "value": _b64(ev.entry.value),
                "lease": ev.entry.lease_id})

    async def _pump_sub(self, sid: int, sub) -> None:
        async for m in sub:
            await self.send({"push": "msg", "sid": sid,
                             "subject": m.subject, "payload": _b64(m.payload)})

    async def _cleanup(self) -> None:
        for t in list(self._tasks):
            t.cancel()
        for w in self.watchers.values():
            w.close()
        for sub in self.subs.values():
            sub.close()
        # leases are NOT dropped here: liveness is TTL-based (a client that
        # reconnects within its TTL keeps its identity, exactly like etcd)
        if not self.writer.is_closing():
            self.writer.close()


class DiscoveryServer:
    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 data_dir: Optional[str] = None, *, wal_fsync: bool = True):
        self.host = host
        self.port = port
        self.store = MemoryKvStore()
        self.bus = MemoryBus()
        self.wal: Optional[Wal] = (
            Wal(data_dir, fsync=wal_fsync) if data_dir else None)
        self._server: Optional[asyncio.base_events.Server] = None
        self._sessions: set = set()

    def wal_append(self, rec: dict) -> None:
        """Durably log one mutation (no-op without --data-dir). Called by
        sessions immediately after applying the mutation, before the
        reply; the fsync blocks the event loop for the write — the
        acknowledged-is-durable trade, same as etcd's fsync-per-commit."""
        if self.wal is None:
            return
        self.wal.append(rec)
        if self.wal.due_for_snapshot():
            self._write_snapshot()

    def _write_snapshot(self) -> None:
        assert self.wal is not None
        self.wal.write_snapshot({"store": self.store.dump_state(),
                                 "bus": self.bus.dump_state()})

    async def _recover(self) -> int:
        assert self.wal is not None
        snap, records = await asyncio.to_thread(self.wal.load)
        if snap is not None:
            await self.store.restore_state(snap.get("store", {}))
            await self.bus.restore_state(snap.get("bus", {}))
        n = 0
        for rec in records:
            await self._apply_wal_record(rec)
            n += 1
        if snap is not None or n:
            logger.info("recovered state: snapshot=%s, %d WAL records",
                        snap is not None, n)
        return n

    async def _apply_wal_record(self, rec: dict) -> None:
        op = rec.get("op")
        if op == "kv_put":
            await self.store.kv_put(rec["key"], _unb64(rec["value"]),
                                    rec.get("lease", 0))
        elif op == "kv_delete":
            await self.store.kv_delete(rec["key"])
        elif op == "lease":
            try:
                await self.store.lease_create(float(rec["ttl"]),
                                              want_id=int(rec["id"]))
            except RuntimeError:
                pass                      # already restored from snapshot
        elif op == "lease_revoke":
            await self.store.lease_revoke(int(rec["id"]))
        elif op == "wq_enqueue":
            q = await self.bus.work_queue(rec["queue"])
            q.restore_item(int(rec["id"]), _unb64(rec["payload"]))
        elif op == "wq_ack":
            q = await self.bus.work_queue(rec["queue"])
            await q.ack(int(rec["id"]))
        else:
            logger.warning("unknown WAL record op %r (skipped)", op)

    async def start(self) -> None:
        if self.wal is not None:
            replayed = await self._recover()
            if replayed:
                # fold a non-trivial replay immediately: without this the
                # WAL grows without bound across crash-restart cycles
                # (each run replays the previous runs' records but never
                # reaches the in-run snapshot threshold). No sessions yet,
                # so the off-thread fold cannot race a wal_append.
                await asyncio.to_thread(self._write_snapshot)
        # hook AFTER recovery (a replayed lease_revoke must not re-log):
        # every lease drop — explicit revoke or TTL expiry — reaches the
        # WAL, so a crash after an expiry cannot resurrect the dead
        # worker's lease+keys from stale records
        self.store.on_lease_drop = (
            lambda lid: self.wal_append({"op": "lease_revoke", "id": lid}))
        self._server = await asyncio.start_server(
            self._on_conn, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        logger.info("discovery/bus daemon on %s:%d", self.host, self.port)

    async def _on_conn(self, reader, writer) -> None:
        session = _ClientSession(self, reader, writer)
        self._sessions.add(session)
        try:
            await session.run()
        finally:
            self._sessions.discard(session)

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    async def close(self) -> None:
        server, self._server = self._server, None  # claim (DL008)
        if server is not None:
            server.close()
            # drop live client connections too: wait_closed() (3.12+)
            # otherwise blocks on them, and a killed daemon must look
            # KILLED to clients (their reconnect path takes over)
            for session in list(self._sessions):
                if not session.writer.is_closing():
                    session.writer.close()
            await server.wait_closed()
        if self.wal is not None:
            # fold the WAL on graceful exit; sessions are closed above,
            # so no wal_append can race the off-thread fold
            await asyncio.to_thread(self._write_snapshot)
            self.wal.close()
        await self.store.close()


async def _amain(host: str, port: int, data_dir: Optional[str]) -> None:
    srv = DiscoveryServer(host, port, data_dir)
    await srv.start()
    print(f"dynamo-tpu discovery/bus daemon listening on {srv.address}",
          flush=True)
    try:
        await asyncio.Event().wait()
    finally:
        await srv.close()


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=6510)
    ap.add_argument("--data-dir", default=None,
                    help="persist KV/lease/queue state here (WAL + "
                         "snapshot); omit for a purely in-memory daemon")
    args = ap.parse_args()
    from .log import setup_logging
    setup_logging()
    try:
        asyncio.run(_amain(args.host, args.port, args.data_dir))
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
