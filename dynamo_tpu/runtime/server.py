"""Self-hosted discovery + message-bus daemon.

The reference delegates discovery to etcd and the request/event planes to
NATS (docker-compose externals, SURVEY.md layer 0). Neither exists in this
image, so the TPU build ships its own daemon speaking a small length-prefixed
JSON protocol; the server-side state machine *is* the in-memory store/bus
(runtime/kvstore.py, runtime/bus.py), so semantics are identical between the
single-process and networked deployments — the reference gets the same
property from testing against real etcd/NATS in one process (SURVEY.md §4).

Run: ``python -m dynamo_tpu.runtime.server --host 0.0.0.0 --port 6510``

Wire format: ``[u32 len][json]`` both ways. Client→server messages carry
``rid`` (request id) and ``op``; server replies ``{"rid", "ok", ...}`` and
pushes unsolicited events as ``{"push": "watch"|"msg", ...}``. Bytes travel
base64 (values, payloads).
"""

from __future__ import annotations

import argparse
import asyncio
import base64
import json
import logging
import struct
from typing import Dict, Optional

from .bus import MemoryBus
from .kvstore import MemoryKvStore, WatchEventType

logger = logging.getLogger("dynamo_tpu.runtime.server")

_LEN = struct.Struct(">I")
MAX_MSG = 256 * 1024 * 1024


def _b64(b: bytes) -> str:
    return base64.b64encode(b).decode()


def _unb64(s: str) -> bytes:
    return base64.b64decode(s)


async def send_msg(writer: asyncio.StreamWriter, msg: dict) -> None:
    raw = json.dumps(msg).encode()
    writer.write(_LEN.pack(len(raw)) + raw)
    await writer.drain()


async def recv_msg(reader: asyncio.StreamReader) -> Optional[dict]:
    try:
        hdr = await reader.readexactly(_LEN.size)
    except (asyncio.IncompleteReadError, ConnectionResetError):
        return None
    (n,) = _LEN.unpack(hdr)
    if n > MAX_MSG:
        raise ValueError(f"message too large: {n}")
    raw = await reader.readexactly(n)
    return json.loads(raw)


class _ClientSession:
    """One connected client: demuxes ops onto the shared store/bus, tracks
    its watchers/subscriptions/served subjects for cleanup on disconnect."""

    def __init__(self, server: "DiscoveryServer",
                 reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        self.server = server
        self.reader = reader
        self.writer = writer
        self.watchers: Dict[int, object] = {}
        self.subs: Dict[int, object] = {}
        self.served: Dict[int, str] = {}
        self._next_handle = 1
        self._tasks: set = set()
        self._write_lock = asyncio.Lock()

    async def send(self, msg: dict) -> None:
        async with self._write_lock:
            try:
                await send_msg(self.writer, msg)
            except (ConnectionError, OSError):
                pass

    def _spawn(self, coro) -> None:
        t = asyncio.get_running_loop().create_task(coro)
        self._tasks.add(t)
        t.add_done_callback(self._tasks.discard)

    async def run(self) -> None:
        try:
            while True:
                msg = await recv_msg(self.reader)
                if msg is None:
                    return
                # each op handled in its own task → a blocking dequeue never
                # stalls the connection; rid-matched replies may interleave
                self._spawn(self._dispatch(msg))
        except (ConnectionError, ValueError):
            pass
        finally:
            await self._cleanup()

    async def _dispatch(self, msg: dict) -> None:
        rid = msg.get("rid")
        op = msg.get("op", "")
        store, bus = self.server.store, self.server.bus
        try:
            if op == "kv_create":
                ok = await store.kv_create(msg["key"], _unb64(msg["value"]),
                                           msg.get("lease", 0))
                await self.send({"rid": rid, "ok": True, "result": ok})
            elif op == "kv_create_or_validate":
                ok = await store.kv_create_or_validate(
                    msg["key"], _unb64(msg["value"]), msg.get("lease", 0))
                await self.send({"rid": rid, "ok": True, "result": ok})
            elif op == "kv_put":
                await store.kv_put(msg["key"], _unb64(msg["value"]),
                                   msg.get("lease", 0))
                await self.send({"rid": rid, "ok": True})
            elif op == "kv_cas":
                exp = msg.get("expected")
                ok = await store.kv_cas(
                    msg["key"], _unb64(exp) if exp is not None else None,
                    _unb64(msg["value"]), msg.get("lease", 0))
                await self.send({"rid": rid, "ok": True, "result": ok})
            elif op == "kv_get":
                e = await store.kv_get(msg["key"])
                await self.send({
                    "rid": rid, "ok": True,
                    "entry": None if e is None else
                    {"key": e.key, "value": _b64(e.value), "lease": e.lease_id}})
            elif op == "kv_get_prefix":
                es = await store.kv_get_prefix(msg["prefix"])
                await self.send({
                    "rid": rid, "ok": True,
                    "entries": [{"key": e.key, "value": _b64(e.value),
                                 "lease": e.lease_id} for e in es]})
            elif op == "kv_delete":
                ok = await store.kv_delete(msg["key"])
                await self.send({"rid": rid, "ok": True, "result": ok})
            elif op == "watch_prefix":
                wid = msg["wid"]      # client-allocated: pushes are routable
                watcher = await store.watch_prefix(msg["prefix"])
                self.watchers[wid] = watcher
                await self.send({"rid": rid, "ok": True, "wid": wid})
                self._spawn(self._pump_watch(wid, watcher))
            elif op == "watch_close":
                w = self.watchers.pop(msg["wid"], None)
                if w is not None:
                    w.close()
                await self.send({"rid": rid, "ok": True})
            elif op == "lease_create":
                lease = await store.lease_create(msg["ttl"],
                                                 want_id=msg.get("want_id", 0))
                await self.send({"rid": rid, "ok": True, "lease_id": lease.id})
            elif op == "lease_refresh":
                ok = await store.lease_refresh(msg["lease_id"])
                await self.send({"rid": rid, "ok": True, "result": ok})
            elif op == "lease_revoke":
                await store.lease_revoke(msg["lease_id"])
                await self.send({"rid": rid, "ok": True})
            elif op == "publish":
                n = await bus.publish(msg["subject"], _unb64(msg["payload"]))
                await self.send({"rid": rid, "ok": True, "receivers": n})
            elif op == "subscribe":
                sid = msg["sid"]
                sub = await bus.subscribe(msg["pattern"])
                self.subs[sid] = sub
                await self.send({"rid": rid, "ok": True, "sid": sid})
                self._spawn(self._pump_sub(sid, sub))
            elif op == "serve":
                sid = msg["sid"]
                sub = await bus.serve(msg["subject"])
                self.subs[sid] = sub
                self.served[sid] = msg["subject"]
                await self.send({"rid": rid, "ok": True, "sid": sid})
                self._spawn(self._pump_sub(sid, sub))
            elif op == "unserve":
                await bus.unserve(msg["subject"])
                gone = [sid for sid, s in self.served.items()
                        if s == msg["subject"]]
                for sid in gone:
                    self.served.pop(sid, None)
                    self.subs.pop(sid, None)
                await self.send({"rid": rid, "ok": True})
            elif op == "sub_close":
                sub = self.subs.pop(msg["sid"], None)
                if sub is not None:
                    sub.close()
                self.served.pop(msg["sid"], None)
                await self.send({"rid": rid, "ok": True})
            elif op == "wq_enqueue":
                q = await bus.work_queue(msg["queue"])
                iid = await q.enqueue(_unb64(msg["payload"]))
                await self.send({"rid": rid, "ok": True, "id": iid})
            elif op == "wq_dequeue":
                q = await bus.work_queue(msg["queue"])
                item = await q.dequeue(timeout=msg.get("timeout"),
                                       ack_deadline=msg.get("ack_deadline", 30.0))
                await self.send({
                    "rid": rid, "ok": True,
                    "item": None if item is None else
                    {"id": item.id, "payload": _b64(item.payload),
                     "deliveries": item.deliveries}})
            elif op == "wq_ack":
                q = await bus.work_queue(msg["queue"])
                await q.ack(msg["id"])
                await self.send({"rid": rid, "ok": True})
            elif op == "wq_nack":
                q = await bus.work_queue(msg["queue"])
                await q.nack(msg["id"])
                await self.send({"rid": rid, "ok": True})
            elif op == "wq_depth":
                q = await bus.work_queue(msg["queue"])
                await self.send({"rid": rid, "ok": True,
                                 "depth": await q.depth()})
            elif op == "ping":
                await self.send({"rid": rid, "ok": True})
            else:
                await self.send({"rid": rid, "ok": False,
                                 "error": f"unknown op {op!r}"})
        except Exception as e:  # noqa: BLE001 — protocol boundary
            logger.exception("op %s failed", op)
            await self.send({"rid": rid, "ok": False, "error": str(e)})

    async def _pump_watch(self, wid: int, watcher) -> None:
        async for ev in watcher:
            await self.send({
                "push": "watch", "wid": wid,
                "type": "put" if ev.type == WatchEventType.PUT else "delete",
                "key": ev.entry.key, "value": _b64(ev.entry.value),
                "lease": ev.entry.lease_id})

    async def _pump_sub(self, sid: int, sub) -> None:
        async for m in sub:
            await self.send({"push": "msg", "sid": sid,
                             "subject": m.subject, "payload": _b64(m.payload)})

    async def _cleanup(self) -> None:
        for t in list(self._tasks):
            t.cancel()
        for w in self.watchers.values():
            w.close()
        for sub in self.subs.values():
            sub.close()
        # leases are NOT dropped here: liveness is TTL-based (a client that
        # reconnects within its TTL keeps its identity, exactly like etcd)
        if not self.writer.is_closing():
            self.writer.close()


class DiscoveryServer:
    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.host = host
        self.port = port
        self.store = MemoryKvStore()
        self.bus = MemoryBus()
        self._server: Optional[asyncio.base_events.Server] = None
        self._sessions: set = set()

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._on_conn, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        logger.info("discovery/bus daemon on %s:%d", self.host, self.port)

    async def _on_conn(self, reader, writer) -> None:
        session = _ClientSession(self, reader, writer)
        self._sessions.add(session)
        try:
            await session.run()
        finally:
            self._sessions.discard(session)

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    async def close(self) -> None:
        if self._server is not None:
            self._server.close()
            # drop live client connections too: wait_closed() (3.12+)
            # otherwise blocks on them, and a killed daemon must look
            # KILLED to clients (their reconnect path takes over)
            for session in list(self._sessions):
                if not session.writer.is_closing():
                    session.writer.close()
            await self._server.wait_closed()
            self._server = None
        await self.store.close()


async def _amain(host: str, port: int) -> None:
    srv = DiscoveryServer(host, port)
    await srv.start()
    print(f"dynamo-tpu discovery/bus daemon listening on {srv.address}",
          flush=True)
    try:
        await asyncio.Event().wait()
    finally:
        await srv.close()


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=6510)
    args = ap.parse_args()
    from .log import setup_logging
    setup_logging()
    try:
        asyncio.run(_amain(args.host, args.port))
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
