"""Response-plane TCP transport: callers run a stream server; workers dial
back and stream response frames.

Reference: lib/runtime/src/pipeline/network/tcp/{server,client}.rs — the
request travels over the message bus, but the response is a raw TCP stream
from worker to caller (``TcpStreamServer`` + ``StreamSender/StreamReceiver``),
so large token streams never transit the bus. The socket is bidirectional:
the caller can push ``STOP``/``KILL`` control frames upstream mid-stream
(network.rs ``ControlMessage``), which is how HTTP client disconnects reach
the engine's step loop.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import socket
import uuid
from typing import Callable, Dict, Optional

from .codec import Frame, FrameKind, Prologue, read_frame, write_frame
from .codec import ConnectionInfo

logger = logging.getLogger("dynamo_tpu.runtime.tcp")

__all__ = ["TcpStreamServer", "StreamReceiver", "StreamSender",
           "open_stream_sender"]


async def open_stream_sender(info: "ConnectionInfo",
                             error: Optional[str] = None,
                             timeout: float = 10.0):
    """Sender factory: the C++ data-plane sender (csrc/data_plane.cpp) when
    the toolchain is available and DYN_NATIVE_DATAPLANE != 0, else the
    asyncio StreamSender below. Only lib-unavailability falls back — real
    connection failures propagate identically for both paths."""
    if os.environ.get("DYN_NATIVE_DATAPLANE", "1") != "0":
        from .native_tcp import NativeStreamSender, load_data_plane_lib
        # first use may g++-compile csrc/data_plane.cpp — off the loop
        # (memoized, so the hop is a dict hit afterwards)
        if await asyncio.to_thread(load_data_plane_lib) is not None:
            return await NativeStreamSender.connect(info, error=error,
                                                    timeout=timeout)
    return await StreamSender.connect(info, error=error, timeout=timeout)


class StreamReceiver:
    """Caller-side handle for one registered response stream."""

    def __init__(self, stream_id: str):
        self.stream_id = stream_id
        self.frames: asyncio.Queue = asyncio.Queue()
        self._writer: Optional[asyncio.StreamWriter] = None
        self._connected = asyncio.Event()
        self.prologue: Optional[Prologue] = None

    async def wait_connected(self, timeout: float = 30.0) -> Prologue:
        """Await the worker's dial-back + prologue frame."""
        await asyncio.wait_for(self._connected.wait(), timeout)
        assert self.prologue is not None
        return self.prologue

    async def next_frame(self, timeout: Optional[float] = None) -> Optional[Frame]:
        if timeout is None:
            return await self.frames.get()
        try:
            return await asyncio.wait_for(self.frames.get(), timeout)
        except asyncio.TimeoutError:
            return None

    async def send_control(self, frame: Frame) -> None:
        """Push STOP/KILL upstream to the sender."""
        if self._writer is not None and not self._writer.is_closing():
            try:
                await write_frame(self._writer, frame)
            except (ConnectionError, OSError):
                pass

    def close(self) -> None:
        if self._writer is not None and not self._writer.is_closing():
            self._writer.close()


class TcpStreamServer:
    """One per process (lazily started, like the reference's
    distributed.rs:110-120 lazy TCP server). Workers dial in, identify the
    stream via the prologue header, and frames flow to the registered
    receiver's queue."""

    def __init__(self, host: str = "127.0.0.1", advertise: Optional[str] = None):
        self.host = host
        self.advertise = advertise
        self._server: Optional[asyncio.base_events.Server] = None
        self._pending: Dict[str, StreamReceiver] = {}
        self.port: Optional[int] = None

    async def start(self) -> None:
        if self._server is not None:
            return
        self._server = await asyncio.start_server(
            self._on_connection, self.host, 0,
            family=socket.AF_INET)
        self.port = self._server.sockets[0].getsockname()[1]
        logger.debug("tcp stream server listening on %s:%d", self.host, self.port)

    @property
    def address(self) -> str:
        return f"{self.advertise or self.host}:{self.port}"

    def register(self, stream_id: Optional[str] = None) -> StreamReceiver:
        sid = stream_id or uuid.uuid4().hex
        rx = StreamReceiver(sid)
        self._pending[sid] = rx
        return rx

    def unregister(self, stream_id: str) -> None:
        self._pending.pop(stream_id, None)

    def connection_info(self, rx: StreamReceiver) -> ConnectionInfo:
        return ConnectionInfo(address=self.address, stream_id=rx.stream_id)

    async def _on_connection(self, reader: asyncio.StreamReader,
                             writer: asyncio.StreamWriter) -> None:
        first = await read_frame(reader)
        if first is None or first.kind != FrameKind.PROLOGUE:
            writer.close()
            return
        hdr = first.header_json()
        sid = hdr.get("stream_id", "")
        rx = self._pending.pop(sid, None)
        if rx is None:
            logger.warning("dial-back for unknown stream %s", sid)
            writer.close()
            return
        rx._writer = writer
        rx.prologue = Prologue(error=hdr.get("error"))
        rx._connected.set()
        try:
            while True:
                try:
                    f = await read_frame(reader)
                except Exception as e:  # malformed/oversized frame
                    logger.warning("stream %s read failed: %s", sid, e)
                    rx.frames.put_nowait(Frame(
                        FrameKind.ERROR,
                        json.dumps({"error": f"stream read failed: {e}"})
                        .encode()))
                    return
                if f is None:
                    rx.frames.put_nowait(Frame(FrameKind.ERROR,
                                               b'{"error": "connection lost"}'))
                    return
                rx.frames.put_nowait(f)
                if f.kind in (FrameKind.SENTINEL, FrameKind.ERROR):
                    return
        finally:
            if not writer.is_closing():
                writer.close()

    async def close(self) -> None:
        # claim before the await (DL008): double-close waits on a dead
        # server instead of racing the teardown
        server, self._server = self._server, None
        if server is not None:
            server.close()
            await server.wait_closed()


class StreamSender:
    """Worker-side handle: dial the caller, send prologue, stream frames,
    watch for upstream control frames."""

    def __init__(self, reader: asyncio.StreamReader,
                 writer: asyncio.StreamWriter):
        self._reader = reader
        self._writer = writer
        self._control_task: Optional[asyncio.Task] = None
        self.on_stop: Optional[Callable[[], None]] = None
        self.on_kill: Optional[Callable[[], None]] = None
        self.killed = False

    @classmethod
    async def connect(cls, info: ConnectionInfo, error: Optional[str] = None,
                      timeout: float = 10.0) -> "StreamSender":
        host, port = info.address.rsplit(":", 1)
        reader, writer = await asyncio.wait_for(
            asyncio.open_connection(host, int(port)), timeout)
        sender = cls(reader, writer)
        hdr = {"stream_id": info.stream_id, "error": error}
        await write_frame(writer, Frame(FrameKind.PROLOGUE,
                                        json.dumps(hdr).encode()))
        sender._control_task = asyncio.get_running_loop().create_task(
            sender._watch_control(), name=f"stream-ctl-{info.stream_id[:8]}")
        return sender

    async def _watch_control(self) -> None:
        try:
            while True:
                f = await read_frame(self._reader)
                if f is None:
                    return
                if f.kind == FrameKind.STOP and self.on_stop is not None:
                    self.on_stop()
                elif f.kind == FrameKind.KILL:
                    self.killed = True
                    if self.on_kill is not None:
                        self.on_kill()
        except (ConnectionError, asyncio.CancelledError):
            pass

    async def send(self, data: bytes, header: bytes = b"") -> None:
        await write_frame(self._writer, Frame(FrameKind.DATA, header, data))

    async def finish(self, error: Optional[str] = None) -> None:
        try:
            if error is not None:
                await write_frame(self._writer, Frame(
                    FrameKind.ERROR, json.dumps({"error": error}).encode()))
            else:
                await write_frame(self._writer, Frame(FrameKind.SENTINEL))
        except (ConnectionError, OSError):
            pass
        finally:
            if self._control_task is not None:
                self._control_task.cancel()
            if not self._writer.is_closing():
                self._writer.close()
