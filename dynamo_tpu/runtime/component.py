"""Component model: Namespace → Component → Endpoint naming + discovery.

Reference: lib/runtime/src/component.rs + component/{namespace,endpoint}.rs.
Split out of distributed.py (round 3 — the reference keeps these in seven
files for the same reason: every transport change was touching one
god-module). The serving side lives in runtime/ingress.py, the calling
side in runtime/egress.py, the per-process runtime in
runtime/distributed.py; this module is pure naming + the discovery
record + serde plumbing.
"""

from __future__ import annotations

import dataclasses
import json
from typing import TYPE_CHECKING, Any, Callable, Optional

from .engine import AsyncEngine

if TYPE_CHECKING:   # avoid the cycle: distributed imports this module
    from .distributed import DistributedRuntime

__all__ = ["Namespace", "Component", "Endpoint", "ComponentEndpointInfo",
           "json_serde"]


def _default_encode(obj: Any) -> bytes:
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        obj = dataclasses.asdict(obj)
    elif hasattr(obj, "to_dict"):
        obj = obj.to_dict()
    return json.dumps(obj).encode()


def json_serde(cls: Optional[type] = None):
    """(encode, decode) pair: dataclass/dict → JSON bytes and back.
    ``cls`` may define ``from_dict`` or be a dataclass for typed decode."""

    def decode(raw: bytes) -> Any:
        d = json.loads(raw)
        if cls is None:
            return d
        if hasattr(cls, "from_dict"):
            return cls.from_dict(d)
        if dataclasses.is_dataclass(cls):
            return cls(**d)
        return d

    return _default_encode, decode


@dataclasses.dataclass
class ComponentEndpointInfo:
    """Discovery record one serving endpoint writes.
    Reference: ``ComponentEndpointInfo`` (component.rs:90-97).

    ``draining``: the planner's decommission flag (docs/planner.md). A
    draining instance stays discoverable — in-flight streams keep their
    dial-back path — but routers must stop admitting new requests to it."""

    subject: str
    worker_id: int
    component: str
    endpoint: str
    namespace: str
    draining: bool = False

    def to_json(self) -> bytes:
        return json.dumps(dataclasses.asdict(self)).encode()

    @classmethod
    def from_json(cls, raw: bytes) -> "ComponentEndpointInfo":
        d = json.loads(raw)
        fields = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in fields})


@dataclasses.dataclass
class Namespace:
    runtime: "DistributedRuntime"
    name: str

    def component(self, name: str) -> "Component":
        return Component(self.runtime, self.name, name)

    # -- event plane (reference traits/events.rs: namespace-scoped pub/sub)
    def event_subject(self, topic: str) -> str:
        return f"evt.{self.name}.{topic}"

    async def publish_event(self, topic: str, payload: Any) -> None:
        await self.runtime.bus.publish(self.event_subject(topic),
                                       _default_encode(payload))

    async def subscribe_event(self, topic: str):
        return await self.runtime.bus.subscribe(self.event_subject(topic))


@dataclasses.dataclass
class Component:
    runtime: "DistributedRuntime"
    namespace: str
    name: str

    def endpoint(self, name: str) -> "Endpoint":
        return Endpoint(self.runtime, self.namespace, self.name, name)

    def event_subject(self, topic: str) -> str:
        return f"evt.{self.namespace}.{self.name}.{topic}"

    async def publish_event(self, topic: str, payload: Any) -> None:
        await self.runtime.bus.publish(self.event_subject(topic),
                                       _default_encode(payload))

    async def subscribe_event(self, topic: str):
        return await self.runtime.bus.subscribe(self.event_subject(topic))


@dataclasses.dataclass
class Endpoint:
    runtime: "DistributedRuntime"
    namespace: str
    component: str
    name: str

    def parent_component(self) -> Component:
        return Component(self.runtime, self.namespace, self.component)

    # naming (reference component.rs:246-257 / component/endpoint.rs:110-137)
    def discovery_prefix(self) -> str:
        return f"{self.namespace}/components/{self.component}/{self.name}:"

    def discovery_key(self, lease_id: int) -> str:
        return f"{self.discovery_prefix()}{lease_id:x}"

    def subject(self, lease_id: int) -> str:
        return f"{self.namespace}|{self.component}.{self.name}-{lease_id:x}"

    def stats_key(self, lease_id: int) -> str:
        return (f"{self.namespace}/stats/{self.component}/"
                f"{self.name}:{lease_id:x}")

    def drain_prefix(self) -> str:
        """Drain-request keys: the planner writes
        ``{ns}/drain/{comp}/{ep}:{lease:x}`` and the serving endpoint —
        which owns its discovery entry — answers by re-announcing itself
        with ``draining=true`` (docs/planner.md drain protocol)."""
        return f"{self.namespace}/drain/{self.component}/{self.name}:"

    def drain_key(self, lease_id: int) -> str:
        return f"{self.drain_prefix()}{lease_id:x}"

    @property
    def path(self) -> str:
        return f"dyn://{self.namespace}/{self.component}/{self.name}"

    def __post_init__(self) -> None:
        # structure characters (| . - : /) in names would corrupt subjects
        # and discovery keys (reference slug.rs; component.rs:323-339 TODO)
        from .slug import validate_name
        validate_name(self.namespace, "namespace")
        validate_name(self.component, "component")
        validate_name(self.name, "endpoint")

    @classmethod
    def parse_path(cls, runtime: "DistributedRuntime",
                   path: str) -> "Endpoint":
        """Parse ``dyn://ns/comp/ep`` or ``ns.comp.ep`` (reference
        protocols.rs:33-200)."""
        p = path
        if p.startswith("dyn://"):
            p = p[len("dyn://"):]
        parts = p.replace(".", "/").split("/")
        if len(parts) != 3 or not all(parts):
            raise ValueError(f"invalid endpoint path: {path!r}")
        return cls(runtime, *parts)

    async def serve(self, engine: AsyncEngine,
                    decode_req: Optional[Callable[[bytes], Any]] = None,
                    encode_resp: Optional[Callable[[Any], bytes]] = None,
                    stats_handler: Optional[Callable[[], Any]] = None,
                    stats_interval: float = 1.0):
        """Register + start serving. Returns the running server handle."""
        from .ingress import EndpointServer
        server = EndpointServer(self, engine,
                                decode_req or json_serde()[1],
                                encode_resp or _default_encode,
                                stats_handler, stats_interval)
        await server.start()
        self.runtime._servers.append(server)
        return server

    def client(self, decode_resp: Optional[Callable[[bytes], Any]] = None,
               encode_req: Optional[Callable[[Any], bytes]] = None):
        from .egress import Client
        return Client(self, encode_req or _default_encode,
                      decode_resp or json_serde()[1])
