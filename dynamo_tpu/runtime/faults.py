"""Deterministic failpoint registry — the chaos-hardening substrate.

Every cross-process boundary the fleet can lose (a daemon link, a disk,
a peer, a dial-back stream) is named as a **failpoint site**: a cheap
``faults.hit("site")`` call at the exact line where the real failure
would surface. Disarmed (the production state) a hit is one dict-truth
check — zero allocation, zero branches beyond ``if not _ARMED``. Armed,
a site deterministically injects the failure class the site declares:

- ``error[:msg]`` — raise (the caller's own failure type via
  ``exc=...`` at the hit, so retry ladders and fallback paths engage
  exactly as they would for the real fault);
- ``delay:ms``    — stall the call (slow-not-dead: the brownout shape);
- ``torn``        — truncate a byte payload mid-write/mid-frame
  (``faults.mangle``);
- ``enospc``      — raise ``OSError(ENOSPC)`` (disk-pressure shape);
- ``1-in-N,<action>`` — fire deterministically on every Nth hit of the
  site (a per-site counter, not a clock or RNG — two identical runs
  inject identically, the property the sim's byte-identical determinism
  gate and recorded replay both lean on).

Arming surfaces (all optional, all composable):

- env: ``DYN_FAULTS="netstore.call=1-in-3,error;wal.append=enospc"``
  parsed at import (subprocess workers inherit it);
- programmatic: :func:`arm` / :func:`disarm` / :func:`reset` (tests);
- fleet-wide: ``llmctl faults {set,clear,status}`` writes
  ``faults/control/{namespace}``; every worker running
  :func:`watch_faults_loop` (launch/run.py) applies the stored table
  live — the chaos-drill lever for a running fleet.

Discipline (docs/chaos.md):

- sites are REGISTERED here, in :data:`SITES` — ``hit()`` on an unknown
  name raises, so a typo'd site can never silently no-op;
- a site is never placed inside ``jax.jit``/``shard_map``/``pallas_call``
  bodies (DL005: traced code must stay pure — inject at the host
  boundary instead);
- async call sites use :func:`hit_async` (delays ride
  ``asyncio.sleep``); sync sites — thread-pool and daemon code — use
  :func:`hit` (the one deliberate ``time.sleep`` below is the injection
  itself);
- every registered site must be exercised by at least one test
  (tests/test_chaos.py coverage gate — an unreferenced site fails the
  suite).
"""

from __future__ import annotations

import asyncio
import dataclasses
import errno
import logging
import os
import re
import time
from typing import Dict, Optional, Type

logger = logging.getLogger("dynamo_tpu.runtime.faults")

__all__ = [
    "SITES",
    "FaultInjected",
    "arm",
    "disarm",
    "reset",
    "armed",
    "fired_count",
    "hit",
    "hit_async",
    "mangle",
    "faults_control_key",
    "watch_faults_loop",
    "arm_from_env",
]

FAULTS_ENV = "DYN_FAULTS"
FAULTS_PREFIX = "faults/"

# The failpoint catalog: every instrumented site, with the module that
# owns it and the failure class it models. hit() on a name not listed
# here raises KeyError — the registry is the single source of truth the
# coverage gate (tests/test_chaos.py) walks.
SITES: Dict[str, str] = {
    "netstore.call":
        "runtime/netstore.py — one daemon RPC attempt (flapping link)",
    "request.egress":
        "runtime/egress.py — request-plane publish toward a worker",
    "request.ingress":
        "runtime/ingress.py — worker-side accept of a decoded request",
    "kvstore.lease.keepalive":
        "runtime/kvstore.py — one lease refresh (liveness blip)",
    "wal.append":
        "runtime/wal.py — durable WAL append (full/failing disk)",
    "diskstore.write":
        "llm/kv/diskstore.py — block payload write (ENOSPC, torn npz)",
    "diskstore.recovery":
        "llm/kv/diskstore.py — manifest/payload read at warm start",
    "diskstore.spill":
        "llm/kv/diskstore.py — write-behind spill pump store",
    "remotestore.put":
        "llm/kv/remotestore.py — object-tier put (promotion pump sink)",
    "fabric.fetch":
        "llm/kv/fabric.py — one peer KV fetch (dead/slow peer)",
    "fabric.dialback":
        "llm/kv/fabric.py — serving peer's dataplane dial-back connect",
    "dataplane.frame":
        "llm/kv/fabric.py — one streamed block frame (torn mid-stream)",
    "prefill.publish":
        "engine/core.py — one prefix-block publish to the object tier",
    "engine.onboard":
        "engine/core.py — off-thread tier-hit onboard prep",
    "engine.harvest":
        "engine/core.py — post-dispatch harvest (loop-fatal boundary)",
    "disagg.layer_stream":
        "llm/kv/stream.py — one per-layer KV frame of a streamed handoff "
        "(torn mid-stream)",
}


class FaultInjected(RuntimeError):
    """Default injected error (sites may request their own class via
    ``exc=`` so production fallback paths engage)."""


_SPEC_RE = re.compile(
    r"^(?:1-in-(?P<n>\d+),)?"
    r"(?P<mode>error|delay|torn|enospc|off)(?::(?P<arg>.*))?$")


@dataclasses.dataclass
class _Armed:
    site: str
    mode: str                 # error | delay | torn | enospc
    every_n: int = 1          # fire on every Nth hit (deterministic)
    arg: str = ""             # error message / delay ms / torn fraction
    hits: int = 0             # total hits while armed
    fired: int = 0            # injections actually performed

    def due(self) -> bool:
        """Advance the per-site hit counter; True when this hit fires.
        Counter-based, so two identical runs inject identically."""
        self.hits += 1
        return self.hits % max(self.every_n, 1) == 0

    def delay_s(self) -> float:
        return float(self.arg or 10.0) / 1e3

    def describe(self) -> str:
        prefix = f"1-in-{self.every_n}," if self.every_n > 1 else ""
        suffix = f":{self.arg}" if self.arg else ""
        return f"{prefix}{self.mode}{suffix}"


def parse_spec(site: str, spec: str) -> Optional[_Armed]:
    """``spec`` grammar: ``[1-in-N,]mode[:arg]``; ``off`` disarms.
    Unknown specs raise ValueError (a typo'd drill must not silently
    run fault-free)."""
    m = _SPEC_RE.match(spec.strip())
    if m is None:
        raise ValueError(f"bad failpoint spec {spec!r} for {site!r} "
                         f"(want [1-in-N,]error|delay:ms|torn|enospc)")
    if m.group("mode") == "off":
        return None
    return _Armed(site=site, mode=m.group("mode"),
                  every_n=int(m.group("n") or 1),
                  arg=m.group("arg") or "")


# site → _Armed. Module-level so the disarmed fast path is one truthy
# check; all mutation goes through arm/disarm/reset.
_ARMED: Dict[str, _Armed] = {}
# fired counts survive disarm (tests assert fired-then-recovered)
_FIRED_TOTAL: Dict[str, int] = {}


def arm(site: str, spec: str) -> None:
    if site not in SITES:
        raise KeyError(f"unknown failpoint site {site!r} "
                       f"(registered: {sorted(SITES)})")
    armed = parse_spec(site, spec)
    if armed is None:
        _ARMED.pop(site, None)
        return
    _ARMED[site] = armed
    logger.info("failpoint armed: %s=%s", site, armed.describe())


def disarm(site: str) -> None:
    _ARMED.pop(site, None)


def disarm_all() -> None:
    """Disarm every site but KEEP the fired counters (the chaos suite's
    per-test isolation; the coverage gate reads the counters after)."""
    _ARMED.clear()


def reset() -> None:
    """Disarm everything and zero fired counters (test isolation)."""
    _ARMED.clear()
    _FIRED_TOTAL.clear()


def armed() -> Dict[str, str]:
    return {site: a.describe() for site, a in sorted(_ARMED.items())}


def fired_count(site: Optional[str] = None) -> int:
    if site is not None:
        return _FIRED_TOTAL.get(site, 0)
    return sum(_FIRED_TOTAL.values())


def _check(site: str) -> Optional[_Armed]:
    a = _ARMED.get(site)
    if a is None:
        if site not in SITES:
            raise KeyError(f"unknown failpoint site {site!r}")
        return None
    if not a.due():
        return None
    a.fired += 1
    _FIRED_TOTAL[site] = _FIRED_TOTAL.get(site, 0) + 1
    return a


def _raise_for(a: _Armed, exc: Optional[Type[BaseException]]) -> None:
    if a.mode == "enospc":
        raise OSError(errno.ENOSPC,
                      f"No space left on device [failpoint {a.site}]")
    msg = a.arg or f"injected fault at {a.site}"
    raise (exc or FaultInjected)(f"{msg} [failpoint {a.site}]")


def hit(site: str, exc: Optional[Type[BaseException]] = None) -> None:
    """Sync failpoint (thread-pool / daemon code). Zero-cost disarmed.
    A ``torn`` arming is payload-shaping and fires only at the site's
    :func:`mangle` call — hit() leaves its counter untouched."""
    if not _ARMED:
        return
    pre = _ARMED.get(site)
    if pre is not None and pre.mode == "torn":
        return
    a = _check(site)
    if a is None:
        return
    if a.mode == "delay":
        # the injection IS the deliberate stall (sync sites run
        # off-loop: spill pumps, onboard prep threads, the daemon WAL)
        time.sleep(a.delay_s())  # dynalint: ok DL001 failpoint delay injection is the fault being modeled
        return
    _raise_for(a, exc)


async def hit_async(site: str,
                    exc: Optional[Type[BaseException]] = None) -> None:
    """Async failpoint (event-loop call sites). Delays ride
    ``asyncio.sleep`` so the loop keeps serving everyone else — the
    injected fault is slow-PEER, never a stalled loop."""
    if not _ARMED:
        return
    pre = _ARMED.get(site)
    if pre is not None and pre.mode == "torn":
        return
    a = _check(site)
    if a is None:
        return
    if a.mode == "delay":
        await asyncio.sleep(a.delay_s())
        return
    _raise_for(a, exc)


def mangle(site: str, data: bytes) -> bytes:
    """Payload-shaping failpoint: armed ``torn`` truncates the byte
    payload (default: half; ``torn:frac`` keeps ``frac`` of it) so the
    consumer exercises its corruption path. Other armed modes behave
    like :func:`hit`. Disarmed: identity, zero-cost."""
    if not _ARMED:
        return data
    a = _check(site)
    if a is None:
        return data
    if a.mode == "torn":
        frac = float(a.arg or 0.5)
        return data[:max(int(len(data) * frac), 1)]
    if a.mode == "delay":
        time.sleep(a.delay_s())  # dynalint: ok DL001 failpoint delay injection is the fault being modeled
        return data
    _raise_for(a, None)
    return data  # unreachable


def arm_from_env(env: Optional[str] = None) -> int:
    """Parse ``DYN_FAULTS="site=spec;site=spec"``. Returns the number of
    sites armed; unknown sites/specs raise loudly (a chaos drill with a
    typo must not run fault-free)."""
    raw = env if env is not None else os.environ.get(FAULTS_ENV, "")
    n = 0
    for part in raw.split(";"):
        part = part.strip()
        if not part:
            continue
        site, _, spec = part.partition("=")
        arm(site.strip(), spec.strip() or "error")
        n += 1
    return n


# ---------------------------------------------------------------- fleet ops
def faults_control_key(namespace: str) -> str:
    """``llmctl faults`` target: a JSON ``{site: spec}`` table every
    watching worker applies declaratively (absent site = disarmed)."""
    return f"{FAULTS_PREFIX}control/{namespace}"


def _apply_table(raw: bytes) -> None:
    import json
    try:
        table = json.loads(raw)
    except ValueError:
        logger.warning("ignoring malformed faults control payload")
        return
    if not isinstance(table, dict):
        logger.warning("ignoring non-dict faults control payload")
        return
    # declarative: the stored table IS the armed set (env/programmatic
    # armings made before the first control write survive until then —
    # fleet control is authoritative once used)
    _ARMED.clear()
    for site, spec in table.items():
        try:
            arm(site, str(spec))
        except (KeyError, ValueError):
            logger.warning("faults control: skipping bad entry %r=%r",
                           site, spec)
    logger.info("faults control applied: %s", armed() or "(all clear)")


async def watch_faults_loop(runtime, namespace: str) -> None:
    """Standing task (launch/run.py): apply ``llmctl faults`` live.
    Like the tier-weights watch, the STORED value applies at startup —
    a late-joining worker converges to the namespace's current drill."""
    from .kvstore import WatchEventType
    from .tracing import detach_trace

    detach_trace()
    key = faults_control_key(namespace)
    entry = await runtime.store.kv_get(key)
    if entry is not None:
        _apply_table(entry.value)
    watcher = await runtime.store.watch_prefix(key)
    async for ev in watcher:
        if ev.type == WatchEventType.PUT:
            _apply_table(ev.entry.value)


# env arming at import: subprocess workers (run.py, bench, tests that
# spawn daemons) inherit the drill without any wiring
if os.environ.get(FAULTS_ENV):
    arm_from_env()
