"""Two-part wire codec + control messages for the response plane.

TPU-native analog of the reference's length-prefixed two-part framing
(lib/runtime/src/pipeline/network/codec/two_part.rs) and the control
messages that ride the response TCP stream
(lib/runtime/src/pipeline/network.rs: ``ControlMessage::{Stop, Kill,
Sentinel}``, ``ResponseStreamPrologue``).

Frame layout (all integers big-endian u32):

    [kind u8][header_len u32][data_len u32][header bytes][data bytes]

``kind`` distinguishes data frames from control frames so a reader never has
to sniff payload bytes. Headers and control payloads are JSON (small, rare);
data payloads are opaque bytes chosen by the layer above (JSON today,
msgpack-able later without touching this file).
"""

from __future__ import annotations

import asyncio
import dataclasses
import json
import struct
from enum import IntEnum
from typing import Optional, Tuple

__all__ = [
    "FrameKind",
    "Frame",
    "ControlMessage",
    "Prologue",
    "RequestControlMessage",
    "ConnectionInfo",
    "write_frame",
    "read_frame",
    "encode_two_part",
    "decode_two_part",
]

_HDR = struct.Struct(">BII")
MAX_FRAME = 256 * 1024 * 1024  # defensive bound, not a protocol limit


class FrameKind(IntEnum):
    DATA = 0        # one response item
    PROLOGUE = 1    # first frame on a response stream
    SENTINEL = 2    # end of stream (clean)
    STOP = 3        # receiver → sender: graceful stop_generating
    KILL = 4        # receiver → sender: hard kill
    ERROR = 5       # stream aborted with error (header carries message)


@dataclasses.dataclass
class Frame:
    kind: FrameKind
    header: bytes = b""
    data: bytes = b""

    def header_json(self) -> dict:
        return json.loads(self.header) if self.header else {}


@dataclasses.dataclass
class Prologue:
    """First frame a worker sends on the response stream; carries early
    errors (e.g. request deserialization failed) before any data flows.
    Reference: ``ResponseStreamPrologue`` (network.rs)."""

    error: Optional[str] = None

    def to_frame(self) -> Frame:
        return Frame(FrameKind.PROLOGUE,
                     json.dumps(dataclasses.asdict(self)).encode())

    @classmethod
    def from_frame(cls, f: Frame) -> "Prologue":
        return cls(**f.header_json())


class ControlMessage:
    """Constructors for receiver→sender control frames."""

    @staticmethod
    def stop() -> Frame:
        return Frame(FrameKind.STOP)

    @staticmethod
    def kill() -> Frame:
        return Frame(FrameKind.KILL)

    @staticmethod
    def sentinel() -> Frame:
        return Frame(FrameKind.SENTINEL)


@dataclasses.dataclass
class ConnectionInfo:
    """Where the worker should dial back to stream responses.
    Reference: ``ConnectionInfo`` in network/tcp/client.rs."""

    address: str          # "host:port"
    stream_id: str        # registered subject on the caller's stream server

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "ConnectionInfo":
        return cls(address=d["address"], stream_id=d["stream_id"])


@dataclasses.dataclass
class RequestControlMessage:
    """Header half of a request two-part message.
    Reference: ``RequestControlMessage{id, request_type, response_type,
    connection_info}`` (network/egress/push.rs).

    ``trace`` is the optional distributed-tracing propagation record
    ``{trace_id, parent_span, origin_ts}`` (runtime/tracing.py
    TraceContext): when present, the serving side opens its trace as a
    CHILD of the caller's instead of a disjoint root — the fleet-tree
    stitch edge. Absent on old senders; ignored by old receivers.

    ``deadline_ms`` is the request's REMAINING end-to-end budget at
    send time (runtime/engine.py EngineContext deadline): the serving
    side re-anchors it against its own monotonic clock, so the deadline
    survives hops without clock synchronization. A worker whose budget
    runs out cancels the request engine-side (slot/hold release within
    one loop tick) even if the client vanished without a KILL frame.
    Absent = no deadline; ignored by old receivers.

    ``tenant`` / ``priority`` are the multi-tenant identity
    (llm/tenancy.py): the serving side re-attaches them to its
    EngineContext so fair-share admission and per-tenant KV quotas
    price the request without re-parsing the payload. Absent = the
    implicit single tenant; ignored by old receivers."""

    id: str
    request_type: str = "single_in"     # single_in | many_in
    response_type: str = "many_out"
    connection_info: Optional[ConnectionInfo] = None
    trace: Optional[dict] = None
    deadline_ms: Optional[float] = None
    tenant: Optional[str] = None
    priority: Optional[str] = None

    def to_json(self) -> bytes:
        d = {"id": self.id, "request_type": self.request_type,
             "response_type": self.response_type}
        if self.connection_info is not None:
            d["connection_info"] = self.connection_info.to_dict()
        if self.trace is not None:
            d["trace"] = self.trace
        if self.deadline_ms is not None:
            d["deadline_ms"] = self.deadline_ms
        if self.tenant is not None:
            d["tenant"] = self.tenant
        if self.priority is not None:
            d["priority"] = self.priority
        return json.dumps(d).encode()

    @classmethod
    def from_json(cls, raw: bytes) -> "RequestControlMessage":
        d = json.loads(raw)
        ci = d.get("connection_info")
        return cls(id=d["id"],
                   request_type=d.get("request_type", "single_in"),
                   response_type=d.get("response_type", "many_out"),
                   connection_info=ConnectionInfo.from_dict(ci) if ci else None,
                   trace=d.get("trace"),
                   deadline_ms=d.get("deadline_ms"),
                   tenant=d.get("tenant"),
                   priority=d.get("priority"))


# ----------------------------------------------------------------- framing

def encode_frame(f: Frame) -> bytes:
    return _HDR.pack(int(f.kind), len(f.header), len(f.data)) + f.header + f.data


async def write_frame(writer: asyncio.StreamWriter, f: Frame) -> None:
    writer.write(encode_frame(f))
    await writer.drain()


async def read_frame(reader: asyncio.StreamReader) -> Optional[Frame]:
    """Read one frame; returns None on clean EOF at a frame boundary."""
    try:
        hdr = await reader.readexactly(_HDR.size)
    except (asyncio.IncompleteReadError, ConnectionResetError):
        return None
    kind, hlen, dlen = _HDR.unpack(hdr)
    if hlen > MAX_FRAME or dlen > MAX_FRAME:
        raise ValueError(f"frame too large: header={hlen} data={dlen}")
    header = await reader.readexactly(hlen) if hlen else b""
    data = await reader.readexactly(dlen) if dlen else b""
    return Frame(FrameKind(kind), header, data)


# ------------------------------------------------- request two-part message

def encode_two_part(ctrl: RequestControlMessage, payload: bytes) -> bytes:
    """Request envelope pushed over the message bus: same [hlen][dlen] shape
    as stream frames but without the kind byte (requests are always data)."""
    h = ctrl.to_json()
    return struct.pack(">II", len(h), len(payload)) + h + payload


def decode_two_part(raw: bytes) -> Tuple[RequestControlMessage, bytes]:
    hlen, dlen = struct.unpack_from(">II", raw, 0)
    off = 8
    ctrl = RequestControlMessage.from_json(raw[off:off + hlen])
    payload = raw[off + hlen:off + hlen + dlen]
    return ctrl, payload
