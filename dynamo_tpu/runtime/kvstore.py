"""Discovery KV store: etcd-shaped interface (kv ops + leases + prefix
watches) with an in-process implementation.

The reference binds discovery to etcd (lib/runtime/src/transports/etcd.rs:
``kv_create`` atomic txn, ``kv_create_or_validate``, ``kv_get_and_watch_prefix``
→ ``PrefixWatcher``/``WatchEvent::{Put,Delete}``; leases in etcd/lease.rs with
a keep-alive loop whose death shuts the runtime down). We keep that *shape* —
leases are the liveness primitive, watches drive client instance lists — but
behind an interface with two backends:

- :class:`MemoryKvStore` — single-process; also the server-side state of the
  network store (runtime/server.py), so semantics are tested once.
- ``NetKvStore`` (runtime/netstore.py) — TCP client to the self-hosted
  discovery daemon, filling etcd's role without an external dependency.

Liveness: a lease has a TTL and must be refreshed; expiry deletes every key
attached to it and fires Delete watch events — exactly how reference workers
vanish from routing when they die (SURVEY.md §5.3).
"""

from __future__ import annotations

import abc
import asyncio
import dataclasses
import time
from enum import Enum
from typing import AsyncIterator, Callable, Dict, List, Optional, Tuple

__all__ = [
    "WatchEventType",
    "WatchEvent",
    "KvEntry",
    "PrefixWatcher",
    "Lease",
    "KvStore",
    "MemoryKvStore",
]


class WatchEventType(Enum):
    PUT = "put"
    DELETE = "delete"


@dataclasses.dataclass
class KvEntry:
    key: str
    value: bytes
    lease_id: int = 0


@dataclasses.dataclass
class WatchEvent:
    type: WatchEventType
    entry: KvEntry


class PrefixWatcher:
    """Async stream of WatchEvents for one prefix; starts with a synthetic
    PUT per existing key (reference: kv_get_and_watch_prefix returns current
    kvs + watcher)."""

    def __init__(self, prefix: str, initial: List[KvEntry],
                 unsubscribe: Callable[["PrefixWatcher"], None]):
        self.prefix = prefix
        self._queue: asyncio.Queue = asyncio.Queue()
        self._unsubscribe = unsubscribe
        self._closed = False
        for e in initial:
            self._queue.put_nowait(WatchEvent(WatchEventType.PUT, e))

    def _push(self, ev: WatchEvent) -> None:
        if not self._closed:
            self._queue.put_nowait(ev)

    async def next(self, timeout: Optional[float] = None) -> Optional[WatchEvent]:
        try:
            if timeout is None:
                return await self._queue.get()
            return await asyncio.wait_for(self._queue.get(), timeout)
        except asyncio.TimeoutError:
            return None

    def __aiter__(self) -> AsyncIterator[WatchEvent]:
        return self

    async def __anext__(self) -> WatchEvent:
        if self._closed and self._queue.empty():
            raise StopAsyncIteration
        return await self._queue.get()

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            self._unsubscribe(self)


class Lease:
    """Client-side lease handle. ``keep_alive`` runs until revoked/cancelled;
    if refreshing fails (store gone) the ``on_lost`` callback fires — the
    reference's lease-death ⇒ runtime-shutdown contract."""

    def __init__(self, store: "KvStore", lease_id: int, ttl: float):
        self.store = store
        self.id = lease_id
        self.ttl = ttl
        self._task: Optional[asyncio.Task] = None
        self._revoked = False
        self.on_lost: Optional[Callable[[], None]] = None

    def start_keepalive(self) -> None:
        if self._task is None:
            self._task = asyncio.get_running_loop().create_task(
                self._keepalive_loop(), name=f"lease-keepalive-{self.id:x}")

    async def _keepalive_loop(self) -> None:
        from .faults import hit_async as _fault
        interval = max(self.ttl / 3.0, 0.05)
        while not self._revoked:
            await asyncio.sleep(interval)
            if self._revoked:
                return
            # transient-flap tolerance (chaos-hardening): a refresh that
            # RAISED (store link hiccup) is retried quickly inside the
            # remaining TTL window before the lease is declared lost —
            # one dropped RPC must not tear down a healthy worker. A
            # refresh that RETURNED False is authoritative (the store
            # says the lease is gone): give up immediately; NetKvStore's
            # lease_refresh already attempts reclaim-by-id internally.
            deadline = asyncio.get_running_loop().time() + (
                self.ttl - interval)
            ok = False
            while not self._revoked:
                try:
                    await _fault("kvstore.lease.keepalive",
                                 exc=ConnectionError)
                    ok = await self.store.lease_refresh(self.id)
                    break
                except Exception:
                    if asyncio.get_running_loop().time() >= deadline:
                        break
                    await asyncio.sleep(min(interval / 4, 0.25))
            if self._revoked:
                return
            if not ok:
                self._revoked = True
                if self.on_lost is not None:
                    self.on_lost()
                return

    async def revoke(self) -> None:
        self._revoked = True
        if self._task is not None:
            self._task.cancel()
            self._task = None
        try:
            await self.store.lease_revoke(self.id)
        except Exception:
            pass


class KvStore(abc.ABC):
    """etcd-shaped discovery store interface.

    ``on_lease_reclaimed(lease_id)``: fired by backends that can reclaim a
    transiently-expired lease under the same id (NetKvStore after a daemon
    restart/liveness blip). The worker's discovery KEYS are replayed by the
    store itself, but derived state — e.g. the KV router's radix index of
    this worker's cached blocks — was wiped by the DELETE watch events and
    must be re-announced by whoever owns it (KNOWN_ISSUES kv-router
    staleness; see KvBlockPool.reannounce)."""

    on_lease_reclaimed: Optional[Callable[[int], None]] = None

    @abc.abstractmethod
    async def kv_create(self, key: str, value: bytes, lease_id: int = 0) -> bool:
        """Atomic create; False if the key already exists."""

    @abc.abstractmethod
    async def kv_create_or_validate(self, key: str, value: bytes,
                                    lease_id: int = 0) -> bool:
        """Create, or succeed iff the existing value is identical."""

    @abc.abstractmethod
    async def kv_put(self, key: str, value: bytes, lease_id: int = 0) -> None: ...

    async def kv_cas(self, key: str, expected: Optional[bytes],
                     value: bytes, lease_id: int = 0) -> bool:
        """Write iff current value == expected (None = absent). Default
        raises — backends opt in (Memory + Net both do)."""
        raise NotImplementedError

    @abc.abstractmethod
    async def kv_get(self, key: str) -> Optional[KvEntry]: ...

    @abc.abstractmethod
    async def kv_get_prefix(self, prefix: str) -> List[KvEntry]: ...

    @abc.abstractmethod
    async def kv_delete(self, key: str) -> bool: ...

    @abc.abstractmethod
    async def watch_prefix(self, prefix: str) -> PrefixWatcher:
        """Current entries as synthetic PUTs, then live events."""

    @abc.abstractmethod
    async def lease_create(self, ttl: float) -> Lease: ...

    @abc.abstractmethod
    async def lease_refresh(self, lease_id: int) -> bool: ...

    @abc.abstractmethod
    async def lease_revoke(self, lease_id: int) -> None: ...

    async def close(self) -> None:
        pass


class MemoryKvStore(KvStore):
    """In-process store. Single event-loop actor discipline: every method
    runs on the owning loop, so no locks (the reference gets the same
    guarantee from etcd's serializability)."""

    def __init__(self, now: Callable[[], float] = time.monotonic):
        self._kv: Dict[str, KvEntry] = {}
        self._watchers: List[Tuple[str, PrefixWatcher]] = []
        self._leases: Dict[int, float] = {}      # id → expiry deadline
        self._lease_ttl: Dict[int, float] = {}
        self._lease_keys: Dict[int, set] = {}
        self._next_lease = 0xA0000001
        self._now = now
        self._reaper: Optional[asyncio.Task] = None
        # durability hook (runtime/server.py): fires on EVERY lease drop,
        # revocation and expiry alike — etcd logs expiry as a revocation,
        # so a crash right after an expiry must not resurrect the dead
        # worker's lease+keys from stale WAL records
        self.on_lease_drop: Optional[Callable[[int], None]] = None

    # ------------------------------------------------------------- helpers
    def _notify(self, ev: WatchEvent) -> None:
        for prefix, w in list(self._watchers):
            if ev.entry.key.startswith(prefix):
                w._push(ev)

    def _attach(self, key: str, lease_id: int) -> None:
        if lease_id:
            self._lease_keys.setdefault(lease_id, set()).add(key)

    def _expire_due(self) -> None:
        now = self._now()
        dead = [lid for lid, dl in self._leases.items() if dl <= now]
        for lid in dead:
            self._drop_lease(lid)

    def _drop_lease(self, lease_id: int) -> None:
        known = lease_id in self._leases or lease_id in self._lease_keys
        self._leases.pop(lease_id, None)
        self._lease_ttl.pop(lease_id, None)
        for key in sorted(self._lease_keys.pop(lease_id, ())):
            entry = self._kv.pop(key, None)
            if entry is not None:
                self._notify(WatchEvent(WatchEventType.DELETE, entry))
        if known and self.on_lease_drop is not None:
            self.on_lease_drop(lease_id)

    def _ensure_reaper(self) -> None:
        if self._reaper is None or self._reaper.done():
            try:
                loop = asyncio.get_running_loop()
            except RuntimeError:
                return
            self._reaper = loop.create_task(self._reap_loop(),
                                            name="kvstore-lease-reaper")

    async def _reap_loop(self) -> None:
        while True:
            await asyncio.sleep(0.05)
            self._expire_due()
            if not self._leases:
                return

    # ---------------------------------------------------------------- kv
    async def kv_create(self, key: str, value: bytes, lease_id: int = 0) -> bool:
        self._expire_due()
        if key in self._kv:
            return False
        e = KvEntry(key, value, lease_id)
        self._kv[key] = e
        self._attach(key, lease_id)
        self._notify(WatchEvent(WatchEventType.PUT, e))
        return True

    async def kv_create_or_validate(self, key: str, value: bytes,
                                    lease_id: int = 0) -> bool:
        self._expire_due()
        cur = self._kv.get(key)
        if cur is None:
            return await self.kv_create(key, value, lease_id)
        return cur.value == value

    async def kv_put(self, key: str, value: bytes, lease_id: int = 0) -> None:
        self._expire_due()
        e = KvEntry(key, value, lease_id)
        self._kv[key] = e
        self._attach(key, lease_id)
        self._notify(WatchEvent(WatchEventType.PUT, e))

    async def kv_cas(self, key: str, expected: Optional[bytes],
                     value: bytes, lease_id: int = 0) -> bool:
        """Compare-and-swap (etcd txn compare-put analog): write iff the
        current value equals ``expected`` (None = key absent). The store's
        only safe read-modify-write primitive — writers in DIFFERENT
        processes cannot serialize with local locks."""
        self._expire_due()
        cur = self._kv.get(key)
        if (cur.value if cur is not None else None) != expected:
            return False
        e = KvEntry(key, value, lease_id)
        self._kv[key] = e
        self._attach(key, lease_id)
        self._notify(WatchEvent(WatchEventType.PUT, e))
        return True

    async def kv_get(self, key: str) -> Optional[KvEntry]:
        self._expire_due()
        return self._kv.get(key)

    async def kv_get_prefix(self, prefix: str) -> List[KvEntry]:
        self._expire_due()
        return [e for k, e in sorted(self._kv.items())
                if k.startswith(prefix)]

    async def kv_delete(self, key: str) -> bool:
        entry = self._kv.pop(key, None)
        if entry is None:
            return False
        if entry.lease_id:
            self._lease_keys.get(entry.lease_id, set()).discard(key)
        self._notify(WatchEvent(WatchEventType.DELETE, entry))
        return True

    async def watch_prefix(self, prefix: str) -> PrefixWatcher:
        self._expire_due()
        initial = await self.kv_get_prefix(prefix)
        w = PrefixWatcher(prefix, initial, self._unsubscribe)
        self._watchers.append((prefix, w))
        return w

    def _unsubscribe(self, watcher: PrefixWatcher) -> None:
        self._watchers = [(p, w) for p, w in self._watchers if w is not watcher]

    # ------------------------------------------------------------- leases
    async def lease_create(self, ttl: float, want_id: int = 0) -> Lease:
        """``want_id``: reclaim a specific id after a store restart (the
        worker's identity — subjects, discovery keys — is the lease id, so
        reconnection must be able to keep it; etcd grants ids the same
        way via LeaseGrant with a client-chosen ID). Raises if taken."""
        self._expire_due()
        if want_id:
            if want_id in self._leases:
                raise RuntimeError(f"lease id {want_id:#x} already held")
            lid = want_id
            self._next_lease = max(self._next_lease, want_id + 1)
        else:
            lid = self._next_lease
            self._next_lease += 1
        self._leases[lid] = self._now() + ttl
        self._lease_ttl[lid] = ttl
        self._ensure_reaper()
        return Lease(self, lid, ttl)

    async def lease_refresh(self, lease_id: int) -> bool:
        self._expire_due()
        if lease_id not in self._leases:
            return False
        self._leases[lease_id] = self._now() + self._lease_ttl[lease_id]
        return True

    async def lease_revoke(self, lease_id: int) -> None:
        self._drop_lease(lease_id)

    async def close(self) -> None:
        if self._reaper is not None:
            self._reaper.cancel()
            self._reaper = None

    # ---------------------------------------------- durability (wal.py)
    def dump_state(self) -> dict:
        """JSON-able snapshot of entries + leases for the daemon's WAL
        layer. Lease deadlines are NOT captured — a restored lease gets a
        fresh TTL window (see wal.py's module docstring)."""
        import base64
        self._expire_due()
        return {
            "kv": [[e.key, base64.b64encode(e.value).decode(), e.lease_id]
                   for e in self._kv.values()],
            "leases": [[lid, self._lease_ttl[lid]] for lid in self._leases],
        }

    async def restore_state(self, state: dict) -> None:
        import base64
        for lid, ttl in state.get("leases", ()):
            await self.lease_create(float(ttl), want_id=int(lid))
        for key, val, lease in state.get("kv", ()):
            await self.kv_put(key, base64.b64decode(val), int(lease))
