"""Calling side of the request plane: discovery watch, routing, dispatch.

Reference: ``Client<T,U>`` (lib/runtime/src/component/client.rs:52-256)
and the push-router send path (pipeline/network/egress/push.rs:88-156).
Split out of distributed.py (round 3); naming lives in
runtime/component.py, the serving side in runtime/ingress.py.
"""

from __future__ import annotations

import asyncio
import itertools
import json
import logging
import random
from typing import Any, AsyncIterator, Callable, Dict, List, Optional

from .codec import (ControlMessage, FrameKind, RequestControlMessage,
                    encode_two_part)
from .component import ComponentEndpointInfo
from .engine import AsyncEngine, Context, ManyOut, ResponseStream, SingleIn
from .kvstore import WatchEventType
from .tcp import TcpStreamServer

logger = logging.getLogger("dynamo_tpu.runtime.distributed")

__all__ = ["Client"]


class _RemoteStream(ResponseStream):
    """Client-side view of a worker's TCP response stream; forwards
    stop/kill from the local context as upstream control frames."""

    def __init__(self, ctx, rx, decode_resp, server: TcpStreamServer):
        self._rx = rx
        self._decode = decode_resp
        self._server = server
        self._ctx = ctx
        super().__init__(self._gen(), ctx)

    def _gen(self) -> AsyncIterator[Any]:
        async def gen():
            try:
                while True:
                    if self._ctx.is_killed:
                        await self._rx.send_control(ControlMessage.kill())
                        return
                    if self._ctx.is_stopped:
                        await self._rx.send_control(ControlMessage.stop())
                    f = await self._rx.next_frame(timeout=0.5)
                    if f is None:
                        continue
                    if f.kind == FrameKind.DATA:
                        yield self._decode(f.data)
                    elif f.kind == FrameKind.SENTINEL:
                        return
                    elif f.kind == FrameKind.ERROR:
                        err = f.header_json().get("error", "stream error")
                        raise RuntimeError(f"remote stream error: {err}")
            finally:
                self._rx.close()
                self._server.unregister(self._rx.stream_id)
        return gen()


class Client(AsyncEngine):
    """Watches discovery, routes requests. Reference ``Client<T,U>``
    (component/client.rs:52-256); default routing is random, like the
    reference's AsyncEngine impl for Client."""

    def __init__(self, endpoint,
                 encode_req: Callable[[Any], bytes],
                 decode_resp: Callable[[bytes], Any]):
        self.endpoint = endpoint
        self.encode_req = encode_req
        self.decode_resp = decode_resp
        self.instances: Dict[int, ComponentEndpointInfo] = {}
        self._watcher = None
        self._watch_task: Optional[asyncio.Task] = None
        self._rr = itertools.count()
        self._instances_event = asyncio.Event()
        self.on_instances_changed: Optional[Callable[[set], None]] = None

    async def start(self) -> "Client":
        rt = self.endpoint.runtime
        await rt.tcp.start()
        self._watcher = await rt.store.watch_prefix(
            self.endpoint.discovery_prefix())
        self._watch_task = asyncio.get_running_loop().create_task(
            self._watch_loop(), name=f"client-watch-{self.endpoint.name}")
        return self

    async def _watch_loop(self) -> None:
        async for ev in self._watcher:
            key = ev.entry.key
            lease_hex = key.rsplit(":", 1)[-1]
            try:
                lease_id = int(lease_hex, 16)
            except ValueError:
                continue
            if ev.type == WatchEventType.PUT:
                try:
                    self.instances[lease_id] = ComponentEndpointInfo.from_json(
                        ev.entry.value)
                except Exception:
                    continue
            else:
                self.instances.pop(lease_id, None)
            self._instances_event.set()
            if self.on_instances_changed is not None:
                self.on_instances_changed(set(self.instances))

    def instance_ids(self) -> List[int]:
        return sorted(self.instances)

    def draining_ids(self) -> List[int]:
        return sorted(i for i, info in self.instances.items()
                      if info.draining)

    def available_ids(self) -> List[int]:
        """Instances eligible for NEW work: the draining ones stay
        discoverable (their in-flight streams are still live) but take no
        new admissions (docs/planner.md). If the whole fleet is draining,
        fall back to all instances — a drain must shift load, never drop
        requests on the floor."""
        avail = [i for i, info in sorted(self.instances.items())
                 if not info.draining]
        return avail if avail else self.instance_ids()

    async def wait_for_instances(self, timeout: float = 30.0) -> List[int]:
        deadline = asyncio.get_running_loop().time() + timeout
        while not self.instances:
            remaining = deadline - asyncio.get_running_loop().time()
            if remaining <= 0:
                raise TimeoutError(
                    f"no instances for {self.endpoint.path} after {timeout}s")
            self._instances_event.clear()
            try:
                await asyncio.wait_for(self._instances_event.wait(),
                                       min(remaining, 1.0))
            except asyncio.TimeoutError:
                pass
        return self.instance_ids()

    # --------------------------------------------------------------- routes
    async def generate(self, request: SingleIn) -> ManyOut:
        return await self.random(request)

    async def random(self, request: SingleIn) -> ManyOut:
        ids = self.available_ids()
        if not ids:
            raise RuntimeError(f"no instances for {self.endpoint.path}")
        return await self.direct(request, random.choice(ids))

    async def round_robin(self, request: SingleIn) -> ManyOut:
        ids = self.available_ids()
        if not ids:
            raise RuntimeError(f"no instances for {self.endpoint.path}")
        return await self.direct(request, ids[next(self._rr) % len(ids)])

    async def direct(self, request: SingleIn, instance_id: int) -> ManyOut:
        """The push-router send path (egress/push.rs:88-156): register a
        response stream, publish the two-part request, await dial-back."""
        info = self.instances.get(instance_id)
        if info is None:
            raise RuntimeError(
                f"unknown instance {instance_id:x} for {self.endpoint.path}")
        rt = self.endpoint.runtime
        ctx = request if isinstance(request, Context) else Context(request)
        rx = rt.tcp.register()
        try:
            # egress span (reference egress/push.rs:134-151): publish +
            # dial-back wait, tagged with the target instance
            from .tracing import span as _span
            with _span("egress", instance=f"{instance_id:x}",
                       path=self.endpoint.path):
                rx, prologue = await self._dispatch_with_retry(
                    rt, rx, ctx, info, instance_id)
        except Exception:
            rt.tcp.unregister(rx.stream_id)
            raise
        if prologue.error is not None:
            rt.tcp.unregister(rx.stream_id)
            raise RuntimeError(f"remote rejected request: {prologue.error}")
        return _RemoteStream(ctx.ctx, rx, self.decode_resp, rt.tcp)

    DIAL_BACK_TIMEOUT = 10.0
    DISPATCH_ATTEMPTS = 3

    async def _dispatch_with_retry(self, rt, rx, ctx, info, instance_id):
        """Publish the two-part request and await the worker's dial-back,
        retrying the failure modes a daemon restart creates:

        - publish reaches ZERO receivers (the worker's serve subscription
          is mid-re-establishment) — NATS "no responders" semantics;
        - publish reached a receiver that died before dialing back (the
          message sat in a killed session's queue) — dial-back timeout,
          re-dispatch on a fresh stream.

        Re-dispatch is at-least-once: a slow-but-alive worker could end up
        serving the request twice, with the client consuming only the last
        stream — the same contract as the reference's NATS request plane.
        (Fire-and-forget requests are deduped worker-side by id —
        runtime/ingress.py.)"""
        from .tracing import current_wire_context
        loop = asyncio.get_running_loop()
        last_err: Exception = RuntimeError("dispatch failed")
        # propagate the request trace on the wire so the worker opens a
        # CHILD trace of ours (runtime/tracing.py TraceContext). An
        # explicit metadata["trace_context"] wins over the ambient
        # contextvar — callers dispatching OFF the request's async chain
        # (fabric RPCs hopping threads) pass identity by value.
        wire_trace = (ctx.metadata.get("trace_context")
                      or current_wire_context())
        from .faults import hit_async as _fault
        for attempt in range(self.DISPATCH_ATTEMPTS):
            conn = rt.tcp.connection_info(rx)
            # deadline propagation: put the REMAINING budget on the wire
            # (re-sampled per attempt — a retried dispatch must not
            # resurrect budget already burned waiting)
            ctrl = RequestControlMessage(id=ctx.id, connection_info=conn,
                                         trace=wire_trace,
                                         deadline_ms=ctx.ctx.remaining_ms(),
                                         tenant=ctx.ctx.tenant,
                                         priority=ctx.ctx.qos)
            payload = encode_two_part(ctrl, self.encode_req(ctx.data))
            deadline = loop.time() + self.DIAL_BACK_TIMEOUT
            delay = 0.05
            try:
                while True:   # no-responders backoff within this attempt
                    await _fault("request.egress", exc=RuntimeError)
                    n = await rt.bus.publish(info.subject, payload)
                    if n is None or n > 0:  # None: bus without counts
                        break
                    if loop.time() >= deadline:
                        raise RuntimeError(
                            f"no responders on {info.subject} "
                            f"(instance {instance_id:x})")
                    await asyncio.sleep(delay)
                    delay = min(delay * 2, 0.5)
                prologue = await rx.wait_connected(
                    timeout=max(deadline - loop.time(), 1.0))
                return rx, prologue
            except (TimeoutError, asyncio.TimeoutError, RuntimeError) as e:
                last_err = e
                if attempt + 1 >= self.DISPATCH_ATTEMPTS:
                    # the caller's cleanup unregisters ITS original rx —
                    # the retry streams registered here must not leak
                    # (unregister is idempotent, double-pop is fine)
                    rt.tcp.unregister(rx.stream_id)
                    raise
                logger.warning(
                    "dispatch to %s attempt %d failed (%s); retrying on a "
                    "fresh stream", self.endpoint.path, attempt + 1, e)
                rt.tcp.unregister(rx.stream_id)
                rx = rt.tcp.register()
        raise last_err

    # -------------------------------------------------------------- scrape
    async def collect_stats(self) -> Dict[int, Any]:
        """Scrape per-instance stats records (reference ServiceClient
        ``collect_services`` via NATS $SRV.STATS; ours ride the KV store —
        same data, discovery-backed transport)."""
        rt = self.endpoint.runtime
        prefix = (f"{self.endpoint.namespace}/stats/"
                  f"{self.endpoint.component}/{self.endpoint.name}:")
        out: Dict[int, Any] = {}
        for e in await rt.store.kv_get_prefix(prefix):
            try:
                out[int(e.key.rsplit(":", 1)[-1], 16)] = json.loads(e.value)
            except Exception:
                continue
        return out

    async def close(self) -> None:
        if self._watch_task is not None:
            self._watch_task.cancel()
        if self._watcher is not None:
            self._watcher.close()
