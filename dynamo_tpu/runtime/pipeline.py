"""Typed request/response pipeline: Frontend → Operators → Backend(engine).

TPU-native re-design of the reference's bidirectional pipeline graph
(lib/runtime/src/pipeline/nodes.rs:70-180, nodes/{sources,sinks}.rs). The
reference wires explicit forward/backward edges between `Source`/`Sink` nodes;
here an :class:`Operator` is simply a stage that sees the forward request, the
downstream engine, and the backward response stream — composition produces one
:class:`AsyncEngine`, so a linked pipeline is itself an engine and can be
served, linked again, or called in-process.

    pipeline = link(preprocessor, backend, engine)
    stream = await pipeline.generate(Context(request))
"""

from __future__ import annotations

import abc
from typing import Generic, TypeVar

from .engine import AsyncEngine, ManyOut, SingleIn

Tin = TypeVar("Tin")
Tmid = TypeVar("Tmid")
Umid = TypeVar("Umid")
Uout = TypeVar("Uout")

__all__ = ["Operator", "link", "ServiceFrontend"]


class Operator(abc.ABC, Generic[Tin, Uout, Tmid, Umid]):
    """A pipeline stage that transforms the request on the way *forward* and
    the response stream on the way *backward*.

    Equivalent role to the reference's ``Operator``/``PipelineOperator`` with
    ``forward_edge``/``backward_edge`` (lib/runtime/src/pipeline/nodes.rs).
    """

    @abc.abstractmethod
    async def generate(self, request: SingleIn[Tin],
                       next_engine: AsyncEngine[Tmid, Umid]) -> ManyOut[Uout]:
        ...

    def attach(self, next_engine: AsyncEngine[Tmid, Umid]) -> AsyncEngine[Tin, Uout]:
        """Bind this operator onto a downstream engine, yielding an engine."""
        return _BoundOperator(self, next_engine)


class _BoundOperator(AsyncEngine[Tin, Uout]):
    def __init__(self, op: Operator, next_engine: AsyncEngine):
        self._op = op
        self._next = next_engine

    async def generate(self, request: SingleIn[Tin]) -> ManyOut[Uout]:
        return await self._op.generate(request, self._next)


class ServiceFrontend(AsyncEngine[Tin, Uout]):
    """Head node of a linked pipeline; also the no-op identity engine wrapper.

    Reference ``ServiceFrontend`` (lib/runtime/src/pipeline/nodes/sources.rs):
    its job there is to hold the graph's entry edge; here it simply delegates,
    existing so graphs have a stable, nameable head.
    """

    def __init__(self, inner: AsyncEngine[Tin, Uout], name: str = "frontend"):
        self._inner = inner
        self.name = name

    async def generate(self, request: SingleIn[Tin]) -> ManyOut[Uout]:
        return await self._inner.generate(request)


def link(*stages) -> AsyncEngine:
    """Compose operators and a terminal engine into one engine.

    ``link(opA, opB, engine)`` ≡ reference graph
    ``Frontend → opA → opB → Backend(engine) → opB' → opA' → Frontend``
    (the backward half is implicit: each operator transforms the returned
    stream before handing it upstream).
    """
    if not stages:
        raise ValueError("link() needs at least a terminal engine")
    tail = stages[-1]
    if isinstance(tail, Operator):
        raise TypeError("last link() stage must be an AsyncEngine, not an Operator")
    engine: AsyncEngine = tail
    for stage in reversed(stages[:-1]):
        if not isinstance(stage, Operator):
            raise TypeError(f"intermediate link() stage {stage!r} must be an Operator")
        engine = stage.attach(engine)
    return ServiceFrontend(engine)
