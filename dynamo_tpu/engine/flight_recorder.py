"""Engine flight recorder: a bounded ring of per-dispatch records plus an
event-loop lag probe, dumpable on demand.

Motivation (ISSUE 7): when a fleet trace shows a worker spending 80 ms in
"decode" the next question is always *which dispatches* — batch fill,
planned tokens, device time vs host gap, which KV tier fed the admission,
how speculation behaved. That truth only exists inside the engine loop
for an instant; the flight recorder keeps the last N dispatch records in
memory (zero steady-state I/O — strictly cheaper than logging) so a
``/debug`` hit or ``llmctl trace dump`` can reconstruct the recent past
of any worker, the same way an aircraft recorder is read after the fact.

Pieces:

- :class:`FlightRecorder` — the ring. ``record(kind, **fields)`` is
  called synchronously from the engine loop (append-only, no locks
  needed under the GIL); ``dump()`` returns the ring newest-last.
- Event-loop **lag probe**: a periodic task that measures how late
  asyncio wakes it up — the direct observable for "something is blocking
  the engine loop" (sync file I/O, long host work), feeding the
  ``nv_llm_engine_loop_lag_ms`` gauge.
- A process-global registry (weak, keyed by name) so the HTTP
  ``/debug`` endpoint can enumerate recorders without plumbing.
- The ``trace/`` KV-store key layout + worker-side watch loop behind
  ``llmctl trace dump``: the CLI writes the control key, every watching
  worker publishes its ring under its lease, the CLI collects.
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import time
import weakref
from collections import deque
from typing import Dict, List, Optional

logger = logging.getLogger("dynamo_tpu.engine.flight")

__all__ = ["FlightRecorder", "register_recorder", "all_recorders",
           "trace_control_key", "trace_dump_key", "watch_trace_dump_loop",
           "TRACE_PREFIX"]

_REGISTRY: "weakref.WeakValueDictionary[str, FlightRecorder]" = \
    weakref.WeakValueDictionary()
_ids = itertools.count()


class FlightRecorder:
    """Bounded ring of per-dispatch records + loop-lag probe."""

    def __init__(self, capacity: int = 512,
                 lag_probe_interval: float = 0.5):
        self._ring: deque = deque(maxlen=capacity)
        self.capacity = capacity
        self.records_total = 0
        self.lag_probe_interval = lag_probe_interval
        self.loop_lag_ms = 0.0       # last probe's scheduling delay
        self.loop_lag_max_ms = 0.0   # high-water mark since start
        self._probe_task: Optional[asyncio.Task] = None

    # --------------------------------------------------------------- records
    def record(self, kind: str, **fields) -> None:
        """Append one dispatch record (engine-loop synchronous; must stay
        allocation-light — scalar fields only, no arrays)."""
        self.records_total += 1
        self._ring.append({"kind": kind, "t": time.time(), **fields})

    def dump(self, last: Optional[int] = None) -> List[dict]:
        out = list(self._ring)
        return out[-last:] if last else out

    def stats(self) -> dict:
        kinds: Dict[str, int] = {}
        for r in self._ring:
            kinds[r["kind"]] = kinds.get(r["kind"], 0) + 1
        return {"records_total": self.records_total,
                "ring": len(self._ring), "capacity": self.capacity,
                "kinds": kinds,
                "loop_lag_ms": round(self.loop_lag_ms, 3),
                "loop_lag_max_ms": round(self.loop_lag_max_ms, 3)}

    # ------------------------------------------------------------- lag probe
    def start_lag_probe(self) -> None:
        """Idempotent; requires a running loop."""
        if self._probe_task is not None and not self._probe_task.done():
            return
        self._probe_task = asyncio.get_running_loop().create_task(
            self._probe_loop(), name="engine-lag-probe")

    def stop_lag_probe(self) -> None:
        if self._probe_task is not None:
            self._probe_task.cancel()
            self._probe_task = None

    async def _probe_loop(self) -> None:
        loop = asyncio.get_running_loop()
        while True:
            t0 = loop.time()
            await asyncio.sleep(self.lag_probe_interval)
            lag_ms = max(loop.time() - t0 - self.lag_probe_interval,
                         0.0) * 1e3
            self.loop_lag_ms = lag_ms
            if lag_ms > self.loop_lag_max_ms:
                self.loop_lag_max_ms = lag_ms
                if lag_ms > 100.0:
                    logger.warning("event-loop lag %.0fms — something is "
                                   "blocking the engine loop", lag_ms)


def register_recorder(recorder: FlightRecorder,
                      name: Optional[str] = None) -> str:
    """Register for /debug enumeration (weak: a collected engine's
    recorder silently drops out). Returns the registry name."""
    name = name or f"engine-{next(_ids)}"
    _REGISTRY[name] = recorder
    return name


def all_recorders() -> Dict[str, FlightRecorder]:
    return dict(_REGISTRY)


# ---------------------------------------------------------------------------
# llmctl trace dump plumbing (the kvtier admin pattern, llm/kv/admin.py)
# ---------------------------------------------------------------------------

TRACE_PREFIX = "trace/"


def trace_control_key(namespace: str) -> str:
    """llmctl writes {"dump": <epoch>} here; watching workers answer."""
    return f"{TRACE_PREFIX}control/{namespace}"


def trace_dump_key(namespace: str, worker_id: int) -> str:
    return f"{TRACE_PREFIX}dump/{namespace}/{worker_id:x}"


async def watch_trace_dump_loop(core, runtime, namespace: str,
                                last: int = 128) -> None:
    """Worker side of ``llmctl trace dump``: on every control-key write,
    publish this worker's flight-recorder ring + tracer stats under its
    lease (so a dead worker's stale dump expires with it)."""
    from ..runtime.kvstore import WatchEventType
    from ..runtime.tracing import tracer
    import json

    lease = await runtime.primary_lease()
    watcher = await runtime.store.watch_prefix(trace_control_key(namespace))
    async for ev in watcher:
        if ev.type != WatchEventType.PUT:
            continue
        try:
            n = int(json.loads(ev.entry.value).get("last", last))
        except Exception:  # noqa: BLE001 — admin input
            n = last
        flight = getattr(core, "flight", None)
        payload = {
            "at": time.time(),
            "worker_id": f"{lease.id:x}",
            "tracer": tracer.stats(),
            "flight": flight.stats() if flight is not None else None,
            "records": flight.dump(last=n) if flight is not None else [],
        }
        try:
            await runtime.store.kv_put(
                trace_dump_key(namespace, lease.id),
                json.dumps(payload).encode(), lease_id=lease.id)
        except Exception:  # noqa: BLE001
            logger.exception("trace dump publish failed")
