"""HF checkpoint → stacked-layer JAX params.

Reads `*.safetensors` from an HF-style model dir (the artifact the MDC's
model_path points at) and produces the stacked layout models/llama.py expects.
Torch linear weights are stored `[out, in]` → transposed to `[in, out]` for
right-multiplication; per-layer tensors are stacked on a leading L axis so
`lax.scan` consumes them directly.
"""

from __future__ import annotations

import glob
import json
import os
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .config import ModelConfig

try:
    from safetensors import safe_open
    _HAVE_ST = True
except ImportError:  # pragma: no cover
    _HAVE_ST = False

_LAYER_MAP = {
    "input_layernorm.weight": ("ln1", False),
    "post_attention_layernorm.weight": ("ln2", False),
    "self_attn.q_proj.weight": ("wq", True),
    "self_attn.k_proj.weight": ("wk", True),
    "self_attn.v_proj.weight": ("wv", True),
    "self_attn.o_proj.weight": ("wo", True),
    "mlp.gate_proj.weight": ("gate", True),
    "mlp.up_proj.weight": ("up", True),
    "mlp.down_proj.weight": ("down", True),
    # qwen2-style attention biases
    "self_attn.q_proj.bias": ("bq", False),
    "self_attn.k_proj.bias": ("bk", False),
    "self_attn.v_proj.bias": ("bv", False),
    # qwen3-style per-head q/k norms
    "self_attn.q_norm.weight": ("q_norm", False),
    "self_attn.k_norm.weight": ("k_norm", False),
    # mixtral MoE router
    "block_sparse_moe.gate.weight": ("router", True),
}

# mixtral expert sub-weights: w1=gate, w3=up, w2=down (all torch [out, in])
_EXPERT_MAP = {"w1": "moe_gate", "w3": "moe_up", "w2": "moe_down"}


def _iter_safetensors(model_dir: str):
    files = sorted(glob.glob(os.path.join(model_dir, "*.safetensors")))
    if not files:
        raise FileNotFoundError(f"no .safetensors under {model_dir}")
    for path in files:
        with safe_open(path, framework="np") as f:
            for name in f.keys():
                yield name, f.get_tensor(name)


def load_llama_params(model_dir: str, cfg: Optional[ModelConfig] = None,
                      dtype=jnp.bfloat16) -> Dict[str, jax.Array]:
    """Load an HF llama/qwen-style checkpoint into the stacked param pytree."""
    if not _HAVE_ST:
        raise RuntimeError("safetensors not available")
    cfg = cfg or ModelConfig.from_model_dir(model_dir)
    L, E = cfg.num_layers, cfg.num_experts
    layer_map = dict(_LAYER_MAP)
    if cfg.post_norms:
        # gemma2: "post_attention_layernorm" is a true post-attn norm (not
        # llama's pre-MLP norm) and the MLP has its own pre/post pair
        layer_map["post_attention_layernorm.weight"] = ("ln1_post", False)
        layer_map["pre_feedforward_layernorm.weight"] = ("ln2", False)
        layer_map["post_feedforward_layernorm.weight"] = ("ln2_post", False)
    staging: Dict[str, list] = {}
    expert_staging: Dict[str, list] = {}   # key → [L][E] tensors
    singles: Dict[str, np.ndarray] = {}
    for name, tensor in _iter_safetensors(model_dir):
        if name == "model.embed_tokens.weight":
            singles["embed"] = tensor
        elif name == "model.norm.weight":
            singles["final_norm"] = tensor
        elif name == "lm_head.weight":
            singles["lm_head"] = tensor.T
        elif name.startswith("model.layers."):
            rest = name[len("model.layers."):]
            idx_str, sub = rest.split(".", 1)
            if sub.startswith("block_sparse_moe.experts."):
                # block_sparse_moe.experts.{e}.w{1,2,3}.weight
                e_str, wname, _ = sub[len("block_sparse_moe.experts."):].split(
                    ".", 2)
                key = _EXPERT_MAP.get(wname)
                if key is None:
                    continue
                grid = expert_staging.setdefault(
                    key, [[None] * E for _ in range(L)])
                grid[int(idx_str)][int(e_str)] = tensor.T
                continue
            mapped = layer_map.get(sub)
            if mapped is None:
                continue  # rotary inv_freq buffers etc.
            key, transpose = mapped
            arr = tensor.T if transpose else tensor
            staging.setdefault(key, [None] * L)[int(idx_str)] = arr

    params: Dict[str, jax.Array] = {}
    for key, arr in singles.items():
        params[key] = jnp.asarray(arr, dtype=dtype)
    for key, per_layer in staging.items():
        missing = [i for i, a in enumerate(per_layer) if a is None]
        if missing:
            raise ValueError(f"checkpoint missing layers {missing} for {key}")
        params[f"layers.{key}"] = jnp.asarray(
            np.stack(per_layer, axis=0), dtype=dtype)
    for key, grid in expert_staging.items():
        missing = [(i, j) for i, row in enumerate(grid)
                   for j, a in enumerate(row) if a is None]
        if missing:
            raise ValueError(f"checkpoint missing experts {missing[:4]}… "
                             f"for {key}")
        params[f"layers.{key}"] = jnp.asarray(
            np.stack([np.stack(row, axis=0) for row in grid], axis=0),
            dtype=dtype)
    if "lm_head" not in params and not cfg.tie_word_embeddings:
        # some checkpoints tie implicitly by omitting lm_head
        cfg.tie_word_embeddings = True
    return params


def save_hf_style(params: Dict[str, jax.Array], cfg: ModelConfig,
                  out_dir: str) -> None:
    """Write params back out as a single HF-style safetensors file (used by
    tests to cross-check against the torch reference implementation)."""
    from safetensors.numpy import save_file
    os.makedirs(out_dir, exist_ok=True)

    def c(a) -> np.ndarray:
        # save_file serializes the raw buffer — it MUST be C-contiguous
        # (np.asarray of a jax array can surface a column-major buffer).
        return np.ascontiguousarray(np.asarray(a, np.float32))

    out: Dict[str, np.ndarray] = {
        "model.embed_tokens.weight": c(params["embed"]),
        "model.norm.weight": c(params["final_norm"]),
    }
    if "lm_head" in params:
        out["lm_head.weight"] = c(np.asarray(params["lm_head"], np.float32).T)
    inv = {v[0]: (k, v[1]) for k, v in _LAYER_MAP.items()}
    if cfg.post_norms:   # gemma2 norm naming (see load_llama_params)
        inv["ln1_post"] = ("post_attention_layernorm.weight", False)
        inv["ln2"] = ("pre_feedforward_layernorm.weight", False)
        inv["ln2_post"] = ("post_feedforward_layernorm.weight", False)
    inv_experts = {v: k for k, v in _EXPERT_MAP.items()}
    for key, (hf_sub, transpose) in inv.items():
        if f"layers.{key}" not in params:
            continue
        stacked = np.ascontiguousarray(
            np.asarray(params[f"layers.{key}"], np.float32))
        for i in range(stacked.shape[0]):
            arr = stacked[i].T if transpose else stacked[i]
            out[f"model.layers.{i}.{hf_sub}"] = np.ascontiguousarray(arr)
    for key, wname in inv_experts.items():
        if f"layers.{key}" not in params:
            continue
        stacked = np.asarray(params[f"layers.{key}"], np.float32)  # [L,E,..]
        for i in range(stacked.shape[0]):
            for e in range(stacked.shape[1]):
                out[(f"model.layers.{i}.block_sparse_moe.experts."
                     f"{e}.{wname}.weight")] = np.ascontiguousarray(
                         stacked[i, e].T)
    save_file(out, os.path.join(out_dir, "model.safetensors"))
