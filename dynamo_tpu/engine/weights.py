"""HF checkpoint → stacked-layer JAX params.

Reads `*.safetensors` from an HF-style model dir (the artifact the MDC's
model_path points at) and produces the stacked layout models/llama.py expects.
Torch linear weights are stored `[out, in]` → transposed to `[in, out]` for
right-multiplication; per-layer tensors are stacked on a leading L axis so
`lax.scan` consumes them directly.
"""

from __future__ import annotations

import glob
import json
import os
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .config import ModelConfig

try:
    from safetensors import safe_open
    _HAVE_ST = True
except ImportError:  # pragma: no cover
    _HAVE_ST = False

_LAYER_MAP = {
    "input_layernorm.weight": ("ln1", False),
    "post_attention_layernorm.weight": ("ln2", False),
    "self_attn.q_proj.weight": ("wq", True),
    "self_attn.k_proj.weight": ("wk", True),
    "self_attn.v_proj.weight": ("wv", True),
    "self_attn.o_proj.weight": ("wo", True),
    "mlp.gate_proj.weight": ("gate", True),
    "mlp.up_proj.weight": ("up", True),
    "mlp.down_proj.weight": ("down", True),
    # qwen2-style attention biases
    "self_attn.q_proj.bias": ("bq", False),
    "self_attn.k_proj.bias": ("bk", False),
    "self_attn.v_proj.bias": ("bv", False),
    # qwen3-style per-head q/k norms
    "self_attn.q_norm.weight": ("q_norm", False),
    "self_attn.k_norm.weight": ("k_norm", False),
    # mixtral MoE router
    "block_sparse_moe.gate.weight": ("router", True),
    # qwen3-moe / qwen2-moe router (same role, different HF naming; the
    # expert tensors live under mlp.experts.{e}.*_proj — _EXPERT_PREFIXES)
    "mlp.gate.weight": ("router", True),
    # qwen2_moe shared expert (dense swiglu + sigmoid gate)
    "mlp.shared_expert.gate_proj.weight": ("sh_gate", True),
    "mlp.shared_expert.up_proj.weight": ("sh_up", True),
    "mlp.shared_expert.down_proj.weight": ("sh_down", True),
    "mlp.shared_expert_gate.weight": ("sh_router", True),
    # deepseek shared experts (PLURAL naming; additive, ungated)
    "mlp.shared_experts.gate_proj.weight": ("sh_gate", True),
    "mlp.shared_experts.up_proj.weight": ("sh_up", True),
    "mlp.shared_experts.down_proj.weight": ("sh_down", True),
    # deepseek MLA attention (models/mla.py)
    "self_attn.q_a_proj.weight": ("wq_a", True),
    "self_attn.q_a_layernorm.weight": ("q_a_norm", False),
    "self_attn.q_b_proj.weight": ("wq_b", True),
    "self_attn.kv_a_proj_with_mqa.weight": ("wkv_a", True),
    "self_attn.kv_a_layernorm.weight": ("kv_norm", False),
    "self_attn.kv_b_proj.weight": ("wkv_b", True),
}

# mixtral expert sub-weights: w1=gate, w3=up, w2=down (all torch [out, in])
_EXPERT_MAP = {"w1": "moe_gate", "w3": "moe_up", "w2": "moe_down",
               # qwen3-moe naming for the same three matmuls
               "gate_proj": "moe_gate", "up_proj": "moe_up",
               "down_proj": "moe_down"}

# per-family expert tensor prefixes under model.layers.{i}.
_EXPERT_PREFIXES = ("block_sparse_moe.experts.", "mlp.experts.")


def _layer_map_for(cfg: ModelConfig) -> Dict[str, tuple]:
    """HF layer-tensor suffix → (stacked key, transpose) for this family.
    One home — the replicated and sharded loaders must agree."""
    layer_map = dict(_LAYER_MAP)
    if cfg.post_norms:
        # gemma2: "post_attention_layernorm" is a true post-attn norm (not
        # llama's pre-MLP norm) and the MLP has its own pre/post pair
        layer_map["post_attention_layernorm.weight"] = ("ln1_post", False)
        layer_map["pre_feedforward_layernorm.weight"] = ("ln2", False)
        layer_map["post_feedforward_layernorm.weight"] = ("ln2_post", False)
    if (cfg.model_type in ("deepseek_v2", "deepseek_v3")
            and cfg.num_experts > 0):
        # hybrid sparsity: mlp.*_proj exists only on the dense-prefix
        # layers and lands in the dense_* stacks (_partial_ranges)
        layer_map["mlp.gate_proj.weight"] = ("dense_gate", True)
        layer_map["mlp.up_proj.weight"] = ("dense_up", True)
        layer_map["mlp.down_proj.weight"] = ("dense_down", True)
    if cfg.moe_routing == "sigmoid_noaux":
        # deepseek_v3 router bias buffer (persistent, so it is in every
        # checkpoint's state dict)
        layer_map["mlp.gate.e_score_correction_bias"] = (
            "router_bias", False)
    if cfg.model_type == "phi3":
        # phi3 ships FUSED projections (_fused_sections); the split
        # suffixes must not also match
        for k in ("self_attn.q_proj.weight", "self_attn.k_proj.weight",
                  "self_attn.v_proj.weight", "mlp.gate_proj.weight",
                  "mlp.up_proj.weight"):
            layer_map.pop(k, None)
    return layer_map


def _fused_sections(cfg: ModelConfig) -> Dict[str, list]:
    """Fused HF layer tensors → the row sections (torch [out, in]
    orientation) that map onto our split keys: phi3 packs q/k/v into
    ``qkv_proj`` and gate/up into ``gate_up_proj`` (HF Phi3Config).
    Returns {suffix: [(key, row_offset, row_count)]}; one home for both
    loaders."""
    if cfg.model_type != "phi3":
        return {}
    qd = cfg.num_heads * cfg.head_dim
    kvd = cfg.num_kv_heads * cfg.head_dim
    return {
        "self_attn.qkv_proj.weight": [
            ("wq", 0, qd), ("wk", qd, kvd), ("wv", qd + kvd, kvd)],
        "mlp.gate_up_proj.weight": [
            ("gate", 0, cfg.intermediate_size),
            ("up", cfg.intermediate_size, cfg.intermediate_size)],
    }


def _partial_ranges(cfg: ModelConfig):
    """Stacked keys that cover only a LAYER RANGE (deepseek hybrid
    sparsity): key -> (lo, hi) global layer bounds. Empty for uniform
    families."""
    if (cfg.model_type not in ("deepseek_v2", "deepseek_v3")
            or cfg.num_experts == 0):
        return {}
    k, L = cfg.first_k_dense, cfg.num_layers
    out = {key: (0, k) for key in ("dense_gate", "dense_up",
                                   "dense_down")}
    for key in ("router", "router_bias", "moe_gate", "moe_up",
                "moe_down", "sh_gate", "sh_up", "sh_down"):
        out[key] = (k, L)
    return out


def load_params_auto(model_dir: str, cfg: Optional[ModelConfig] = None,
                     mesh=None, dtype=jnp.bfloat16) -> Dict[str, jax.Array]:
    """THE loader entry point: streams shards straight from disk when a
    mesh is given (host peak = one shard — the 70B path), replicated
    otherwise. MoE and MLA checkpoints use the replicated reader even
    with a mesh (EngineCore's shard_params re-places them) — so a
    sharded MLA/MoE load stages the FULL model in host RAM; shard-
    streaming those layouts is the open limit, not the engine (which
    serves MLA over dp/tp/ep meshes)."""
    cfg = cfg or ModelConfig.from_model_dir(model_dir)
    if mesh is not None and cfg.num_experts == 0 and cfg.kv_lora_rank == 0:
        return load_llama_params_sharded(model_dir, mesh, cfg, dtype=dtype)
    return load_llama_params(model_dir, cfg, dtype=dtype)


def _iter_safetensors(model_dir: str):
    files = sorted(glob.glob(os.path.join(model_dir, "*.safetensors")))
    if not files:
        raise FileNotFoundError(f"no .safetensors under {model_dir}")
    for path in files:
        with safe_open(path, framework="np") as f:
            for name in f.keys():
                yield name, f.get_tensor(name)


def load_llama_params(model_dir: str, cfg: Optional[ModelConfig] = None,
                      dtype=jnp.bfloat16) -> Dict[str, jax.Array]:
    """Load an HF llama/qwen-style checkpoint into the stacked param pytree."""
    if not _HAVE_ST:
        raise RuntimeError("safetensors not available")
    cfg = cfg or ModelConfig.from_model_dir(model_dir)
    L, E = cfg.num_layers, cfg.num_experts
    layer_map = _layer_map_for(cfg)
    fused = _fused_sections(cfg)
    staging: Dict[str, list] = {}
    expert_staging: Dict[str, list] = {}   # key → [L][E] tensors
    singles: Dict[str, np.ndarray] = {}
    for name, tensor in _iter_safetensors(model_dir):
        if name == "model.embed_tokens.weight":
            singles["embed"] = tensor
        elif name == "model.norm.weight":
            singles["final_norm"] = tensor
        elif name == "lm_head.weight":
            singles["lm_head"] = tensor.T
        elif name.startswith("model.layers."):
            rest = name[len("model.layers."):]
            idx_str, sub = rest.split(".", 1)
            if int(idx_str) >= L:
                if int(idx_str) < L + cfg.num_nextn_predict_layers:
                    # deepseek_v3 MTP heads live at model.layers.{L}+ —
                    # generation never runs them (HF skips them too);
                    # their attention-shaped names must not land in the
                    # decoder stacks. The bound keeps the mismatch
                    # guard: only the declared MTP indices skip
                    continue
                raise ValueError(
                    f"checkpoint tensor {name} is beyond the config's "
                    f"{L} layers (+{cfg.num_nextn_predict_layers} MTP) "
                    f"— config.json/checkpoint mismatch")
            expert_prefix = next(
                (p for p in _EXPERT_PREFIXES if sub.startswith(p)), None)
            if expert_prefix is not None:
                # {prefix}{e}.w{1,2,3}.weight (mixtral) or
                # {prefix}{e}.{gate,up,down}_proj.weight (qwen3-moe)
                e_str, wname, _ = sub[len(expert_prefix):].split(".", 2)
                key = _EXPERT_MAP.get(wname)
                if key is None:
                    continue
                grid = expert_staging.setdefault(
                    key, [[None] * E for _ in range(L)])
                grid[int(idx_str)][int(e_str)] = tensor.T
                continue
            if sub in fused:
                # split the fused tensor's torch rows into our keys
                for key, off, cnt in fused[sub]:
                    staging.setdefault(key, [None] * L)[int(idx_str)] = \
                        tensor[off:off + cnt].T
                continue
            mapped = layer_map.get(sub)
            if mapped is None:
                continue  # rotary inv_freq buffers etc.
            key, transpose = mapped
            arr = tensor.T if transpose else tensor
            staging.setdefault(key, [None] * L)[int(idx_str)] = arr

    params: Dict[str, jax.Array] = {}
    partial = _partial_ranges(cfg)
    for key, arr in singles.items():
        params[key] = jnp.asarray(arr, dtype=dtype)
    for key, per_layer in staging.items():
        lo, hi = partial.get(key, (0, L))
        rows = per_layer[lo:hi]
        missing = [lo + i for i, a in enumerate(rows) if a is None]
        extra = [i for i, a in enumerate(per_layer) if a is not None
                 and not (lo <= i < hi)]
        if missing or extra:
            raise ValueError(
                f"checkpoint layer coverage wrong for {key}: missing "
                f"{missing[:4]}, outside-range {extra[:4]} "
                f"(expected layers [{lo}, {hi}))")
        params[f"layers.{key}"] = jnp.asarray(
            np.stack(rows, axis=0), dtype=dtype)
    for key, grid in expert_staging.items():
        lo, hi = partial.get(key, (0, L))
        rows = grid[lo:hi]
        missing = [(lo + i, j) for i, row in enumerate(rows)
                   for j, a in enumerate(row) if a is None]
        extra = [(i, j) for i, row in enumerate(grid)
                 for j, a in enumerate(row)
                 if a is not None and not (lo <= i < hi)]
        if extra:
            raise ValueError(
                f"checkpoint expert coverage wrong for {key}: tensors "
                f"at layers outside [{lo}, {hi}): {extra[:4]}")
        if missing:
            raise ValueError(f"checkpoint missing experts {missing[:4]}… "
                             f"for {key}")
        params[f"layers.{key}"] = jnp.asarray(
            np.stack([np.stack(row, axis=0) for row in rows], axis=0),
            dtype=dtype)
    if "lm_head" not in params and not cfg.tie_word_embeddings:
        # some checkpoints tie implicitly by omitting lm_head
        cfg.tie_word_embeddings = True
    return params


def load_llama_params_sharded(model_dir: str, mesh,
                              cfg: Optional[ModelConfig] = None,
                              dtype=jnp.bfloat16) -> Dict[str, jax.Array]:
    """Load a checkpoint DIRECTLY into its tp-sharded device layout.

    The replicated loader (load_llama_params) stages the whole model in
    host numpy — ~140 GB of host RAM for a 70B bf16 checkpoint, and each
    device then holds a full copy until shard_params re-places it. This
    loader reads only each device's shard from disk (safetensors
    `get_slice` reads sub-ranges without materializing the tensor) and
    assembles sharded jax Arrays with `make_array_from_callback`, so peak
    host memory is ONE shard — the practical enabler for 70B TP-8
    serving (BASELINE config 4; the reference gets this from its external
    engines' sharded loaders).

    Llama/qwen/gemma families (stacked dense layers) only. MoE expert
    checkpoints raise — route them through ``load_params_auto``, which
    uses the replicated reader + shard_params for them.
    """
    if not _HAVE_ST:
        raise RuntimeError("safetensors not available")
    if (cfg or ModelConfig.from_model_dir(model_dir)).kv_lora_rank > 0:
        raise NotImplementedError(
            "shard-streaming MLA checkpoints is not implemented — route "
            "through load_params_auto (replicated read + shard_params; "
            "host peak = full model)")
    import contextlib

    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..parallel.sharding import fit_or_replicate, param_pspecs
    cfg = cfg or ModelConfig.from_model_dir(model_dir)
    L = cfg.num_layers

    # index pass: tensor name → OPEN file handle (headers parsed once —
    # a 70B TP-8 load issues thousands of slice reads)
    files = sorted(glob.glob(os.path.join(model_dir, "*.safetensors")))
    if not files:
        raise FileNotFoundError(f"no .safetensors under {model_dir}")
    with contextlib.ExitStack() as stack:
        handles = {path: stack.enter_context(
            safe_open(path, framework="np")) for path in files}
        where: Dict[str, object] = {}
        for f in handles.values():
            for name in f.keys():
                where[name] = f

        # "wq" → [(hf_suffix, T?), ...]: some keys have per-family HF
        # namings (router: mixtral block_sparse_moe.gate vs qwen3-moe
        # mlp.gate) — resolve by whichever name the checkpoint contains.
        # No MoE sharded load SUCCEEDS (layers.moe_* raises guidance
        # below), but resolving the router by presence lets BOTH families
        # reach that guidance instead of a bogus missing-layers error
        by_key: Dict[str, list] = {}
        for suffix, (key, transpose) in _layer_map_for(cfg).items():
            by_key.setdefault(key, []).append((suffix, transpose, None))
        for suffix, sections in _fused_sections(cfg).items():
            # fused tensors (phi3 qkv_proj / gate_up_proj): each split
            # key reads a torch-row window of the fused tensor — the
            # slice reader shifts AND CLAMPS the logical out-axis into
            # the section (col_off=None means unfused; 0 is a real fused
            # offset whose open slices must still clamp to the section)
            for key, off, _cnt in sections:
                by_key.setdefault(key, []).append((suffix, True, off))
        singles = {"embed": ("model.embed_tokens.weight", False),
                   "final_norm": ("model.norm.weight", False),
                   "lm_head": ("lm_head.weight", True)}

        def read_slice(name: str, idx, transpose: bool,
                       col_off=None, col_dim: int = 0) -> np.ndarray:
            """Read tensor[idx] from disk; idx indexes the LOGICAL
            (already transposed) orientation, so transposed reads swap
            the slices. ``col_off`` (None = unfused) shifts the logical
            out-axis into a fused tensor's section and CLAMPS open
            slices to the section width ``col_dim`` — an offset of 0 is
            a real fused section whose slice(None) would otherwise read
            the whole fused axis."""
            sl = where[name].get_slice(name)
            if transpose:
                if len(idx) == 2:
                    c = idx[1]
                    if col_off is not None:
                        start, stop, step = c.indices(col_dim)
                        c = slice(start + col_off, stop + col_off, step)
                    return np.ascontiguousarray(sl[c, idx[0]].T)
                return np.ascontiguousarray(sl[idx[0]].T)
            return np.ascontiguousarray(sl[tuple(idx)])

        specs = param_pspecs(cfg)
        params: Dict[str, jax.Array] = {}
        from .models.llama import param_shapes
        for pkey, shape in param_shapes(cfg).items():
            spec = fit_or_replicate(pkey, shape, specs.get(pkey, P()),
                                    mesh, _np_dtype(dtype).itemsize)
            sharding = NamedSharding(mesh, spec)
            if pkey in singles:
                name, transpose = singles[pkey]
                if name not in where:
                    continue        # tied checkpoints omit lm_head

                def cb(idx, name=name, transpose=transpose):
                    return read_slice(name, idx, transpose).astype(
                        _np_dtype(dtype))

                params[pkey] = jax.make_array_from_callback(
                    shape, sharding, cb)
                continue
            if pkey.startswith("layers.") and pkey[7:] in by_key:
                cands = by_key[pkey[7:]]
                suffix, transpose, col_off = next(
                    (c for c in cands
                     if f"model.layers.0.{c[0]}" in where), cands[0])
                names = [f"model.layers.{i}.{suffix}" for i in range(L)]
                if any(n not in where for n in names):
                    missing = [i for i, n in enumerate(names)
                               if n not in where]
                    raise ValueError(
                        f"checkpoint missing layers {missing[:4]}… "
                        f"for {pkey}")
                col_dim = shape[-1]

                def cb(idx, names=names, transpose=transpose,
                       col_off=col_off, col_dim=col_dim):
                    l_sl = idx[0]
                    rest = tuple(idx[1:])
                    rows = [read_slice(names[i], rest, transpose,
                                       col_off, col_dim)
                            for i in range(*l_sl.indices(L))]
                    return np.stack(rows, axis=0).astype(_np_dtype(dtype))

                params[pkey] = jax.make_array_from_callback(
                    shape, sharding, cb)
                continue
            raise NotImplementedError(
                f"sharded loading not implemented for {pkey} "
                f"(MoE checkpoints: use load_params_auto, which falls "
                f"back to load_llama_params + shard_params)")

    if "lm_head" not in params and not cfg.tie_word_embeddings:
        cfg.tie_word_embeddings = True
    return params


def _np_dtype(dtype):
    name = jnp.dtype(dtype).name
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes
        return np.dtype(getattr(ml_dtypes, name))


def save_hf_style(params: Dict[str, jax.Array], cfg: ModelConfig,
                  out_dir: str) -> None:
    """Write params back out as a single HF-style safetensors file (used by
    tests to cross-check against the torch reference implementation)."""
    from safetensors.numpy import save_file
    if (cfg.model_type in ("deepseek_v2", "deepseek_v3")
            and cfg.num_experts > 0):
        raise NotImplementedError(
            "save_hf_style cannot write the deepseek hybrid MoE layout "
            "(partial layer stacks + deepseek expert naming); the MLA "
            "tests carry their own converter")
    os.makedirs(out_dir, exist_ok=True)

    def c(a) -> np.ndarray:
        # save_file serializes the raw buffer — it MUST be C-contiguous
        # (np.asarray of a jax array can surface a column-major buffer).
        return np.ascontiguousarray(np.asarray(a, np.float32))

    out: Dict[str, np.ndarray] = {
        "model.embed_tokens.weight": c(params["embed"]),
        "model.norm.weight": c(params["final_norm"]),
    }
    if "lm_head" in params:
        out["lm_head.weight"] = c(np.asarray(params["lm_head"], np.float32).T)
    inv = {v[0]: (k, v[1]) for k, v in _LAYER_MAP.items()}
    # _LAYER_MAP maps BOTH shared-expert namings (qwen2 singular,
    # deepseek plural) onto sh_*; the dict inversion keeps whichever
    # iterated last — pin the family's own naming explicitly
    if cfg.model_type == "qwen2_moe":
        inv["sh_gate"] = ("mlp.shared_expert.gate_proj.weight", True)
        inv["sh_up"] = ("mlp.shared_expert.up_proj.weight", True)
        inv["sh_down"] = ("mlp.shared_expert.down_proj.weight", True)
    if cfg.post_norms:   # gemma2 norm naming (see load_llama_params)
        inv["ln1_post"] = ("post_attention_layernorm.weight", False)
        inv["ln2"] = ("pre_feedforward_layernorm.weight", False)
        inv["ln2_post"] = ("post_feedforward_layernorm.weight", False)
    # two HF namings map to "router"/each expert matmul (mixtral vs
    # qwen3-moe); saving must pick the family's names explicitly
    if cfg.model_type in ("qwen3_moe", "qwen2_moe"):
        inv["router"] = ("mlp.gate.weight", True)
        inv_experts = {"moe_gate": "gate_proj", "moe_up": "up_proj",
                       "moe_down": "down_proj"}
        expert_prefix = "mlp.experts."
    else:
        inv["router"] = ("block_sparse_moe.gate.weight", True)
        inv_experts = {"moe_gate": "w1", "moe_up": "w3",
                       "moe_down": "w2"}
        expert_prefix = "block_sparse_moe.experts."
    fused = _fused_sections(cfg)
    for suffix, sections in fused.items():
        # phi3 fused tensors: concatenate our split keys back into the
        # HF torch-row layout (inverse of the loaders' split)
        for key, _off, _cnt in sections:
            inv.pop(key, None)
        L = cfg.num_layers
        for i in range(L):
            rows = [np.asarray(params[f"layers.{k}"][i], np.float32).T
                    for k, _o, _c in sections]
            out[f"model.layers.{i}.{suffix}"] = np.ascontiguousarray(
                np.concatenate(rows, axis=0))
    for key, (hf_sub, transpose) in inv.items():
        if f"layers.{key}" not in params:
            continue
        stacked = np.ascontiguousarray(
            np.asarray(params[f"layers.{key}"], np.float32))
        for i in range(stacked.shape[0]):
            arr = stacked[i].T if transpose else stacked[i]
            out[f"model.layers.{i}.{hf_sub}"] = np.ascontiguousarray(arr)
    for key, wname in inv_experts.items():
        if f"layers.{key}" not in params:
            continue
        stacked = np.asarray(params[f"layers.{key}"], np.float32)  # [L,E,..]
        for i in range(stacked.shape[0]):
            for e in range(stacked.shape[1]):
                out[(f"model.layers.{i}.{expert_prefix}"
                     f"{e}.{wname}.weight")] = np.ascontiguousarray(
                         stacked[i, e].T)
    save_file(out, os.path.join(out_dir, "model.safetensors"))
