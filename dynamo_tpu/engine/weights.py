"""HF checkpoint → stacked-layer JAX params.

Reads `*.safetensors` from an HF-style model dir (the artifact the MDC's
model_path points at) and produces the stacked layout models/llama.py expects.
Torch linear weights are stored `[out, in]` → transposed to `[in, out]` for
right-multiplication; per-layer tensors are stacked on a leading L axis so
`lax.scan` consumes them directly.
"""

from __future__ import annotations

import contextlib
import glob
import os
import weakref
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .config import ModelConfig

try:
    from safetensors import safe_open
    _HAVE_ST = True
except ImportError:  # pragma: no cover
    _HAVE_ST = False

_LAYER_MAP = {
    "input_layernorm.weight": ("ln1", False),
    "post_attention_layernorm.weight": ("ln2", False),
    "self_attn.q_proj.weight": ("wq", True),
    "self_attn.k_proj.weight": ("wk", True),
    "self_attn.v_proj.weight": ("wv", True),
    "self_attn.o_proj.weight": ("wo", True),
    "mlp.gate_proj.weight": ("gate", True),
    "mlp.up_proj.weight": ("up", True),
    "mlp.down_proj.weight": ("down", True),
    # qwen2-style attention biases
    "self_attn.q_proj.bias": ("bq", False),
    "self_attn.k_proj.bias": ("bk", False),
    "self_attn.v_proj.bias": ("bv", False),
    # qwen3-style per-head q/k norms
    "self_attn.q_norm.weight": ("q_norm", False),
    "self_attn.k_norm.weight": ("k_norm", False),
    # mixtral MoE router
    "block_sparse_moe.gate.weight": ("router", True),
    # qwen3-moe / qwen2-moe router (same role, different HF naming; the
    # expert tensors live under mlp.experts.{e}.*_proj — _EXPERT_PREFIXES)
    "mlp.gate.weight": ("router", True),
    # qwen2_moe shared expert (dense swiglu + sigmoid gate)
    "mlp.shared_expert.gate_proj.weight": ("sh_gate", True),
    "mlp.shared_expert.up_proj.weight": ("sh_up", True),
    "mlp.shared_expert.down_proj.weight": ("sh_down", True),
    "mlp.shared_expert_gate.weight": ("sh_router", True),
    # deepseek shared experts (PLURAL naming; additive, ungated)
    "mlp.shared_experts.gate_proj.weight": ("sh_gate", True),
    "mlp.shared_experts.up_proj.weight": ("sh_up", True),
    "mlp.shared_experts.down_proj.weight": ("sh_down", True),
    # deepseek MLA attention (models/mla.py)
    "self_attn.q_a_proj.weight": ("wq_a", True),
    "self_attn.q_a_layernorm.weight": ("q_a_norm", False),
    "self_attn.q_b_proj.weight": ("wq_b", True),
    "self_attn.kv_a_proj_with_mqa.weight": ("wkv_a", True),
    "self_attn.kv_a_layernorm.weight": ("kv_norm", False),
    "self_attn.kv_b_proj.weight": ("wkv_b", True),
}

# mixtral expert sub-weights: w1=gate, w3=up, w2=down (all torch [out, in])
_EXPERT_MAP = {"w1": "moe_gate", "w3": "moe_up", "w2": "moe_down",
               # qwen3-moe naming for the same three matmuls
               "gate_proj": "moe_gate", "up_proj": "moe_up",
               "down_proj": "moe_down"}

# per-family expert tensor prefixes under model.layers.{i}.
_EXPERT_PREFIXES = ("block_sparse_moe.experts.", "mlp.experts.")


def _layer_map_for(cfg: ModelConfig) -> Dict[str, tuple]:
    """HF layer-tensor suffix → (stacked key, transpose) for this family.
    One home — the replicated and sharded loaders must agree."""
    layer_map = dict(_LAYER_MAP)
    if cfg.post_norms:
        # gemma2: "post_attention_layernorm" is a true post-attn norm (not
        # llama's pre-MLP norm) and the MLP has its own pre/post pair
        layer_map["post_attention_layernorm.weight"] = ("ln1_post", False)
        layer_map["pre_feedforward_layernorm.weight"] = ("ln2", False)
        layer_map["post_feedforward_layernorm.weight"] = ("ln2_post", False)
    if (cfg.model_type in ("deepseek_v2", "deepseek_v3")
            and cfg.num_experts > 0):
        # hybrid sparsity: mlp.*_proj exists only on the dense-prefix
        # layers and lands in the dense_* stacks (_partial_ranges)
        layer_map["mlp.gate_proj.weight"] = ("dense_gate", True)
        layer_map["mlp.up_proj.weight"] = ("dense_up", True)
        layer_map["mlp.down_proj.weight"] = ("dense_down", True)
    if cfg.moe_routing == "sigmoid_noaux":
        # deepseek_v3 router bias buffer (persistent, so it is in every
        # checkpoint's state dict)
        layer_map["mlp.gate.e_score_correction_bias"] = (
            "router_bias", False)
    if cfg.model_type == "phi3":
        # phi3 ships FUSED projections (_fused_sections); the split
        # suffixes must not also match
        for k in ("self_attn.q_proj.weight", "self_attn.k_proj.weight",
                  "self_attn.v_proj.weight", "mlp.gate_proj.weight",
                  "mlp.up_proj.weight"):
            layer_map.pop(k, None)
    return layer_map


def _fused_sections(cfg: ModelConfig) -> Dict[str, list]:
    """Fused HF layer tensors → the row sections (torch [out, in]
    orientation) that map onto our split keys: phi3 packs q/k/v into
    ``qkv_proj`` and gate/up into ``gate_up_proj`` (HF Phi3Config).
    Returns {suffix: [(key, row_offset, row_count)]}; one home for both
    loaders."""
    if cfg.model_type != "phi3":
        return {}
    qd = cfg.num_heads * cfg.head_dim
    kvd = cfg.num_kv_heads * cfg.head_dim
    return {
        "self_attn.qkv_proj.weight": [
            ("wq", 0, qd), ("wk", qd, kvd), ("wv", qd + kvd, kvd)],
        "mlp.gate_up_proj.weight": [
            ("gate", 0, cfg.intermediate_size),
            ("up", cfg.intermediate_size, cfg.intermediate_size)],
    }


def _partial_ranges(cfg: ModelConfig):
    """Stacked keys that cover only a LAYER RANGE (deepseek hybrid
    sparsity): key -> (lo, hi) global layer bounds. Empty for uniform
    families."""
    if (cfg.model_type not in ("deepseek_v2", "deepseek_v3")
            or cfg.num_experts == 0):
        return {}
    k, L = cfg.first_k_dense, cfg.num_layers
    out = {key: (0, k) for key in ("dense_gate", "dense_up",
                                   "dense_down")}
    for key in ("router", "router_bias", "moe_gate", "moe_up",
                "moe_down", "sh_gate", "sh_up", "sh_down"):
        out[key] = (k, L)
    return out


def load_params_auto(model_dir: str, cfg: Optional[ModelConfig] = None,
                     mesh=None, dtype=jnp.bfloat16) -> Dict[str, jax.Array]:
    """THE loader entry point: streams each device's shard straight from
    disk when a mesh is given — llama/qwen/gemma/phi3 AND MoE/MLA
    (deepseek) layouts — so host peak is one param-stack shard, never the
    full model (the enabler for 70B / deepseek-class bring-up on a
    standard TPU-VM host; the reference gets this from its engines'
    per-rank shard loaders, lib/llm vllm subprocess.rs:37-41). Without a
    mesh, the replicated reader stages the whole model in host numpy."""
    cfg = cfg or ModelConfig.from_model_dir(model_dir)
    if mesh is not None:
        return load_params_sharded(model_dir, mesh, cfg, dtype=dtype)
    return load_llama_params(model_dir, cfg, dtype=dtype)


class LoadAccounting:
    """Live-host-byte tracker for checkpoint loads (weakref-finalized):
    ``peak`` is the high-water mark of HEAP bytes simultaneously alive
    among the loader's STAGING copies — read-slice transients in the
    streaming path, full param-stack assemblies in the replicated path.
    The buffers the streaming loader hands to jax.make_array_from_callback
    are excluded: they become the device shard storage itself (the CPU
    backend zero-copy-aliases them), i.e. they are the model, not
    staging. Only arrays that OWN their buffer are counted: safetensors
    hands out mmap-backed views (file-cache pages the OS can evict — not
    heap), and a view's lifetime says nothing about its root buffer's
    anyway."""

    def __init__(self) -> None:
        self.live = 0
        self.peak = 0
        self.total = 0
        # largest single buffer handed to jax.make_array_from_callback —
        # the device shard storage itself (alive only until the transfer
        # completes on a real accelerator; aliased forever on CPU), kept
        # as its own number so staging and handoff cannot be conflated
        self.largest_handoff = 0

    def track(self, arr: np.ndarray) -> np.ndarray:
        if arr.base is not None:   # view — not loader-owned heap
            return arr
        nb = int(arr.nbytes)
        self.live += nb
        self.total += nb
        if self.live > self.peak:
            self.peak = self.live
        weakref.finalize(arr, self._release, nb)
        return arr

    def transient(self, nbytes: int) -> None:
        """Explicit accounting for a lexically-scoped staging buffer:
        ``nbytes`` live briefly ON TOP of the tracked live set. Used by
        the streaming read path, whose buffer lifetimes are exact
        (dead before the next read) — weakref tracking can't see them
        because safetensors slice reads surface as views of fresh
        memoryview-backed copies (measured), not as owning arrays."""
        if self.live + nbytes > self.peak:
            self.peak = self.live + nbytes
        self.total += nbytes

    def handoff(self, nbytes: int) -> None:
        if nbytes > self.largest_handoff:
            self.largest_handoff = nbytes

    def _release(self, nb: int) -> None:
        self.live -= nb


_ACCOUNTING: Optional[LoadAccounting] = None


@contextlib.contextmanager
def load_accounting():
    """``with load_accounting() as acct: load(...)`` — afterwards
    ``acct.peak``/``acct.total`` hold the staging byte counts and
    ``acct.largest_handoff`` the biggest shard buffer handed to jax, for
    every loader call made inside the block."""
    global _ACCOUNTING
    acct = LoadAccounting()
    prev = _ACCOUNTING
    _ACCOUNTING = acct
    try:
        yield acct
    finally:
        _ACCOUNTING = prev


def _track(arr: np.ndarray) -> np.ndarray:
    if _ACCOUNTING is not None:
        _ACCOUNTING.track(arr)
    return arr


def _note_handoff(arr: np.ndarray) -> np.ndarray:
    if _ACCOUNTING is not None:
        _ACCOUNTING.handoff(int(arr.nbytes))
    return arr


def _note_transient(nbytes: int) -> None:
    if _ACCOUNTING is not None:
        _ACCOUNTING.transient(int(nbytes))


# safetensors dtype tag -> on-disk bytes per element
_ST_ITEMSIZE = {"F64": 8, "I64": 8, "U64": 8, "F32": 4, "I32": 4,
                "U32": 4, "F16": 2, "BF16": 2, "I16": 2, "U16": 2,
                "I8": 1, "U8": 1, "BOOL": 1, "F8_E4M3": 1, "F8_E5M2": 1}


def _iter_safetensors(model_dir: str):
    files = sorted(glob.glob(os.path.join(model_dir, "*.safetensors")))
    if not files:
        raise FileNotFoundError(f"no .safetensors under {model_dir}")
    for path in files:
        with safe_open(path, framework="np") as f:
            for name in f.keys():
                yield name, _track(f.get_tensor(name))


def load_llama_params(model_dir: str, cfg: Optional[ModelConfig] = None,
                      dtype=jnp.bfloat16) -> Dict[str, jax.Array]:
    """Load an HF llama/qwen-style checkpoint into the stacked param pytree."""
    if not _HAVE_ST:
        raise RuntimeError("safetensors not available")
    cfg = cfg or ModelConfig.from_model_dir(model_dir)
    L, E = cfg.num_layers, cfg.num_experts
    layer_map = _layer_map_for(cfg)
    fused = _fused_sections(cfg)
    staging: Dict[str, list] = {}
    expert_staging: Dict[str, list] = {}   # key → [L][E] tensors
    singles: Dict[str, np.ndarray] = {}
    for name, tensor in _iter_safetensors(model_dir):
        if name == "model.embed_tokens.weight":
            singles["embed"] = tensor
        elif name == "model.norm.weight":
            singles["final_norm"] = tensor
        elif name == "lm_head.weight":
            singles["lm_head"] = tensor.T
        elif name.startswith("model.layers."):
            rest = name[len("model.layers."):]
            idx_str, sub = rest.split(".", 1)
            if int(idx_str) >= L:
                if int(idx_str) < L + cfg.num_nextn_predict_layers:
                    # deepseek_v3 MTP heads live at model.layers.{L}+ —
                    # generation never runs them (HF skips them too);
                    # their attention-shaped names must not land in the
                    # decoder stacks. The bound keeps the mismatch
                    # guard: only the declared MTP indices skip
                    continue
                raise ValueError(
                    f"checkpoint tensor {name} is beyond the config's "
                    f"{L} layers (+{cfg.num_nextn_predict_layers} MTP) "
                    f"— config.json/checkpoint mismatch")
            expert_prefix = next(
                (p for p in _EXPERT_PREFIXES if sub.startswith(p)), None)
            if expert_prefix is not None:
                # {prefix}{e}.w{1,2,3}.weight (mixtral) or
                # {prefix}{e}.{gate,up,down}_proj.weight (qwen3-moe)
                e_str, wname, _ = sub[len(expert_prefix):].split(".", 2)
                key = _EXPERT_MAP.get(wname)
                if key is None:
                    continue
                grid = expert_staging.setdefault(
                    key, [[None] * E for _ in range(L)])
                grid[int(idx_str)][int(e_str)] = tensor.T
                continue
            if sub in fused:
                # split the fused tensor's torch rows into our keys
                for key, off, cnt in fused[sub]:
                    staging.setdefault(key, [None] * L)[int(idx_str)] = \
                        tensor[off:off + cnt].T
                continue
            mapped = layer_map.get(sub)
            if mapped is None:
                continue  # rotary inv_freq buffers etc.
            key, transpose = mapped
            arr = tensor.T if transpose else tensor
            staging.setdefault(key, [None] * L)[int(idx_str)] = arr

    params: Dict[str, jax.Array] = {}
    partial = _partial_ranges(cfg)
    for key, arr in singles.items():
        params[key] = jnp.asarray(arr, dtype=dtype)
    for key, per_layer in staging.items():
        lo, hi = partial.get(key, (0, L))
        rows = per_layer[lo:hi]
        missing = [lo + i for i, a in enumerate(rows) if a is None]
        extra = [i for i, a in enumerate(per_layer) if a is not None
                 and not (lo <= i < hi)]
        if missing or extra:
            raise ValueError(
                f"checkpoint layer coverage wrong for {key}: missing "
                f"{missing[:4]}, outside-range {extra[:4]} "
                f"(expected layers [{lo}, {hi}))")
        params[f"layers.{key}"] = jnp.asarray(
            _track(np.stack(rows, axis=0)), dtype=dtype)
    for key, grid in expert_staging.items():
        lo, hi = partial.get(key, (0, L))
        rows = grid[lo:hi]
        missing = [(lo + i, j) for i, row in enumerate(rows)
                   for j, a in enumerate(row) if a is None]
        extra = [(i, j) for i, row in enumerate(grid)
                 for j, a in enumerate(row)
                 if a is not None and not (lo <= i < hi)]
        if extra:
            raise ValueError(
                f"checkpoint expert coverage wrong for {key}: tensors "
                f"at layers outside [{lo}, {hi}): {extra[:4]}")
        if missing:
            raise ValueError(f"checkpoint missing experts {missing[:4]}… "
                             f"for {key}")
        params[f"layers.{key}"] = jnp.asarray(
            _track(np.stack([_track(np.stack(row, axis=0))
                             for row in rows], axis=0)),
            dtype=dtype)
    if "lm_head" not in params and not cfg.tie_word_embeddings:
        # some checkpoints tie implicitly by omitting lm_head
        cfg.tie_word_embeddings = True
    return params


def load_params_sharded(model_dir: str, mesh,
                        cfg: Optional[ModelConfig] = None,
                        dtype=jnp.bfloat16) -> Dict[str, jax.Array]:
    """Load a checkpoint DIRECTLY into its mesh-sharded device layout.

    The replicated loader (load_llama_params) stages the whole model in
    host numpy — ~140 GB of host RAM for a 70B bf16 checkpoint, and each
    device then holds a full copy until shard_params re-places it. This
    loader reads only each device's shard from disk (safetensors
    `get_slice` reads sub-ranges without materializing the tensor) and
    assembles sharded jax Arrays with `make_array_from_callback`, so peak
    host memory is ONE param-stack shard — the practical enabler for
    70B TP-8 and deepseek-class bring-up on a standard TPU-VM host
    (BASELINE config 4; the reference gets this from its external
    engines' per-rank shard loaders, vllm subprocess.rs:37-41).

    Covers every family the engine serves: stacked dense layers
    (llama/qwen/gemma, phi3 fused tensors), MoE expert grids (mixtral /
    qwen-moe / deepseek hybrid with partial layer ranges), and MLA
    latent projections. ``load_accounting()`` wraps a load to measure
    the staging high-water mark.
    """
    if not _HAVE_ST:
        raise RuntimeError("safetensors not available")
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..parallel.sharding import fit_or_replicate, param_pspecs
    cfg = cfg or ModelConfig.from_model_dir(model_dir)
    L = cfg.num_layers

    # index pass: tensor name → OPEN file handle (headers parsed once —
    # a 70B TP-8 load issues thousands of slice reads)
    files = sorted(glob.glob(os.path.join(model_dir, "*.safetensors")))
    if not files:
        raise FileNotFoundError(f"no .safetensors under {model_dir}")
    with contextlib.ExitStack() as stack:
        handles = {path: stack.enter_context(
            safe_open(path, framework="np")) for path in files}
        where: Dict[str, object] = {}
        for f in handles.values():
            for name in f.keys():
                where[name] = f

        # "wq" → [(hf_suffix, T?), ...]: some keys have per-family HF
        # namings (router: mixtral block_sparse_moe.gate vs qwen3-moe /
        # deepseek mlp.gate) — resolve by whichever name the checkpoint
        # contains at the key's FIRST covered layer (partial-range keys
        # like the deepseek router never exist at layer 0)
        by_key: Dict[str, list] = {}
        for suffix, (key, transpose) in _layer_map_for(cfg).items():
            by_key.setdefault(key, []).append((suffix, transpose, None))
        for suffix, sections in _fused_sections(cfg).items():
            # fused tensors (phi3 qkv_proj / gate_up_proj): each split
            # key reads a torch-row window of the fused tensor — the
            # slice reader shifts AND CLAMPS the logical out-axis into
            # the section (col_off=None means unfused; 0 is a real fused
            # offset whose open slices must still clamp to the section)
            for key, off, _cnt in sections:
                by_key.setdefault(key, []).append((suffix, True, off))
        singles = {"embed": ("model.embed_tokens.weight", False),
                   "final_norm": ("model.norm.weight", False),
                   "lm_head": ("lm_head.weight", True)}
        partial = _partial_ranges(cfg)

        def read_slice(name: str, idx, transpose: bool,
                       col_off=None, col_dim: int = 0) -> np.ndarray:
            """Read tensor[idx] from disk; idx indexes the LOGICAL
            (already transposed) orientation, so transposed reads swap
            the slices. ``col_off`` (None = unfused) shifts the logical
            out-axis into a fused tensor's section and CLAMPS open
            slices to the section width ``col_dim`` — an offset of 0 is
            a real fused section whose slice(None) would otherwise read
            the whole fused axis."""
            sl = where[name].get_slice(name)
            if transpose:
                if len(idx) == 2:
                    c = idx[1]
                    if col_off is not None:
                        start, stop, step = c.indices(col_dim)
                        c = slice(start + col_off, stop + col_off, step)
                    out = np.ascontiguousarray(sl[c, idx[0]].T)
                    # the fresh slice copy and its contiguous transpose
                    # copy coexist inside this call (measured: slice
                    # reads are heap copies, not mmap views)
                    _note_transient(2 * out.nbytes)
                    return out
                out = np.ascontiguousarray(sl[idx[0]].T)
                _note_transient(2 * out.nbytes)
                return out
            out = np.ascontiguousarray(sl[tuple(idx)])
            _note_transient(out.nbytes)
            return out

        def _resolve_expert_naming(lo: int):
            """(prefix, {stacked key → hf wname}) by checkpoint presence:
            mixtral block_sparse_moe.experts.{e}.w{1,3,2} vs qwen-moe /
            deepseek mlp.experts.{e}.{gate,up,down}_proj."""
            for prefix in _EXPERT_PREFIXES:
                for wname, key in _EXPERT_MAP.items():
                    if (f"model.layers.{lo}.{prefix}0.{wname}.weight"
                            in where):
                        inv = {k: w for w, k in _EXPERT_MAP.items()
                               if (f"model.layers.{lo}.{prefix}0."
                                   f"{w}.weight") in where}
                        return prefix, inv
            raise ValueError(
                f"no expert tensors found at layer {lo} under any of "
                f"{_EXPERT_PREFIXES} — checkpoint/config mismatch")

        if "pp" in mesh.axis_names and mesh.shape["pp"] > 1:
            # pipeline-parallel mesh: layer stacks stream straight into
            # their L-over-"pp" (×in-stage "tp") placement — each rank
            # reads only ITS layer slice off disk, the per-host working
            # set the cross-host capacity axis exists for
            from ..parallel.pipeline_parallel import pp_param_pspecs
            specs = pp_param_pspecs(cfg, tp=mesh.shape["tp"])
        else:
            specs = param_pspecs(cfg)
        params: Dict[str, jax.Array] = {}
        if cfg.kv_lora_rank > 0:
            from .models.mla import param_shapes
        else:
            from .models.llama import param_shapes
        expert_naming = None
        for pkey, shape in param_shapes(cfg).items():
            spec = fit_or_replicate(pkey, shape, specs.get(pkey, P()),
                                    mesh, _np_dtype(dtype).itemsize)
            sharding = NamedSharding(mesh, spec)
            if pkey in singles:
                name, transpose = singles[pkey]
                if name not in where:
                    continue        # tied checkpoints omit lm_head

                def cb(idx, name=name, transpose=transpose, shape=shape):
                    # preallocate the handoff buffer and fill it in
                    # row-CHUNKS read straight off disk, so the staging
                    # transient is one chunk in the DISK dtype — not the
                    # whole (possibly f32) shard (a 70B embed shard read
                    # whole would stage GBs)
                    dims = [len(range(*sl.indices(dim)))
                            for sl, dim in zip(idx, shape)]
                    out = _note_handoff(
                        np.empty(dims, _np_dtype(dtype)))
                    r_sl = idx[0]
                    start, stop, step = r_sl.indices(shape[0])
                    disk_item = _ST_ITEMSIZE.get(
                        where[name].get_slice(name).get_dtype(), 4)
                    row_bytes = max(
                        np.prod(dims[1:], dtype=np.int64), 1) * disk_item
                    chunk = max(int((64 << 20) // row_bytes), 1)
                    for c0 in range(start, stop, chunk * step):
                        c1 = min(c0 + chunk * step, stop)
                        out[(c0 - start) // step:
                            (c1 - start) // step] = read_slice(
                            name, (slice(c0, c1, step),) + tuple(idx[1:]),
                            transpose)
                    return out

                params[pkey] = jax.make_array_from_callback(
                    shape, sharding, cb)
                continue
            key = pkey[7:] if pkey.startswith("layers.") else pkey
            lo, hi = partial.get(key, (0, L))
            Lr = hi - lo
            if key in ("moe_gate", "moe_up", "moe_down"):
                # expert grid [Lr, E, in, out]: one disk tensor per
                # (layer, expert) — each device reads ONLY its ep × tp
                # sub-grid
                if expert_naming is None:
                    expert_naming = _resolve_expert_naming(lo)
                prefix, inv = expert_naming
                if key not in inv:
                    raise ValueError(
                        f"expert projection for {pkey} not found at layer "
                        f"{lo} under model.layers.{lo}.{prefix}0.* — "
                        f"present: {sorted(inv.values())}; the checkpoint "
                        f"is missing or misnames this projection")
                wname = inv[key]
                E = shape[1]
                names = [[(f"model.layers.{lo + i}.{prefix}{e}."
                           f"{wname}.weight") for e in range(E)]
                         for i in range(Lr)]
                missing = [n for row in names for n in row
                           if n not in where]
                if missing:
                    raise ValueError(
                        f"checkpoint missing expert tensors for {pkey}: "
                        f"{missing[:3]}…")

                def cb(idx, names=names, E=E, Lr=Lr, shape=shape):
                    # preallocate the handoff buffer, fill one
                    # (layer, expert) piece at a time: the staging
                    # transient is ONE disk-dtype piece (assignment
                    # casts in place), never a stacked copy
                    l_sl, e_sl = idx[0], idx[1]
                    rest = tuple(idx[2:])
                    ls = list(range(*l_sl.indices(Lr)))
                    es = list(range(*e_sl.indices(E)))
                    dims = [len(range(*sl.indices(dim)))
                            for sl, dim in zip(rest, shape[2:])]
                    out = _note_handoff(np.empty(
                        [len(ls), len(es)] + dims, _np_dtype(dtype)))
                    for j, i in enumerate(ls):
                        for m, e in enumerate(es):
                            out[j, m] = read_slice(names[i][e], rest, True)
                    return out

                params[pkey] = jax.make_array_from_callback(
                    shape, sharding, cb)
                continue
            if key in by_key:
                cands = by_key[key]
                suffix, transpose, col_off = next(
                    (c for c in cands
                     if f"model.layers.{lo}.{c[0]}" in where), cands[0])
                names = [f"model.layers.{lo + i}.{suffix}"
                         for i in range(Lr)]
                if any(n not in where for n in names):
                    missing = [lo + i for i, n in enumerate(names)
                               if n not in where]
                    raise ValueError(
                        f"checkpoint missing layers {missing[:4]}… "
                        f"for {pkey}")
                col_dim = shape[-1]

                def cb(idx, names=names, transpose=transpose,
                       col_off=col_off, col_dim=col_dim, Lr=Lr,
                       shape=shape):
                    # prealloc-and-fill (see expert path): transient =
                    # one layer's disk-dtype slice
                    l_sl = idx[0]
                    rest = tuple(idx[1:])
                    ls = list(range(*l_sl.indices(Lr)))
                    dims = [len(range(*sl.indices(dim)))
                            for sl, dim in zip(rest, shape[1:])]
                    out = _note_handoff(np.empty(
                        [len(ls)] + dims, _np_dtype(dtype)))
                    for j, i in enumerate(ls):
                        out[j] = read_slice(
                            names[i], rest, transpose, col_off, col_dim)
                    return out

                params[pkey] = jax.make_array_from_callback(
                    shape, sharding, cb)
                continue
            raise NotImplementedError(
                f"sharded loading not implemented for {pkey}")

    if "lm_head" not in params and not cfg.tie_word_embeddings:
        cfg.tie_word_embeddings = True
    return params


# Backwards-compatible name (pre-round-5 the streaming loader was
# llama-family-only; it now covers MoE and MLA too).
load_llama_params_sharded = load_params_sharded


def _np_dtype(dtype):
    name = jnp.dtype(dtype).name
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes
        return np.dtype(getattr(ml_dtypes, name))


def save_hf_style(params: Dict[str, jax.Array], cfg: ModelConfig,
                  out_dir: str) -> None:
    """Write params back out as a single HF-style safetensors file (used by
    tests to cross-check against the torch reference implementation)."""
    from safetensors.numpy import save_file
    if (cfg.model_type in ("deepseek_v2", "deepseek_v3")
            and cfg.num_experts > 0):
        raise NotImplementedError(
            "save_hf_style cannot write the deepseek hybrid MoE layout "
            "(partial layer stacks + deepseek expert naming); the MLA "
            "tests carry their own converter")
    os.makedirs(out_dir, exist_ok=True)

    def c(a) -> np.ndarray:
        # save_file serializes the raw buffer — it MUST be C-contiguous
        # (np.asarray of a jax array can surface a column-major buffer).
        return np.ascontiguousarray(np.asarray(a, np.float32))

    out: Dict[str, np.ndarray] = {
        "model.embed_tokens.weight": c(params["embed"]),
        "model.norm.weight": c(params["final_norm"]),
    }
    if "lm_head" in params:
        out["lm_head.weight"] = c(np.asarray(params["lm_head"], np.float32).T)
    inv = {v[0]: (k, v[1]) for k, v in _LAYER_MAP.items()}
    # _LAYER_MAP maps BOTH shared-expert namings (qwen2 singular,
    # deepseek plural) onto sh_*; the dict inversion keeps whichever
    # iterated last — pin the family's own naming explicitly
    if cfg.model_type == "qwen2_moe":
        inv["sh_gate"] = ("mlp.shared_expert.gate_proj.weight", True)
        inv["sh_up"] = ("mlp.shared_expert.up_proj.weight", True)
        inv["sh_down"] = ("mlp.shared_expert.down_proj.weight", True)
    if cfg.post_norms:   # gemma2 norm naming (see load_llama_params)
        inv["ln1_post"] = ("post_attention_layernorm.weight", False)
        inv["ln2"] = ("pre_feedforward_layernorm.weight", False)
        inv["ln2_post"] = ("post_feedforward_layernorm.weight", False)
    # two HF namings map to "router"/each expert matmul (mixtral vs
    # qwen3-moe); saving must pick the family's names explicitly
    if cfg.model_type in ("qwen3_moe", "qwen2_moe"):
        inv["router"] = ("mlp.gate.weight", True)
        inv_experts = {"moe_gate": "gate_proj", "moe_up": "up_proj",
                       "moe_down": "down_proj"}
        expert_prefix = "mlp.experts."
    else:
        inv["router"] = ("block_sparse_moe.gate.weight", True)
        inv_experts = {"moe_gate": "w1", "moe_up": "w3",
                       "moe_down": "w2"}
        expert_prefix = "block_sparse_moe.experts."
    fused = _fused_sections(cfg)
    for suffix, sections in fused.items():
        # phi3 fused tensors: concatenate our split keys back into the
        # HF torch-row layout (inverse of the loaders' split)
        for key, _off, _cnt in sections:
            inv.pop(key, None)
        L = cfg.num_layers
        for i in range(L):
            rows = [np.asarray(params[f"layers.{k}"][i], np.float32).T
                    for k, _o, _c in sections]
            out[f"model.layers.{i}.{suffix}"] = np.ascontiguousarray(
                np.concatenate(rows, axis=0))
    for key, (hf_sub, transpose) in inv.items():
        if f"layers.{key}" not in params:
            continue
        stacked = np.ascontiguousarray(
            np.asarray(params[f"layers.{key}"], np.float32))
        for i in range(stacked.shape[0]):
            arr = stacked[i].T if transpose else stacked[i]
            out[f"model.layers.{i}.{hf_sub}"] = np.ascontiguousarray(arr)
    for key, wname in inv_experts.items():
        if f"layers.{key}" not in params:
            continue
        stacked = np.asarray(params[f"layers.{key}"], np.float32)  # [L,E,..]
        for i in range(stacked.shape[0]):
            for e in range(stacked.shape[1]):
                out[(f"model.layers.{i}.{expert_prefix}"
                     f"{e}.{wname}.weight")] = np.ascontiguousarray(
                         stacked[i, e].T)
    save_file(out, os.path.join(out_dir, "model.safetensors"))
