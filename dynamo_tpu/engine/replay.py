"""Deterministic schedule recording + replay for the engine core.

Debugging aid for async-interleaving bugs (KNOWN_ISSUES: the pipelined
dispatch + preemption exactness race). The reference debugs its engine-side
races with deterministic in-process mock transports
(lib/runtime/tests/common/mock.rs); our engine's nondeterminism lives in
the asyncio-loop interleaving of admissions/harvests against in-flight XLA
dispatches, so the analogous tool is: record the complete scheduler
decision log of a live run (every dispatched program's HOST inputs, in
device order), then

- `replay()` re-executes the identical dispatch sequence synchronously
  (block_until_ready between programs). If the replay reproduces the live
  run's (corrupt) tokens, the bug is deterministic given the schedule and
  lives in the recorded inputs or step semantics; if the replay diverges
  from the live run, the corruption needed real async overlap — a buffer
  lifetime / donation hazard.
- `check_log()` simulates pool-slot ownership over the log and flags any
  dispatch that READS a KV pool slot last written by a different request —
  the stale-read signature — plus input-consistency invariants
  (chained positions/tokens, table/ownership mismatches), with no model
  evaluation at all.

Recording copies only small host arrays; it does not synchronize the
device, so it can run inside the adversarial sweeps without perturbing
the interleaving materially.

Pipeline-parallel runs record and replay through the SAME event set:
the pp core's _prefill_jit/_decode_k_jit keep the single-device host
contracts (engine/core._compile_jits_pp), so exec_prefill_event /
exec_dispatch_event marshal a recorded pp schedule into the
token-interleaved stage programs untouched — replay() against a
same-config pp core is bit-exact (tests/test_pipeline_parallel.py), and
the live multihost follower consumes the identical stream.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

import numpy as np


# Leader-side host bookkeeping: events the recorder emits for stream
# accounting, divergence diffing (compare_replay), and preemption-policy
# forensics — they carry NO device-state transition, so neither the
# offline replayer nor a multihost follower executes them. Every event
# the recorder emits must be EITHER replayed below OR listed here
# (dynalint DL009 enforces the classification is total and disjoint
# from multihost.WIRE_EVENTS).
HOST_EVENTS = frozenset(
    {"admit", "first_token", "harvest", "ragged_harvest", "spec_harvest",
     "preempt", "release"})


class Recorder:
    """Collects scheduler events in device-dispatch order."""

    def __init__(self) -> None:
        self.events: List[dict] = []
        self.dispatch_seq = 0

    def rec(self, ev: str, **kw) -> None:
        kw["ev"] = ev
        self.events.append(kw)

    def next_dispatch_id(self) -> int:
        self.dispatch_seq += 1
        return self.dispatch_seq


# --------------------------------------------------------------------------
# Synchronous replay of the recorded dispatch sequence
# --------------------------------------------------------------------------


def _exec_prefill(core, kv, ev: dict, sp: bool):
    """The ONE home of recorded-event → prefill-jit marshalling (used by
    both the offline replayer and the live multihost follower). The sp
    variant issues _prefill_sp_jit and has no start_pos (the sp path
    never has a prefix hit); everything else is identical by
    construction. Returns (tok_device, kv)."""
    import jax.numpy as jnp

    from .sampling import make_slot_keys

    key = make_slot_keys(core.cfg.seed, jnp.asarray([ev["samp_seed"]]),
                         jnp.asarray(ev["key_step"]))[0]
    head = (jnp.asarray(ev["padded"]), jnp.asarray(ev["table"]))
    pos = (() if sp
           else (jnp.asarray(ev["start_pos"], jnp.int32),))
    tail = (jnp.asarray(ev["true_len"], jnp.int32), key,
            jnp.asarray(ev["temp"], jnp.float32),
            jnp.asarray(ev["top_k"], jnp.int32),
            jnp.asarray(ev["top_p"], jnp.float32))
    fn = core._prefill_sp_jit if sp else core._prefill_jit
    tok, _lp, kv = fn(core.params, kv, *head, *pos, *tail)
    return tok, kv


def exec_prefill_event(core, kv, ev: dict):
    return _exec_prefill(core, kv, ev, sp=False)


def exec_sp_prefill_event(core, kv, ev: dict):
    return _exec_prefill(core, kv, ev, sp=True)


def exec_kv_store_event(kv, ev: dict, pool, block_size: int,
                        spill_stage: Optional[dict] = None) -> None:
    """Mirror one of the leader's offload commits: gather the SAME device
    blocks from ``kv`` (bit-identical by the replay/stream induction) and
    apply the literal hash→slot placements to ``pool``. Single home of
    the kv_store event, shared by the offline replayer and the live
    multihost follower (engine/multihost.py).

    ``spill_stage``: when the leader runs a disk (G3) tier, the event's
    ``spills`` list names the evicted hashes its spill queue accepted —
    stage a copy of each such row (read from the mirror arena BEFORE the
    eviction overwrites it) keyed by hash, so the later "kv_disk_store"
    commit can apply the leader's literal disk placements from
    bit-identical bytes (exec_kv_disk_store_event)."""
    from .block_copy import gather_blocks_to_host

    spills = set(ev.get("spills") or ())
    ids = [int(it[3]) for it in ev["items"]]
    values = gather_blocks_to_host(kv, ids, block_size, pool.num_kv_heads)
    for i, (h, hslot, evicted, _bid) in enumerate(ev["items"]):
        if (spill_stage is not None and evicted is not None
                and evicted in spills):
            vslot = pool._by_hash.get(evicted)
            if vslot is not None and pool._arena is not None:
                spill_stage[evicted] = pool.row_copy(vslot)
        pool.apply_store(h, hslot, evicted,
                         {key: arr[:, :, i]
                          for key, arr in values.items()})


def exec_kv_disk_store_event(ev: dict, disk_store, pool,
                             spill_stage: dict) -> None:
    """Apply one of the leader's disk-tier spill commits to a mirror
    store: literal placements (hash + the leader's eviction set), bytes
    from the staged row copy (eviction-driven spills) or straight from
    the host mirror arena (flush-driven spills — the row is still
    resident there). Never re-runs the LRU policy. Shared by the offline
    replayer and the live multihost follower."""
    for h, th, ph, evicted in ev["items"]:
        values = spill_stage.pop(h, None)
        if values is None:
            slot = pool._by_hash.get(h) if pool is not None else None
            if slot is None:
                raise ValueError(
                    f"kv_disk_store for hash {h:#x} has no staged row "
                    f"copy and no host-mirror residence — the leader's "
                    f"kv_store spills list and this mirror diverged")
            values = pool.row_copy(slot)
        disk_store.apply_put(h, list(evicted), values,
                             tokens_hash=th, parent_hash=ph)


def exec_kv_remote_restore_event(kv, ev: dict, block_size: int,
                                 remote_store=None):
    """Re-execute a remote (G4) tier restore: scatter the leader's
    FETCHED bytes into the same device targets with the same program
    the leader's admission ran. Single home of the kv_remote_restore
    event (offline replayer + live multihost follower).

    Fetch-or-bytes: the event normally carries ``values`` (the stacked
    wire dict the leader fetched — the fleet-shared tier cannot be
    re-walked per rank); when absent, the hashes are fetched from
    ``remote_store`` instead — correct whenever the store shares the
    leader's content-addressed object root, where equal hash ⇒ equal
    bytes by construction. Returns the new kv."""
    from .block_copy import prep_host_values, scatter_prepped

    vals = ev.get("values")
    if vals is None:
        if remote_store is None:
            raise ValueError(
                "kv_remote_restore carries no values and no remote "
                "store was provided — replay with the recorded engine "
                "config (kv_remote_dir) or a bytes-mode recording")
        vals = remote_store.fetch(list(ev["remote_hashes"]))
    ids, pv = prep_host_values(list(ev["remote_targets"]), vals)
    return scatter_prepped(kv, ids, pv, block_size)


def exec_host_restore_event(kv, ev: dict, pool, block_size: int,
                            disk_store=None):
    """Re-execute a host/disk-tier h2d restore from the mirror tiers:
    same slots/hashes, same device targets, same scatter program as the
    leader's admission. Single home of the hit_transfer restore path
    (see exec_kv_store_event). Returns the new kv."""
    from .block_copy import prep_host_values, scatter_prepped

    parts = []
    targets: list = []
    if ev.get("host_slots"):
        parts.append(pool.fetch(list(ev["host_slots"])))
        targets += list(ev["host_targets"])
    if ev.get("disk_hashes"):
        if disk_store is None:
            raise ValueError(
                "hit_transfer references disk-tier hashes but no mirror "
                "disk store was provided — replay with the recorded "
                "engine config (kv_disk_dir/kv_disk_blocks)")
        parts.append(disk_store.fetch(list(ev["disk_hashes"])))
        targets += list(ev["disk_targets"])
    vals = (parts[0] if len(parts) == 1 else
            {k: np.concatenate([p[k] for p in parts], axis=2)
             for k in parts[0]})
    ids, vals = prep_host_values(targets, vals)
    return scatter_prepped(kv, ids, vals, block_size)


def exec_dispatch_event(core, kv, ev: dict, chain):
    """Issue the recorded K-step decode dispatch against `kv`. ``chain`` is
    the chained-from dispatch's [K, B] device tokens (None when host-fed).
    Single home of the event → _decode_k_jit marshalling, like
    exec_prefill_event. Returns (toks_k, kv)."""
    import jax.numpy as jnp

    host_tokens = jnp.array(np.asarray(ev["tokens"]))
    if ev["chained_from"] is not None:
        tokens_in = core._merge_jit(
            chain[-1], host_tokens, jnp.array(np.asarray(ev["mask"])))
    else:
        tokens_in = host_tokens
    K = int(ev["K"])
    B = np.asarray(ev["tokens"]).shape[0]
    planned = np.asarray(ev.get("planned", np.zeros((K, B), np.int32)))
    pmask = np.asarray(ev.get("planned_mask", np.zeros((K, B), bool)))
    toks_k, _lps, kv = core._decode_k_jit(
        core.params, kv, tokens_in,
        jnp.array(ev["positions"]), jnp.array(ev["tables"]),
        jnp.array(ev["seeds"]), jnp.array(ev["steps"]),
        jnp.array(ev["temperature"]), jnp.array(ev["top_k"]),
        jnp.array(ev["top_p"]),
        jnp.array(planned), jnp.array(pmask))
    return toks_k, kv


def exec_verify_event(core, kv, ev: dict):
    """Issue the recorded speculative verify dispatch (engine/spec/)
    against ``kv``. Single home of the event → _verify_jit marshalling
    (offline replayer + live multihost follower). Returns
    (toks [B, Tv], kv)."""
    import jax.numpy as jnp

    if core._verify_jit is None or \
            core.cfg.spec_k + 1 != np.asarray(ev["tokens"]).shape[1]:
        raise NotImplementedError(
            f"recorded verify dispatch has {np.asarray(ev['tokens']).shape[1]}"
            f" rows/slot but this core compiled spec_k={core.cfg.spec_k} — "
            f"replay with the recorded engine config")
    toks, _lps, kv = core._verify_jit(
        core.params, kv, jnp.array(np.asarray(ev["tokens"])),
        jnp.array(ev["positions"]), jnp.array(ev["tables"]),
        jnp.array(ev["seeds"]), jnp.array(ev["steps"]),
        jnp.array(ev["temperature"]), jnp.array(ev["top_k"]),
        jnp.array(ev["top_p"]))
    return toks, kv


def exec_ragged_event(core, kv, ev: dict, chain=None):
    """Issue the recorded unified ragged dispatch (engine/ragged.py)
    against ``kv``. Single home of the event → _ragged_jit marshalling
    (offline replayer + live multihost follower). ``chain`` is the
    chained-from dispatch's device tokens for a pipelined ragged event
    (None when host-fed). Returns (toks [S or capacity], kv)."""
    import jax.numpy as jnp

    if core._ragged_jit is None:
        raise NotImplementedError(
            "recorded ragged dispatch but this core compiled without "
            "ragged_dispatch — replay with the recorded engine config")
    if core.cfg.ragged_max_tokens != np.asarray(ev["tokens"]).shape[0]:
        raise NotImplementedError(
            f"recorded ragged dispatch has "
            f"{np.asarray(ev['tokens']).shape[0]} token rows but this "
            f"core compiled ragged_max_tokens="
            f"{core.cfg.ragged_max_tokens} — replay with the recorded "
            f"engine config")
    # the steps array's shape IS the sampling-variant marker: [B+1]
    # slot steps (spec_k == 0) vs [capacity] row steps (the spec-
    # enabled row-sampled program) — a mismatch means the replaying
    # core compiled the other variant
    row_sampled = (np.asarray(ev["steps"]).shape[0]
                   == np.asarray(ev["tokens"]).shape[0])
    if row_sampled != core._ragged_row_sampled:
        raise NotImplementedError(
            f"recorded ragged dispatch was "
            f"{'row' if row_sampled else 'slot'}-sampled but this core "
            f"compiled spec_k={core.cfg.spec_k} — replay with the "
            f"recorded engine config")
    host_tokens = jnp.array(np.asarray(ev["tokens"]))
    if ev.get("chained_from") is not None:
        tokens_in = core._ragged_merge_jit(
            chain, jnp.array(np.asarray(ev["srows"])), host_tokens,
            jnp.array(np.asarray(ev["mask"])))
    else:
        tokens_in = host_tokens
    toks, _lps, kv = core._ragged_jit(
        core.params, kv, tokens_in,
        jnp.array(np.asarray(ev["positions"])),
        jnp.array(np.asarray(ev["tables"])),
        jnp.array(np.asarray(ev["row_slot"])),
        jnp.array(np.asarray(ev["starts"])),
        jnp.array(np.asarray(ev["counts"])),
        jnp.array(np.asarray(ev["sample_rows"])),
        jnp.array(np.asarray(ev["seeds"])),
        jnp.array(np.asarray(ev["steps"])),
        jnp.array(np.asarray(ev["temperature"])),
        jnp.array(np.asarray(ev["top_k"])),
        jnp.array(np.asarray(ev["top_p"])))
    return toks, kv


class _MemDiskMirror:
    """In-memory stand-in for DiskKvStore during offline replay (the
    replayer applies the leader's literal disk placements; durability is
    the live store's concern, not the replay's): apply_put / fetch /
    contains with the same signatures."""

    def __init__(self) -> None:
        self._blocks: Dict[int, dict] = {}

    def apply_put(self, h, evicted, values, tokens_hash=None,
                  parent_hash=None) -> None:
        for e in evicted:
            self._blocks.pop(e, None)
        self._blocks[h] = values

    def contains(self, h) -> bool:
        return h in self._blocks

    def fetch(self, hashes) -> dict:
        blocks = [self._blocks[h] for h in hashes]
        return {k: np.ascontiguousarray(
                    np.stack([b[k] for b in blocks], axis=2))
                for k in blocks[0]}


def replay(core, events: List[dict], fingerprint: bool = False) -> dict:
    """Re-execute the recorded schedule against a fresh KV cache, strictly
    synchronously. `core` supplies params and compiled jits (its own KV is
    untouched). Returns {"prefill": {seq: tok}, "dispatch": {id: [K,B]},
    "verify": {id: [B,Tv]}, "fingerprints": [(label, digest), ...]}.
    """
    import jax

    from .models import llama

    dtype = jax.tree_util.tree_leaves(core.params)[0].dtype
    # the pool LAYOUT must match the recording core's (an int8-KV engine
    # replayed against a bf16 pool would retrace the unquantized branch
    # and report phantom divergence)
    kv = llama.init_kv_cache(core.model_cfg, core.cfg.num_kv_blocks,
                             core.cfg.kv_block_size, dtype=dtype,
                             quantization=core.cfg.kv_quantization)
    out = {"prefill": {}, "dispatch": {}, "verify": {}, "ragged": {},
           "fingerprints": []}
    disp_toks: Dict[int, object] = {}
    disk_mirror = None     # disk (G3) mirror, built from kv_disk_store
    spill_stage: Dict[int, dict] = {}   # hash → staged evicted-row copy
    mirror = None          # host-tier mirror pool, built from kv_store
    # events exactly like a multihost follower's (engine/multihost.py):
    # gather the SAME blocks from the replay KV, apply literal placements
    mirrored_slots: set = set()   # host slots with an IN-LOG store
    # pool slots written by in-log prefills/dispatches: a prefix hit whose
    # blocks were registered BEFORE recording began has no in-log writer —
    # the fresh replay KV holds zeros there and every downstream compare
    # would report phantom mismatches (advisor round-1 finding)
    bs = core.cfg.kv_block_size
    written: set = set()

    def fp(label):
        if not fingerprint:
            return
        import hashlib
        h = hashlib.blake2b(digest_size=16)
        # key-agnostic (llama {"k","v"}, MLA {"kv"}); sorted so the
        # fingerprint is stable across dict orders
        for key in sorted(kv):
            h.update(np.asarray(kv[key]).tobytes())
        out["fingerprints"].append((label, h.hexdigest()))

    for ev in events:
        kind = ev["ev"]
        if kind in HOST_EVENTS:
            # leader-side bookkeeping (see HOST_EVENTS): the replay
            # re-derives device state only; compare_replay reads the
            # harvest family out of the SAME event list for the diff
            continue
        if kind == "prefill_unsupported":
            raise NotImplementedError(
                f"run used an unrecorded admission path "
                f"({ev.get('path')}, rid={ev.get('rid')}); replay would "
                f"silently diverge — record only runs without disagg "
                f"onboarding")
        if kind == "precomputed_device_admit":
            # live multihost followers resolve this from their own
            # process bridge (their prefill replica's parked shard); an
            # OFFLINE replay has no bridge and the arrays were never
            # logged (device-resident by design)
            raise NotImplementedError(
                f"device-plane disagg admission for rid={ev.get('rid')} "
                f"is not offline-replayable: the payload's arrays are "
                f"device-resident and not in the log — record with the "
                f"wire plane (precomputed_admit) for replayable disagg "
                f"runs")
        if kind == "handoff_gather":
            # read-only device program (prefill epilogue gather); its
            # output feeds the handoff plane, not the KV pool — offline
            # replay of pool state may skip it
            continue
        if kind == "kv_store":
            from ..llm.kv.offload import make_host_pool
            if mirror is None:
                if core.cfg.host_kv_blocks <= 0:
                    raise NotImplementedError(
                        "the record offloaded to a host tier but the "
                        "replaying core has host_kv_blocks=0 — replay "
                        "with the recorded engine config")
                mirror = make_host_pool(
                    core.cfg.host_kv_blocks, core.model_cfg, bs,
                    core.cfg.kv_quantization,
                    int(next(iter(core.kv.values())).shape[-1]), dtype)
            top = max(it[1] for it in ev["items"])
            if top >= core.cfg.host_kv_blocks:
                raise NotImplementedError(
                    f"recorded host-pool slot {top} exceeds this core's "
                    f"host_kv_blocks={core.cfg.host_kv_blocks} — replay "
                    f"with the recorded engine config")
            for b in (int(it[3]) for it in ev["items"]):
                for o in range(bs):
                    if b * bs + o not in written:
                        raise NotImplementedError(
                            f"kv_store gathers block {b} with no in-log "
                            f"writer — its content predates the "
                            f"recording; start recording before any "
                            f"blocks are stored")
            exec_kv_store_event(kv, ev, mirror, bs,
                                spill_stage=spill_stage)
            mirrored_slots.update(int(it[1]) for it in ev["items"])
        if kind == "kv_disk_store":
            # the leader's spill-pump commit: apply its literal disk
            # placements from the rows staged at the kv_store eviction
            # (or still host-mirror-resident, for flush-driven spills)
            if disk_mirror is None:
                disk_mirror = _MemDiskMirror()
            exec_kv_disk_store_event(ev, disk_mirror, mirror, spill_stage)
        if kind == "kv_remote_restore":
            # remote (G4) tier restore: scatter the leader's fetched
            # bytes (carried on the event — the fleet-shared tier is not
            # per-rank replayable) into the same targets; ordered BEFORE
            # the admission's hit_transfer, so the restored blocks gain
            # their in-log writer before the hit walk below reads them
            kv = exec_kv_remote_restore_event(kv, ev, bs,
                                              remote_store=core.remote_store)
            written.update(int(b) * bs + o
                           for b in ev["remote_targets"]
                           for o in range(bs))
            fp(("kv_remote_restore", ev.get("rid")))
        if kind == "hit_transfer" and int(ev.get("hit", 0)) > 0:
            if int(ev.get("disk_hit", 0)) > 0:
                if disk_mirror is None:
                    raise NotImplementedError(
                        f"disk-restored hit for rid={ev.get('rid')} "
                        f"references disk blocks with no in-log "
                        f"kv_disk_store — those spills happened before "
                        f"recording began")
                # handles the combined case too (host_slots may be
                # non-empty alongside the disk hashes)
                kv = exec_host_restore_event(kv, ev, mirror, bs,
                                             disk_store=disk_mirror)
                written.update(int(b) * bs + o
                               for b in (list(ev.get("host_targets") or [])
                                         + list(ev["disk_targets"]))
                               for o in range(bs))
                fp(("disk_restore", ev.get("rid")))
            elif int(ev.get("host_hit", 0)) > 0:
                # host-tier hit: replay the h2d restore from the mirror
                # (exactly the follower's path); the restored target
                # blocks gain an in-log writer for the check below
                if ev.get("host_slots") is None or \
                        ev.get("host_targets") is None:
                    raise NotImplementedError(
                        f"host-restored hit for rid={ev.get('rid')} has "
                        f"no host_slots/host_targets — this log was "
                        f"recorded by a pre-r3 engine; host restores "
                        f"are not replayable for that log version")
                missing_slots = [s for s in ev["host_slots"]
                                 if s not in mirrored_slots]
                if mirror is None or missing_slots:
                    raise NotImplementedError(
                        f"host-restored hit for rid={ev.get('rid')} "
                        f"references host slots {missing_slots[:4]} with "
                        f"no in-log kv_store — those offloads happened "
                        f"before recording began; the mirror would "
                        f"scatter zeros and report phantom divergence")
                kv = exec_host_restore_event(kv, ev, mirror, bs)
                written.update(int(b) * bs + o
                               for b in ev["host_targets"]
                               for o in range(bs))
                fp(("host_restore", ev.get("rid")))
            table = list(ev["blocks"])
            for p in range(int(ev["hit"])):
                ps = table[p // bs] * bs + p % bs
                if ps not in written:
                    raise NotImplementedError(
                        f"prefix hit for rid={ev.get('rid')} reads pool "
                        f"slot {ps} (kv position {p}) with no in-log "
                        f"writer — its blocks were registered before "
                        f"recording began, so the fresh replay KV is zeros "
                        f"there and compare_replay would report phantom "
                        f"mismatches; start recording before any prefix "
                        f"blocks are stored")
        if kind == "precomputed_admit":
            # wire-plane disagg admission: the record carries the remote
            # prefill's KV values, so the replay applies the identical
            # scatter and those slots gain an in-log writer
            from .block_copy import scatter_blocks_from_host
            kv = scatter_blocks_from_host(kv, list(ev["targets"]),
                                          ev["values"], bs)
            written.update(int(b) * bs + o for b in ev["targets"]
                           for o in range(bs))
            fp(("precomputed_admit", ev.get("rid")))
        if kind == "kv_layer_stream":
            # streaming layer-wise disagg admission (llm/kv/stream.py):
            # one event per arrived layer, carrying the already-sliced
            # suffix values — replay applies the identical single-layer
            # scatter. Target blocks gain their in-log writer at the
            # LAST layer, when the live engine marked the slot ready.
            from .block_copy import scatter_layer_from_host
            kv = scatter_layer_from_host(kv, list(ev["targets"]),
                                         int(ev["layer"]), ev["values"],
                                         bs)
            if int(ev["layer"]) == int(ev["num_layers"]) - 1:
                written.update(int(b) * bs + o for b in ev["targets"]
                               for o in range(bs))
            fp(("kv_layer_stream", ev.get("rid"), int(ev["layer"])))
        if kind in ("prefill", "prefill_sp"):
            tok, kv = (exec_prefill_event(core, kv, ev)
                       if kind == "prefill"
                       else exec_sp_prefill_event(core, kv, ev))
            tok = jax.block_until_ready(tok)
            out["prefill"][ev["pf_seq"]] = int(tok)
            table = np.asarray(ev["table"])
            start = int(ev.get("start_pos", 0))   # sp path: always 0
            n = int(ev["true_len"])
            written.update(
                int(table[p // bs]) * bs + p % bs
                for p in range(start, start + n))
            fp(("prefill", ev["pf_seq"]))
        elif kind == "dispatch":
            K = int(ev["K"])
            chain = (disp_toks[ev["chained_from"]]
                     if ev["chained_from"] is not None else None)
            toks_k, kv = exec_dispatch_event(core, kv, ev, chain)
            toks_k = jax.block_until_ready(toks_k)
            disp_toks[ev["id"]] = toks_k
            out["dispatch"][ev["id"]] = np.asarray(toks_k).copy()
            tables = np.asarray(ev["tables"])
            positions = np.asarray(ev["positions"])
            for i, rid in enumerate(ev.get("reqs", [])):
                if rid is None:
                    continue
                p0 = int(positions[i])
                written.update(
                    int(tables[i, p // bs]) * bs + p % bs
                    for p in range(p0, p0 + K))
            fp(("dispatch", ev["id"]))
        elif kind == "ragged":
            # unified ragged dispatch (engine/ragged.py): every span's
            # rows wrote their positions' pool slots through the span's
            # slot table — prefill chunks, decode rows, and spec spans
            # alike; pipelined events chain off the previous ragged
            # dispatch's device tokens
            chain = (disp_toks[ev["chained_from"]]
                     if ev.get("chained_from") is not None else None)
            toks_r, kv = exec_ragged_event(core, kv, ev, chain)
            toks_r = jax.block_until_ready(toks_r)
            disp_toks[ev["id"]] = toks_r
            out["ragged"][ev["id"]] = np.asarray(toks_r).copy()
            tables = np.asarray(ev["tables"])
            positions = np.asarray(ev["positions"])
            starts = np.asarray(ev["starts"])
            counts = np.asarray(ev["counts"])
            for slot in range(counts.shape[0]):
                for r in range(int(counts[slot])):
                    p = int(positions[starts[slot] + r])
                    written.add(int(tables[slot, p // bs]) * bs + p % bs)
            fp(("ragged", ev["id"]))
        elif kind == "verify":
            # speculative verify (engine/spec/): every row — accepted,
            # rejected, pad — wrote its position's pool slot, so all of
            # them count as written (stale rows are rewritten by later
            # events before any read, exactly as in the live run)
            toks_v, kv = exec_verify_event(core, kv, ev)
            toks_v = jax.block_until_ready(toks_v)
            out["verify"][ev["id"]] = np.asarray(toks_v).copy()
            tables = np.asarray(ev["tables"])
            positions = np.asarray(ev["positions"])
            n_rows = np.asarray(ev["n_rows"])
            for i, rid in enumerate(ev.get("reqs", [])):
                if rid is None:
                    continue
                p0 = int(positions[i])
                written.update(
                    int(tables[i, p // bs]) * bs + p % bs
                    for p in range(p0, p0 + int(n_rows[i])))
            fp(("verify", ev["id"]))
    # expose the mirror tiers: follower-equivalence tests compare their
    # contents against the live engine's pools bit-for-bit
    out["host_mirror"] = mirror
    out["disk_mirror"] = disk_mirror
    return out


def compare_replay(events: List[dict], replayed: dict) -> List[str]:
    """Diff the live run's harvested tokens / first tokens against the
    synchronous replay. Returns human-readable mismatch lines."""
    diffs = []
    for ev in events:
        if ev["ev"] == "harvest":
            rep = replayed["dispatch"].get(ev["id"])
            if rep is None:
                continue
            live = np.asarray(ev["toks"])
            if not np.array_equal(live, rep):
                bad = np.argwhere(live != rep)
                diffs.append(
                    f"dispatch {ev['id']}: live != replay at (k,slot) "
                    f"{bad.tolist()} live={live.tolist()} "
                    f"replay={rep.tolist()}")
        elif ev["ev"] == "spec_harvest":
            rep = replayed.get("verify", {}).get(ev["id"])
            if rep is None:
                continue
            live = np.asarray(ev["toks"])
            if not np.array_equal(live, rep):
                bad = np.argwhere(live != rep)
                diffs.append(
                    f"verify {ev['id']}: live != replay at (slot,row) "
                    f"{bad.tolist()} live={live.tolist()} "
                    f"replay={rep.tolist()}")
        elif ev["ev"] == "ragged_harvest":
            rep = replayed.get("ragged", {}).get(ev["id"])
            if rep is None:
                continue
            live = np.asarray(ev["toks"])
            if not np.array_equal(live, rep):
                bad = np.argwhere(live != rep)
                diffs.append(
                    f"ragged {ev['id']}: live != replay at slots "
                    f"{bad.tolist()} live={live.tolist()} "
                    f"replay={rep.tolist()}")
        elif ev["ev"] == "first_token":
            rep = replayed["prefill"].get(ev["pf_seq"])
            if rep is not None and rep != ev["tok"]:
                diffs.append(
                    f"prefill {ev['pf_seq']} ({ev['rid']}): live tok "
                    f"{ev['tok']} != replay {rep}")
    return diffs


# --------------------------------------------------------------------------
# Pure log analysis: pool-slot ownership + stale-read detection
# --------------------------------------------------------------------------


@dataclasses.dataclass
class StaleRead:
    dispatch_id: int
    slot: int
    rid: str
    kv_pos: int
    pool_slot: int
    writer: Optional[str]

    def __str__(self) -> str:
        return (f"dispatch {self.dispatch_id} slot {self.slot} ({self.rid}) "
                f"reads kv position {self.kv_pos} from pool slot "
                f"{self.pool_slot}, last written by {self.writer!r}")


def check_log(events: List[dict], block_size: int) -> List[StaleRead]:
    """Simulate per-pool-slot last-writer over the recorded device order and
    report reads of slots whose last writer is a different request.

    Device order == log order for prefill/dispatch events (one stream).
    A prefill writes positions start_pos..start_pos+true_len-1 through its
    table (pads go to the trash block). A K-step dispatch, for each active
    slot, writes the input token's KV at positions p..p+K-1 and at step k
    reads every position <= p+k through its table. Writes to the trash
    block (id 0) are ignored.
    """
    last_writer: Dict[int, str] = {}
    stale: List[StaleRead] = []

    def write(pool_slot: int, rid: str) -> None:
        if pool_slot // block_size != 0:       # trash block: ignore
            last_writer[pool_slot] = rid

    for ev in events:
        if ev["ev"] == "hit_transfer":
            # prefix-cache hit (recorded before the admission's prefill):
            # the first `hit` positions are legitimately shared with their
            # original writer — transfer read rights so by-design sharing
            # isn't reported as a stale read
            table = list(ev["blocks"])
            for p in range(int(ev["hit"])):
                ps = table[p // block_size] * block_size + p % block_size
                write(ps, ev["rid"])
        if ev["ev"] == "precomputed_admit":
            # wire-plane disagg scatter writes whole target blocks
            for b in ev["targets"]:
                for o in range(block_size):
                    write(int(b) * block_size + o, ev["rid"])
        if ev["ev"] == "kv_layer_stream":
            # streaming disagg scatter: each layer event writes the same
            # whole target blocks (per-slot ownership is layer-agnostic)
            for b in ev["targets"]:
                for o in range(block_size):
                    write(int(b) * block_size + o, ev["rid"])
        if ev["ev"] in ("prefill", "prefill_sp"):
            table = np.asarray(ev["table"])
            rid = ev["rid"]
            start = int(ev.get("start_pos", 0))   # sp path: always 0
            n = int(ev["true_len"])
            # reads: the chunk attends to everything < start+n through the
            # same table (prefix continuation) — check those too
            for p in range(0, start + n):
                ps = int(table[p // block_size]) * block_size + p % block_size
                if p >= start:
                    write(ps, rid)
                else:
                    w = last_writer.get(ps)
                    if w is not None and w != rid:
                        stale.append(StaleRead(-1, -1, rid, p, ps, w))
        elif ev["ev"] == "ragged":
            # a ragged dispatch (engine/ragged.py) is counts[slot]
            # fused steps per slot from the pool's perspective: span
            # row r writes position pos0+r and reads everything <= it
            # through the slot's table — the verify event's ownership
            # semantics with per-slot row counts
            tables = np.asarray(ev["tables"])
            positions = np.asarray(ev["positions"])
            starts = np.asarray(ev["starts"])
            counts = np.asarray(ev["counts"])
            for i, rid in enumerate(ev["reqs"]):
                if rid is None or int(counts[i]) == 0:
                    continue
                for r in range(int(counts[i])):
                    p = int(positions[int(starts[i]) + r])
                    ps = (int(tables[i, p // block_size]) * block_size
                          + p % block_size)
                    write(ps, rid)
                    for q in range(0, p + 1):
                        qs = (int(tables[i, q // block_size])
                              * block_size + q % block_size)
                        w = last_writer.get(qs)
                        if w is not None and w != rid:
                            stale.append(StaleRead(
                                ev["id"], i, rid, q, qs, w))
        elif ev["ev"] in ("dispatch", "verify"):
            # a verify dispatch (engine/spec/) is K=n_rows[i] fused
            # steps per slot from the pool's perspective: row t writes
            # position p0+t and reads everything <= it through the same
            # table — identical ownership semantics to a K-step scan
            tables = np.asarray(ev["tables"])
            positions = np.asarray(ev["positions"])
            n_rows = (np.asarray(ev["n_rows"])
                      if ev["ev"] == "verify" else None)
            for i, rid in enumerate(ev["reqs"]):
                if rid is None:
                    continue
                K = int(ev["K"]) if n_rows is None else int(n_rows[i])
                p0 = int(positions[i])
                for k in range(K):
                    p = p0 + k
                    ps = (int(tables[i, p // block_size]) * block_size
                          + p % block_size)
                    write(ps, rid)
                    # reads: every position <= p via this table
                    for q in range(0, p + 1):
                        qs = (int(tables[i, q // block_size]) * block_size
                              + q % block_size)
                        w = last_writer.get(qs)
                        if w is not None and w != rid:
                            stale.append(StaleRead(
                                ev["id"], i, rid, q, qs, w))
    # dedupe (same slot re-read every later step)
    seen = set()
    uniq = []
    for s in stale:
        key = (s.rid, s.kv_pos, s.pool_slot, s.writer)
        if key not in seen:
            seen.add(key)
            uniq.append(s)
    return uniq


def check_inputs(events: List[dict]) -> List[str]:
    """Input-consistency invariants over the log, reconstructed purely from
    admit/harvest/dispatch events: chained dispatches must run K ahead on
    positions/steps and their request mapping must equal the chained-from
    dispatch's; host-fed dispatches must feed the request's last harvested
    token at its current position."""
    problems = []
    state: Dict[str, dict] = {}       # rid -> {pos, key_step, last_tok}
    disp: Dict[int, dict] = {}
    rag_disp: Dict[int, dict] = {}    # ragged events by id (harvest
    #                                   needs starts for row-sampled toks)
    for ev in events:
        if ev["ev"] == "admit":
            state[ev["rid"]] = {
                "pos": ev["pos"], "key_step": ev["key_step"],
                "last": None}         # last token may be deferred
        elif ev["ev"] == "first_token":
            if ev["rid"] in state:
                state[ev["rid"]]["last"] = ev["tok"]
        elif ev["ev"] == "dispatch":
            disp[ev["id"]] = ev
            positions = np.asarray(ev["positions"])
            steps = np.asarray(ev["steps"])
            tokens = np.asarray(ev["tokens"])
            mask = np.asarray(ev["mask"])
            if ev["chained_from"] is not None:
                src = disp.get(ev["chained_from"])
                for i, rid in enumerate(ev["reqs"]):
                    if mask[i] and (src is None or src["reqs"][i] != rid):
                        problems.append(
                            f"dispatch {ev['id']} slot {i} chained but "
                            f"chained-from mapping differs")
            for i, rid in enumerate(ev["reqs"]):
                if rid is None or rid not in state:
                    continue
                st = state[rid]
                ahead = int(ev["K"]) if mask[i] else 0
                if int(positions[i]) != st["pos"] + ahead:
                    problems.append(
                        f"dispatch {ev['id']} slot {i} ({rid}): position "
                        f"{int(positions[i])} != state {st['pos']}+{ahead}")
                if int(steps[i]) != st["key_step"] + ahead:
                    problems.append(
                        f"dispatch {ev['id']} slot {i} ({rid}): key step "
                        f"{int(steps[i])} != state {st['key_step']}+{ahead}")
                pm = np.asarray(ev["planned_mask"]) if "planned_mask" in ev \
                    else None
                planned_first = bool(pm is not None and pm[0, i])
                if (not mask[i] and not planned_first
                        and st["last"] is not None
                        and int(tokens[i]) != st["last"]):
                    problems.append(
                        f"dispatch {ev['id']} slot {i} ({rid}): host token "
                        f"{int(tokens[i])} != last harvested {st['last']}")
        elif ev["ev"] == "verify":
            positions = np.asarray(ev["positions"])
            steps = np.asarray(ev["steps"])
            tokens = np.asarray(ev["tokens"])
            for i, rid in enumerate(ev["reqs"]):
                if rid is None or rid not in state:
                    continue
                st = state[rid]
                if int(positions[i]) != st["pos"]:
                    problems.append(
                        f"verify {ev['id']} slot {i} ({rid}): position "
                        f"{int(positions[i])} != state {st['pos']}")
                if int(steps[i]) != st["key_step"]:
                    problems.append(
                        f"verify {ev['id']} slot {i} ({rid}): key step "
                        f"{int(steps[i])} != state {st['key_step']}")
                if (st["last"] is not None
                        and int(tokens[i, 0]) != st["last"]):
                    problems.append(
                        f"verify {ev['id']} slot {i} ({rid}): row-0 "
                        f"token {int(tokens[i, 0])} != last harvested "
                        f"{st['last']}")
        elif ev["ev"] == "ragged":
            rag_disp[ev["id"]] = ev
            positions = np.asarray(ev["positions"])
            starts = np.asarray(ev["starts"])
            counts = np.asarray(ev["counts"])
            steps = np.asarray(ev["steps"])
            # [capacity] row steps = the spec-enabled row-sampled
            # variant; [B+1] slot steps = the slot-sampled one
            row_sampled = steps.shape[0] == positions.shape[0]
            mask = (np.asarray(ev["mask"])
                    if ev.get("chained_from") is not None else None)
            for i, rid in enumerate(ev["reqs"]):
                if rid is None or rid not in state \
                        or int(counts[i]) == 0:
                    continue
                st = state[rid]
                # pipelined ragged: chained spans run one un-harvested
                # token ahead of host state (the dispatch-event mask
                # convention; chained spans are single decode rows)
                ahead = int(mask is not None
                            and mask[int(starts[i])])
                p0 = int(positions[int(starts[i])])
                if p0 != st["pos"] + ahead:
                    problems.append(
                        f"ragged {ev['id']} slot {i} ({rid}): first-row "
                        f"position {p0} != state {st['pos']}+{ahead}")
                if row_sampled:
                    # row r keys at key_step + r — check the first row
                    if int(steps[int(starts[i])]) \
                            != st["key_step"] + ahead:
                        problems.append(
                            f"ragged {ev['id']} slot {i} ({rid}): "
                            f"first-row key step "
                            f"{int(steps[int(starts[i])])} != state "
                            f"{st['key_step']}+{ahead}")
                elif int(steps[i]) != (st["key_step"] + ahead
                                       + int(counts[i]) - 1):
                    # the span's LAST row samples at key_step + len - 1
                    # (the lane skew convention)
                    problems.append(
                        f"ragged {ev['id']} slot {i} ({rid}): sample "
                        f"key step {int(steps[i])} != state "
                        f"{st['key_step']}+{ahead}+{int(counts[i]) - 1}")
        elif ev["ev"] == "ragged_harvest":
            toks = np.asarray(ev["toks"])
            src = rag_disp.get(ev["id"])
            for slot, rid, n, emitted in ev["applied"]:
                if rid in state:
                    st = state[rid]
                    st["pos"] += n
                    st["key_step"] += n
                    if emitted and n > 0:
                        if (src is not None and toks.shape[0]
                                == np.asarray(src["positions"]).shape[0]):
                            # row-sampled: the last APPLIED row's token
                            # (spec spans may rewind before the span end)
                            start = int(np.asarray(src["starts"])[slot])
                            st["last"] = int(toks[start + n - 1])
                        else:
                            st["last"] = int(toks[slot])
        elif ev["ev"] == "harvest":
            toks = np.asarray(ev["toks"])
            for slot, rid, n in ev["applied"]:
                if rid in state:
                    st = state[rid]
                    st["pos"] += n
                    st["key_step"] += n
                    if n > 0:
                        st["last"] = int(toks[n - 1, slot])
        elif ev["ev"] == "spec_harvest":
            toks = np.asarray(ev["toks"])      # [B, Tv]
            for slot, rid, n, _accepted in ev["applied"]:
                if rid in state:
                    st = state[rid]
                    st["pos"] += n
                    st["key_step"] += n
                    if n > 0:
                        st["last"] = int(toks[slot, n - 1])
        elif ev["ev"] == "preempt":
            state.pop(ev["rid"], None)
    return problems
