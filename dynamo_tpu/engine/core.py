"""Continuous-batching engine core: slots, paged-block allocator, and the
async scheduling loop driving the jitted prefill/decode steps.

The reference's analog is the external engine it orchestrates (vLLM's
scheduler + paged allocator); here it is native. TPU-first specifics:

- one jitted decode program serves the whole batch every step (static
  [max_num_seqs] shapes; inactive slots aim at the trash block and their
  outputs are ignored);
- prefill programs are compiled per bucket length (EngineConfig.prefill_buckets)
  so XLA sees only static shapes;
- KV caches are donated through every step call → XLA updates HBM in place;
- cancellation is step-granular: each loop iteration polls request contexts
  (an in-flight XLA dispatch is never interrupted), matching the semantics
  the runtime's EngineContext promises (SURVEY.md §7 "hard parts").
"""

from __future__ import annotations

import asyncio
import dataclasses
import logging
import os
import time
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..llm.kv.blocks import TokenBlockSequence
from ..llm.kv.offload import OffloadJob
from ..llm.kv.pool import KvBlockManager
from .block_copy import scatter_blocks_from_host
from ..llm.kv_router.protocols import ForwardPassMetrics
from ..llm.protocols.common import FinishReason
from .config import EngineConfig, ModelConfig
from .models import llama
from .sampling import SlotSampling, make_slot_keys, sample_tokens

logger = logging.getLogger("dynamo_tpu.engine")


@dataclasses.dataclass
class EngineRequest:
    """One sequence's engine-side state."""

    rid: str
    prompt: List[int]
    sampling: SlotSampling
    max_new_tokens: int
    eos_ids: frozenset
    ctx: object = None            # runtime EngineContext (cancellation)
    out_queue: asyncio.Queue = dataclasses.field(default_factory=asyncio.Queue)
    # disaggregation (SURVEY.md §7 stage 7):
    # - prefill worker: async callback(first_token, logprob, host_values,
    #   seq_hashes) shipping the prompt's KV blocks to the decode engine;
    #   the request finishes after prefill (the reference's max_tokens=1
    #   remote-decode prefill, examples/llm/components/prefill_worker.py).
    handoff: object = None
    # - device mode: handoff receives the DEVICE gather ({"stacked", ...})
    #   instead of host wire values — the in-process ICI bulk plane
    #   (llm/kv_transport.py); no device→host fetch happens at all.
    handoff_device: bool = False
    # - wire mode with layer streaming negotiated (llm/kv/stream.py): the
    #   handoff receives a LayeredHarvest (per-layer device→host fetches)
    #   instead of whole-stack host values, so the prefill worker chains
    #   per-layer DATA frames while later layers are still fetching
    handoff_layered: bool = False
    # - decode worker: KV arrived from a remote prefill (KvPayload with
    #   host wire values, or kv_transport.DeviceKvPayload with device
    #   arrays); admission scatters it instead of running the prefill
    #   program.
    precomputed: object = None
    # engine state
    slot: int = -1
    blocks: List[int] = dataclasses.field(default_factory=list)
    pos: int = 0                  # tokens currently in KV
    generated: int = 0
    # monotone per-request PRNG step: equals `generated` until a
    # preemption, after which it keeps advancing so recompute never reuses
    # consumed sampling keys (seeded streams stay reproducible under load)
    key_step: int = 0
    last_token: int = -1
    # False while the admission prefill's sampled token is still being
    # fetched from the device (overlap_admission_fetch): the slot is held
    # but excluded from decode until completion
    ready: bool = True
    prefix_hit_tokens: int = 0
    seq: Optional[TokenBlockSequence] = None   # full token history + hashes
    registered_blocks: int = 0
    emitted_total: int = 0        # tokens the client has seen (across lives)
    # lane-prefill mode (EngineConfig.lane_prefill_max_tokens): the FULL
    # prompt (incl. any prefix-hit tokens); while pos < len(lane_prompt)
    # the slot's decode inputs come from here ("planned" tokens) and
    # sampled outputs are discarded — the step consuming the last prompt
    # token yields the first real generation. None = normal admission.
    lane_prompt: Optional[List[int]] = None
    # client-stream indices where the next token was derived through a
    # DIFFERENT compiled program than an uncontended prefill-path run would
    # use: recompute preemptions (prefill re-derives the boundary token)
    # and lane admissions (the decode program derives the first token).
    # Bit-exactness vs an uncontended run is guaranteed only UP TO the
    # first of these — f32 numerics differ across program shapes and can
    # legitimately flip a greedy argmax at near-tie logits (KNOWN_ISSUES).
    numeric_boundaries: List[int] = dataclasses.field(default_factory=list)
    # speculative decoding (engine/spec/): max drafts verified per
    # dispatch for THIS request. -1 = follow the engine's live default
    # (EngineCore.spec_k_live, llmctl spec set-k); 0 = explicitly off;
    # n > 0 clamps to the compiled maximum EngineConfig.spec_k.
    spec_k: int = -1
    # multi-tenant serving plane (llm/tenancy.py): tenant attributes
    # this request's registered KV blocks in the tiers' quota ledger
    # ("" = the implicit single tenant — untenanted behavior exactly);
    # session groups requests for exported-trace prefix structure.
    tenant: str = ""
    session: str = ""
    enqueue_time: float = dataclasses.field(default_factory=time.monotonic)
    first_token_time: Optional[float] = None
    # the request's runtime Trace (runtime/tracing.py) — attached by
    # submit() from the ambient contextvar so the engine can feed
    # per-phase spans (queue wait, KV onboard incl. fabric fetch,
    # preemption markers) into the same fleet trace the frontend opened.
    # Kept as `object` to stay dependency-light; None = untraced.
    trace: object = None

    # tier-hit onboard prep failed once: the re-admission skips the
    # host/disk/remote cascade and recomputes cold (graceful fallback —
    # a broken tier must never make serving worse than no tier)
    cold_admission: bool = False

    @property
    def cancelled(self) -> bool:
        """Client-stop OR deadline-exceeded — both vacate the slot the
        same way; _finish_request counts them apart."""
        if self.ctx is None:
            return False
        return bool(self.ctx.is_stopped
                    or getattr(self.ctx, "deadline_exceeded", False))


_FINISH = object()  # queue sentinel


class EngineCore:
    """The model-executing scheduler. Owns params + KV cache on device."""

    def __init__(self, model_cfg: ModelConfig, engine_cfg: EngineConfig,
                 params: Optional[dict] = None, attn_impl: str = "auto",
                 param_dtype=jnp.bfloat16, mesh=None,
                 kv_event_publisher=None):
        if engine_cfg.kv_block_size == 0:
            # bring-up auto-selection (EngineConfig.auto_kv_block_size —
            # the round-5 small-C finding, promoted from a bench.py-only
            # default): resolved HERE, before anything reads the block
            # size, so every downstream consumer sees a concrete value
            engine_cfg = dataclasses.replace(
                engine_cfg,
                kv_block_size=EngineConfig.auto_kv_block_size(
                    model_cfg, engine_cfg.kv_quantization))
        self.model_cfg = model_cfg
        self.cfg = engine_cfg
        self.mesh = mesh
        # pipeline parallelism (parallel/pipeline_parallel.py): the mesh
        # is authoritative — a "pp" axis switches param/KV placement and
        # the whole compiled program set to the token-interleaved stage
        # ring. EngineConfig.pp must agree when set (every rank of a
        # multihost engine builds from identical flags).
        self.pp = (mesh.shape["pp"]
                   if mesh is not None and "pp" in mesh.axis_names else 1)
        if engine_cfg.pp > 1 and engine_cfg.pp != self.pp:
            raise ValueError(
                f"EngineConfig.pp={engine_cfg.pp} but the mesh carries "
                f"pp={self.pp} — build the mesh with make_pp_mesh(pp, tp)")
        if self.pp > 1:
            # the mesh can carry pp the config never saw (tests build
            # meshes directly): re-run the config-level pp validation
            # against the REAL stage count, then the model-level checks
            dataclasses.replace(engine_cfg, pp=self.pp)  # raises on misuse
            if model_cfg.kv_lora_rank > 0:
                raise NotImplementedError(
                    "pp with MLA latent-KV attention is not implemented "
                    "(the latent pool has no per-stage form yet)")
        # model-family dispatch: MLA (deepseek-class latent-KV attention)
        # vs the llama family. The MLA integration is single-chip,
        # full-precision first — each unsupported combination refuses
        # loudly below rather than serving garbage.
        self.is_mla = model_cfg.kv_lora_rank > 0
        if self.is_mla:
            from .models import mla
            self.model_mod = mla
            if engine_cfg.quantization.startswith("int4"):
                # int8 works (quant.py _LAYER_MATMULS carries the MLA
                # names; wkv_b deliberately stays full precision for the
                # absorbed einsums); the grouped-int4 paths (Pallas
                # kernel lane alignment, hybrid-scan slicing of packed
                # rows) are unvalidated for this family
                raise NotImplementedError(
                    "MLA + int4 weight quantization is not integrated "
                    "yet (int8 is)")
        else:
            self.model_mod = llama
        if (model_cfg.sliding_window is not None
                and engine_cfg.max_model_len <= model_cfg.sliding_window):
            # the window can never bind at this serving length: drop it so
            # decode keeps the Pallas-eligible path (window masking forces
            # the XLA gather implementation)
            model_cfg = dataclasses.replace(model_cfg, sliding_window=None)
            self.model_cfg = model_cfg
        _rs = model_cfg.rope_scaling
        if (_rs is not None and _rs.rope_type == "longrope"
                and _rs.longrope_active == "auto"
                and engine_cfg.max_model_len
                <= _rs.original_max_position_embeddings):
            # every servable sequence fits the pretrained window, so the
            # SHORT factors are HF-exact for all of them (HF switches to
            # long only past original_max); the attention scaling stays
            # config-derived either way (llama.rope_attention_scaling)
            model_cfg = dataclasses.replace(
                model_cfg, rope_scaling=dataclasses.replace(
                    _rs, longrope_active="short"))
            self.model_cfg = model_cfg
        self.statics = llama.ModelStatics(
            cfg=model_cfg, block_size=engine_cfg.kv_block_size,
            attn_impl=attn_impl,
            kv_coalesce=engine_cfg.kv_contig_alloc)
        if engine_cfg.quantization not in ("none", "int8", "int8-noembed",
                                           "int4", "int4-noembed"):
            raise ValueError(
                f"unknown quantization {engine_cfg.quantization!r}")
        quantized = engine_cfg.quantization != "none"
        # int4 = grouped-int4 dense matmuls + lm_head, int8 embed
        # (quant.py module docstring); -noembed leaves the embed in the
        # load dtype for either width
        qbits = 4 if engine_cfg.quantization.startswith("int4") else 8
        qembed = not engine_cfg.quantization.endswith("-noembed")
        if params is None and quantized:
            # streaming init→quantize: never materializes the full bf16
            # tree (16 GB for 8B geometry — OOM on one 16 GB v5e)
            from .quant import init_params_quantized
            params = init_params_quantized(
                model_cfg, jax.random.PRNGKey(engine_cfg.seed),
                dtype=param_dtype, include_embed=qembed, bits=qbits)
        elif params is None:
            params = self.model_mod.init_params(
                model_cfg, jax.random.PRNGKey(engine_cfg.seed), dtype=param_dtype)
        elif quantized:
            from .quant import quantize_params
            params = quantize_params(
                params, include_embed=qembed, bits=qbits)
        if (mesh is None
                and os.environ.get("DYN_FUSE_MATMULS", "1") != "0"):
            # single-device decode perf: wq|wk|wv → wqkv, gate|up →
            # gateup (llama.fuse_stacked_matmuls). The gate is ANY mesh,
            # not just tp: under tp the fused out axis cannot carry the
            # column permutation the TP-8 projection was flagged for,
            # and under pp (even tp=1) the stage ring shards the UNFUSED
            # per-tensor layout — a pp mesh silently taking the fused
            # path would break pp_param_pspecs' per-key placement
            # (test_pipeline_parallel asserts no fused keys on a pp
            # core). dict(): the transform deletes split keys — never
            # from the caller's own tree
            params = llama.fuse_stacked_matmuls(dict(params), model_cfg)
        self.params = params
        kv_shards = 1
        if (mesh is not None and engine_cfg.kv_quantization != "none"
                and not self.is_mla):
            # llama pools only: the MLA latent pool replicates under tp
            # (no per-shard scale sections; mla.init_kv_cache)
            # int8 + tensor parallelism: the pool row carries one
            # (values, scales) section per tp shard so the lane-axis tp
            # sharding never splits a scale group (attention.py
            # quantize_kv_rows groups)
            kv_shards = mesh.shape.get("tp", 1)
            if model_cfg.num_kv_heads % kv_shards != 0:
                raise ValueError(
                    f"kv_quantization with tp={kv_shards} needs tp to "
                    f"divide the KV head count "
                    f"({model_cfg.num_kv_heads}) — each tp shard must "
                    f"own whole heads to carry its own in-row scale "
                    f"group")
        if self.is_mla:
            self.kv = self.model_mod.init_kv_cache(
                model_cfg, engine_cfg.num_kv_blocks,
                engine_cfg.kv_block_size, dtype=param_dtype,
                quantization=engine_cfg.kv_quantization)
        else:
            self.kv = llama.init_kv_cache(
                model_cfg, engine_cfg.num_kv_blocks,
                engine_cfg.kv_block_size, dtype=param_dtype,
                quantization=engine_cfg.kv_quantization,
                kv_shards=kv_shards)
        if mesh is not None and self.pp > 1:
            # pp(×tp) placement: layer stacks + KV pool shard L over the
            # stage ring; embed/final_norm/lm_head replicate (the last
            # stage samples locally). Validates layer divisibility and
            # the sliding-window refusal up front.
            from ..parallel.pipeline_parallel import (place_pp,
                                                      pp_split_config)
            pp_split_config(self.statics, self.pp)
            self.params, self.kv = place_pp(self.params, self.kv, mesh,
                                            model_cfg)
            if model_cfg.lm_head_pallas:
                # the stage's in-shard_map _logits has no Pallas
                # partitioning rule — route to the XLA head paths
                model_cfg = dataclasses.replace(model_cfg,
                                                lm_head_pallas=False)
                self.model_cfg = model_cfg
                self.statics = dataclasses.replace(self.statics,
                                                   cfg=model_cfg)
        elif mesh is not None:
            # place params/KV under the tp/sp layout; every jitted step then
            # runs SPMD over the mesh with XLA-inserted ICI collectives
            from ..parallel.sharding import shard_kv, shard_params
            self.params = shard_params(self.params, mesh, model_cfg)
            self.kv = shard_kv(self.kv, mesh)
            if mesh.shape.get("tp", 1) > 1 and model_cfg.lm_head_pallas:
                # the head is vocab-sharded over tp; the fused Pallas head
                # cannot partition — route _logits to the XLA paths
                model_cfg = dataclasses.replace(model_cfg,
                                                lm_head_pallas=False)
                self.model_cfg = model_cfg
                self.statics = dataclasses.replace(self.statics,
                                                   cfg=model_cfg)
        if model_cfg.lm_head_pallas and quantized:
            # eager one-time kernel selftest (must run OUTSIDE jit traces):
            # a lowering failure on this backend degrades to the XLA head
            # paths instead of breaking every decode program (the head is
            # int8 under every quantization mode, incl. int4)
            from .attention import _on_tpu
            from .lm_head import kernel_selftest
            if _on_tpu() and not kernel_selftest():
                model_cfg = dataclasses.replace(model_cfg,
                                                lm_head_pallas=False)
                self.model_cfg = model_cfg
                self.statics = dataclasses.replace(self.statics,
                                                   cfg=model_cfg)
        self.kv_event_publisher = kv_event_publisher
        host_pool = None
        self.offload_engine = None
        self.disk_store = None
        self.spill_engine = None
        self._pending_spills: List[int] = []
        if engine_cfg.host_kv_blocks > 0:
            from ..llm.kv.offload import KvOffloadEngine, make_host_pool
            host_pool = make_host_pool(
                engine_cfg.host_kv_blocks, model_cfg,
                engine_cfg.kv_block_size, engine_cfg.kv_quantization,
                int(next(iter(self.kv.values())).shape[-1]), param_dtype)
        if engine_cfg.kv_disk_blocks > 0:
            # G3 tier (llm/kv/diskstore.py): content-addressed on-disk
            # block store under the host pool — host evictions spill
            # there (write-behind), disk hits promote through the
            # off-thread onboard path, and acknowledged blocks survive
            # kill -9 (warm restart). __post_init__ guaranteed the host
            # tier exists.
            from ..llm.kv.diskstore import DiskKvStore, DiskSpillEngine
            self.disk_store = DiskKvStore(
                engine_cfg.kv_disk_dir, engine_cfg.kv_disk_blocks,
                expect_block_size=engine_cfg.kv_block_size)
            self.spill_engine = DiskSpillEngine(
                self.disk_store, on_commit=self._emit_kv_disk_store)
            host_pool.on_evict = self._on_host_evict
        self.remote_store = None
        self.remote_spill_engine = None
        self.kv_fabric = None            # llm/kv/fabric.py, attached at run
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        if engine_cfg.kv_remote_dir:
            # G4 tier (llm/kv/remotestore.py): the fleet fabric's durable
            # rung — disk-tier capacity evictions promote to the shared
            # object store (write-behind, acknowledged iff durable), and
            # remote hits onboard through the same off-thread path as
            # disk. The peer-worker backend attaches at runtime
            # (attach_kv_fabric). __post_init__ guaranteed the disk tier
            # exists.
            from ..llm.kv.diskstore import DiskSpillEngine
            from ..llm.kv.remotestore import ObjectKvBackend, RemoteKvStore
            self.remote_store = RemoteKvStore(ObjectKvBackend(
                engine_cfg.kv_remote_dir, engine_cfg.kv_remote_blocks))
            self.remote_spill_engine = DiskSpillEngine(
                self.remote_store, on_commit=self._emit_kv_remote_store)
            self.disk_store.on_evict = self._on_disk_evict
        self.kv_manager = KvBlockManager(
            engine_cfg.num_kv_blocks, engine_cfg.kv_block_size,
            enable_reuse=engine_cfg.enable_prefix_reuse,
            on_stored=self._on_block_stored,
            on_removed=self._on_block_removed, host_pool=host_pool,
            disk_store=self.disk_store, remote_store=self.remote_store)
        if host_pool is not None:
            self.offload_engine = KvOffloadEngine(
                host_pool, engine_cfg.kv_block_size,
                get_kv=lambda: self.kv,
                release_holds=self.kv_manager.pool.release,
                simulated_gbps=engine_cfg.offload_simulated_gbps or None,
                on_store=self._emit_kv_store)
        self.M = engine_cfg.max_blocks_per_seq
        self.B = engine_cfg.max_num_seqs
        # jitted cross-quant repack converters, keyed by the payload's
        # (lane width, dtype); shapes re-specialize inside each jit cache
        self._repack_jits: dict = {}

        self.slots: List[Optional[EngineRequest]] = [None] * self.B
        # optional engine.replay.Recorder capturing the schedule decision
        # log (dispatch inputs in device order) for deterministic replay
        self.recorder = None
        self._pending: Optional[dict] = None   # un-harvested decode dispatch
        self._ragged_pending: Optional[dict] = None  # pipelined ragged
        self._admissions: List[tuple] = []     # (req, tok_dev, logprob_dev)
        self._onboards: List[tuple] = []  # (req, slot, plan, prepped,
        #                                    remote_values-for-recorder)
        self._onboard_tasks: set = set()
        self._handoff_tasks: set = set()
        self.waiting: asyncio.Queue[EngineRequest] = asyncio.Queue()
        # every submitted-not-finished request by id (slots/waiting
        # alone can miss one mid-admission) — _fail_pending's registry
        self._inflight_reqs: dict = {}
        self._dead: Optional[BaseException] = None
        self._work_event = asyncio.Event()
        self._loop_task: Optional[asyncio.Task] = None
        self._stopping = False
        self._step = 0
        # host mirrors of per-slot state
        self._block_tables = np.zeros((self.B, self.M), dtype=np.int32)
        self._positions = np.zeros((self.B,), dtype=np.int32)
        self._tokens = np.zeros((self.B,), dtype=np.int32)
        self._samp = {
            "temperature": np.zeros((self.B,), np.float32),
            "top_k": np.zeros((self.B,), np.int32),
            "top_p": np.ones((self.B,), np.float32),
        }
        self._seeds = np.zeros((self.B,), np.int64)
        # speculative decoding (engine/spec/): host-side drafter + the
        # live draft budget (llmctl spec set-k moves it within
        # [0, cfg.spec_k]; the verify program's shape is compiled at
        # cfg.spec_k+1 rows and never widens at runtime)
        self.spec_k_live = engine_cfg.spec_k
        self.drafter = None
        if engine_cfg.spec_k > 0:
            from .spec import PromptLookupDrafter
            self.drafter = PromptLookupDrafter(
                max_ngram=engine_cfg.spec_ngram_max,
                min_ngram=engine_cfg.spec_ngram_min,
                window=engine_cfg.spec_window)
        self._compile_jits()
        # serving stats
        self.total_prefill_tokens = 0
        self.total_decode_tokens = 0
        self.preemptions = 0
        self.lane_admissions = 0
        self.host_onboards = 0
        # contiguity-aware layout (docs/kv_layout.md): defrag passes run
        # + blocks migrated; per-move truth lives on the pool
        # (defrag_moves_total — relocate() increments it)
        self.defrag_passes = 0
        self._defrag_last_step = -(1 << 30)
        # disk (G3) tier: promote-path admissions + blocks restored
        self.disk_onboards = 0
        self.disk_onboarded_blocks = 0
        # remote (G4) fabric tier: fetch-path admissions + the graceful
        # fallbacks (a failed peer fetch recomputes, never errors)
        self.remote_onboards = 0
        self.remote_onboarded_blocks = 0
        self.remote_fetch_failures = 0
        # prefill-as-a-service (components/prefill_service.py): prefix
        # blocks this engine published to the durable object tier
        self.prefill_published_blocks = 0
        # streaming layer-wise KV handoff (llm/kv/stream.py): layers this
        # DECODE engine progressively scattered, stream admissions that
        # fell back (torn → monolithic fill, dead stream → cold
        # recompute), and the transfer-overlap split — busy seconds the
        # engine spent prepping/scattering already-arrived layers (work
        # hidden behind the in-flight transfer) vs seconds it sat exposed
        # waiting on the wire. The nv_llm_disagg_stream_* gauge feed.
        self.disagg_stream_admits = 0
        self.disagg_stream_layers_scattered = 0
        self.disagg_stream_fallbacks = 0
        self.disagg_stream_hidden_s = 0.0
        self.disagg_stream_exposed_s = 0.0
        self._stream_tasks: set = set()
        # end-to-end cancellation/deadlines (docs/chaos.md): requests
        # vacated because the client stopped caring (disconnect → KILL
        # frame → ctx.kill) vs because their wire-propagated deadline
        # budget ran out engine-side — the nv_llm_requests_cancelled_
        # total / _deadline_exceeded_total feeds
        self.requests_cancelled_total = 0
        self.requests_deadline_exceeded_total = 0
        # multi-tenant serving plane (llm/tenancy.py): attached by
        # enable_tenancy() — per-tenant block ledger threaded through the
        # device/host/disk/remote tiers (quota-preferred eviction) plus
        # per-tenant admission counters, the nv_llm_tenant_* gauge feed
        self.tenancy = None
        self.tenant_admitted: dict = {}
        self.tenant_hits: dict = {}
        self.tenant_queries: dict = {}
        # tier-hit onboards whose off-thread prep failed and were
        # re-admitted COLD (full recompute) instead of erroring out
        self.onboard_cold_retries = 0
        # measured prefill rate feed for the fabric's admission gate and
        # the router's NetKV scoring: wall seconds spent in prefill
        # admissions (dispatch + host glue — an upper bound, so the
        # modeled recompute it feeds is conservative). The cumulative
        # totals stay for bench provenance; the RATE the gate prices
        # with is age-weighted (fabric.PrefillRateEstimator) so XLA-
        # compile-inflated early admissions on a young engine don't skew
        # fetch-vs-recompute pricing.
        self.prefill_wall_s = 0.0
        from ..llm.kv.fabric import PrefillRateEstimator
        self.prefill_rate_estimator = PrefillRateEstimator()
        # ragged-dispatch stats (nv_llm_ragged_* metrics feed;
        # docs/ragged_attention.md). "saved" counts the split-path
        # dispatches each ragged batch stood in for, minus itself
        # (ragged.RaggedBatch.dispatches_replaced).
        self.ragged_dispatches = 0
        self.ragged_rows_total = 0
        self.ragged_prefill_rows_total = 0
        self.ragged_decode_rows_total = 0
        self.ragged_mixed_dispatches = 0
        self.ragged_dispatches_saved = 0
        # ragged×spec: draft rows that rode ragged dispatches (the
        # nv_llm_ragged_spec_rows_total feed); acceptance rides the
        # shared spec_* counters below
        self.ragged_spec_rows = 0
        # cross-sequence wave prefetch (attention.ragged_prefetch_counts
        # — the host-side mirror of the kernel's parity chain): first
        # waves seen / first waves a predecessor prefetched
        self.ragged_first_waves = 0
        self.ragged_prefetched_waves = 0
        # speculation stats (nv_llm_spec_* metrics feed)
        self.spec_dispatches = 0       # verify dispatches issued
        self.spec_drafted_tokens = 0   # draft tokens scored
        self.spec_accepted_tokens = 0  # drafts that matched their sample
        self.spec_emitted_tokens = 0   # tokens emitted by verify steps
        # synchronous device→host fetches the engine loop has paid
        # (harvests + admission token fetches): count + MEASURED stall
        # seconds. On the tunneled rig each blocking fetch costs ~131 ms;
        # on a local TPU-VM, microseconds — sampling host_stall_s around
        # a latency window lets tools/serve_bench.py report
        # host-scheduler-only latency net of the measured (not modeled)
        # tunnel tax: an async copy that already landed, or a fetch of an
        # already-host value, measures ~0 by construction (VERDICT r3
        # next #7)
        self.host_roundtrips = 0
        self.host_stall_s = 0.0
        # flight recorder (engine/flight_recorder.py): bounded ring of
        # per-dispatch records + loop-lag probe, dumpable via /debug and
        # llmctl trace dump; per-phase spans feed each request's trace
        from .flight_recorder import FlightRecorder, register_recorder
        self.flight = FlightRecorder()
        register_recorder(self.flight)
        self._flight_prev_stall_s = 0.0
        self._flight_cycle_end = time.monotonic()

    # ------------------------------------------------------------------ jit
    def _compile_jits_pp(self) -> None:
        """Pipeline-parallel program set (parallel/pipeline_parallel.py),
        with the SAME host-facing contracts as the single-device
        programs — prefill(params, kv, tokens, table, start_pos,
        true_len, key, temp, top_k, top_p) → (tok, logprob, kv) and the
        K-step decode scan's (toks [K,B], logprobs [K,B], kv). Keeping
        the contracts identical is what makes every engine path —
        dispatch pipelining, harvest, preemption, lane prefill, chunked
        prefill, engine/replay.py and the multihost followers' stage
        dispatches — compose with pp UNCHANGED: followers and the
        offline replayer re-issue the recorded events through these same
        jits. The single-step _decode_jit has no pp form (EngineConfig
        requires K > 1); spec verify and sp prefill are refused at
        bring-up."""
        from ..parallel.pipeline_parallel import (pp_decode_k_forward,
                                                  pp_prefill_forward)
        statics = self.statics
        mesh = self.mesh
        K = self.cfg.decode_steps_per_dispatch
        seed = self.cfg.seed

        def prefill(params, kv, tokens, block_table, start_pos, true_len,
                    key, temperature, top_k, top_p):
            logits, kv = pp_prefill_forward(
                params, kv, tokens, block_table, start_pos, true_len,
                statics, mesh)
            tok, logprob = sample_tokens(
                logits[None, :], key[None], temperature[None],
                top_k[None], top_p[None])
            return tok[0], logprob[0], kv

        self._prefill_jit = jax.jit(prefill, donate_argnums=(1,))
        self._decode_jit = None

        def decode_k(params, kv, tokens, positions, block_tables,
                     seeds, steps0, temperature, top_k, top_p,
                     planned, planned_mask):
            return pp_decode_k_forward(
                params, kv, tokens, positions, block_tables, seeds,
                steps0, temperature, top_k, top_p, planned,
                planned_mask, statics, mesh, K, seed)

        self._decode_k_jit = jax.jit(decode_k, donate_argnums=(1,))
        self._planned_zero = (jnp.zeros((K, self.cfg.max_num_seqs),
                                        jnp.int32),
                              jnp.zeros((K, self.cfg.max_num_seqs), bool))
        self._merge_jit = jax.jit(
            lambda dev, host, mask: jnp.where(mask, dev, host))
        self._verify_jit = None
        self._ragged_jit = None   # EngineConfig refuses ragged + pp
        self._ragged_row_sampled = False
        self._prefill_sp_jit = None
        self._sp = 1

    def _compile_jits(self) -> None:
        if self.pp > 1:
            self._compile_jits_pp()
            return
        statics = self.statics
        # packed-int4 weights unpack ONCE at the top of every program —
        # a K-step decode dispatch then reads S4 at packed bandwidth
        # (engine/quant.py module docstring; S4 cannot cross the jit
        # boundary on this backend)
        from .quant import unpack_params

        def prefill(params, kv, tokens, block_table, start_pos, true_len,
                    key, temperature, top_k, top_p):
            params = unpack_params(params)
            logits, kv = self.model_mod.prefill_forward(
                params, kv, tokens, block_table, start_pos, true_len, statics)
            tok, logprob = sample_tokens(
                logits[None, :], key[None], temperature[None], top_k[None],
                top_p[None])
            return tok[0], logprob[0], kv

        self._prefill_jit = jax.jit(prefill, donate_argnums=(1,))

        def decode(params, kv, tokens, positions, block_tables,
                   keys, temperature, top_k, top_p):
            params = unpack_params(params)
            logits, kv = self.model_mod.decode_forward(
                params, kv, tokens, positions, block_tables, statics)
            toks, logprobs = sample_tokens(logits, keys, temperature,
                                           top_k, top_p)
            return toks, logprobs, kv

        self._decode_jit = jax.jit(decode, donate_argnums=(1,))

        # K decode steps fused into one dispatch (EngineConfig
        # decode_steps_per_dispatch): the sampled token feeds the next step
        # ON DEVICE, and the host harvests [K, B] tokens once per dispatch.
        K = self.cfg.decode_steps_per_dispatch
        seed = self.cfg.seed

        def decode_k(params, kv, tokens, positions, block_tables,
                     seeds, steps0, temperature, top_k, top_p,
                     planned, planned_mask):
            params = unpack_params(params)
            # planned [K, B] / planned_mask [K, B]: lane-prefill slots feed
            # predetermined prompt tokens per step instead of chaining the
            # sample; the step after a lane's last planned token chains the
            # freshly sampled first generation — prefill→decode transition
            # happens on device, mid-scan.
            def body(carry, xs):
                kv, toks, pos = carry
                keys = make_slot_keys(seed, seeds, steps0 + xs["k"])
                tok_in = jnp.where(xs["pm"], xs["pt"], toks)
                logits, kv = self.model_mod.decode_forward(
                    params, kv, tok_in, pos, block_tables, statics)
                toks2, logprobs = sample_tokens(logits, keys, temperature,
                                                top_k, top_p)
                return (kv, toks2, pos + 1), (toks2, logprobs)

            (kv, _, _), (toks_k, logprobs_k) = jax.lax.scan(
                body, (kv, tokens, positions),
                {"k": jnp.arange(K), "pt": planned, "pm": planned_mask})
            return toks_k, logprobs_k, kv

        self._decode_k_jit = (jax.jit(decode_k, donate_argnums=(1,))
                              if K > 1 else None)
        # device-resident zeros reused by every dispatch with no active
        # lane (the overwhelmingly common case)
        self._planned_zero = (jnp.zeros((K, self.cfg.max_num_seqs),
                                        jnp.int32),
                              jnp.zeros((K, self.cfg.max_num_seqs), bool))
        # pipelined-dispatch input merge: continuing slots chain the
        # previous dispatch's device tokens, fresh slots feed host values
        self._merge_jit = jax.jit(
            lambda dev, host, mask: jnp.where(mask, dev, host))

        # unified ragged dispatch (engine/ragged.py +
        # docs/ragged_attention.md): ONE program serves a flat
        # [ragged_max_tokens] mixed prefill+decode token batch — each
        # slot's contiguous row span scatters its KV and attends masked
        # at its own positions (per-row the decode program's exact
        # math), and each slot samples from its LAST row's logits with
        # the same per-(seed, key_step) key discipline the split
        # programs use. One compiled shape serves every batch mix, so
        # the per-bucket prefill program family never compiles when
        # ragged serving is on.
        #
        # spec_k > 0 compiles the ROW-SAMPLED variant instead (still
        # exactly ONE program): logits and a sample for EVERY token
        # row, each row keyed at its slot's key_step + row offset —
        # the verify program's lockstep-PRNG discipline riding the
        # ragged batch, so speculative spans verify in the same
        # dispatch as prefill chunks and plain decode rows. At the
        # sample row of a non-spec span the key (and hence the token)
        # is identical to the slot-sampled variant by construction:
        # row r of a span keys at key_step + r, the last row at
        # key_step + len - 1 — the lane skew convention.
        self._ragged_jit = None
        self._ragged_row_sampled = False
        if self.cfg.ragged_dispatch:
            Lmax = self.cfg.ragged_max_seq_rows
            self._ragged_row_sampled = self.cfg.spec_k > 0

            if self._ragged_row_sampled:
                def ragged(params, kv, tokens, positions, tables,
                           row_slot, seq_starts, seq_counts,
                           sample_rows, seeds, steps, temperature,
                           top_k, top_p):
                    # steps is [capacity] ROW steps here; the other
                    # sampling params stay per-slot and gather through
                    # row_slot (the trailing trash slot holds zeros)
                    params = unpack_params(params)
                    logits, kv = self.model_mod.ragged_forward(
                        params, kv, tokens, positions, tables,
                        row_slot, seq_starts, seq_counts, sample_rows,
                        statics, max_rows=Lmax, sample_all_rows=True)
                    keys = make_slot_keys(
                        seed, jnp.take(seeds, row_slot), steps)
                    toks, logprobs = sample_tokens(
                        logits, keys,
                        jnp.take(temperature, row_slot),
                        jnp.take(top_k, row_slot),
                        jnp.take(top_p, row_slot))
                    return toks, logprobs, kv
            else:
                def ragged(params, kv, tokens, positions, tables,
                           row_slot, seq_starts, seq_counts,
                           sample_rows, seeds, steps, temperature,
                           top_k, top_p):
                    params = unpack_params(params)
                    logits, kv = self.model_mod.ragged_forward(
                        params, kv, tokens, positions, tables,
                        row_slot, seq_starts, seq_counts, sample_rows,
                        statics, max_rows=Lmax)
                    keys = make_slot_keys(seed, seeds, steps)
                    toks, logprobs = sample_tokens(logits, keys,
                                                   temperature, top_k,
                                                   top_p)
                    return toks, logprobs, kv

            self._ragged_jit = jax.jit(ragged, donate_argnums=(1,))
            # pipelined-dispatch chained-sample merge (ragged form):
            # chained rows take the PREVIOUS dispatch's device token at
            # their slot's recorded sample row; everything else feeds
            # host values. jnp.take covers both variants ([S] slot
            # toks index by slot, [capacity] row toks by sample row).
            self._ragged_merge_jit = jax.jit(
                lambda prev, srows, host, mask: jnp.where(
                    mask, jnp.take(prev, srows), host))

        # speculative verify (engine/spec/, docs/speculative.md): score
        # Tv = spec_k+1 positions per slot in ONE dispatch by flattening
        # [B, Tv] query rows through the SAME paged decode forward.
        # decode_forward scatters each row's input-token KV before
        # attention and row (b, t) attends positions <= pos_b + t, so
        # the rows of one sequence score its draft chain causally —
        # parallel scoring at ~one batched step's weight read instead of
        # Tv sequential steps. Per-position keys are LOCKSTEP with plain
        # decode (steps0 + t == the key_step decode would use at that
        # stream index), so sampled row t is bit-identical to what
        # non-speculative decode would emit there; acceptance is then
        # host-side token equality (spec.accept_lockstep).
        self._verify_jit = None
        if self.cfg.spec_k > 0:
            Tv = self.cfg.spec_k + 1

            def verify(params, kv, tokens, positions, block_tables,
                       seeds, steps0, temperature, top_k, top_p):
                params = unpack_params(params)
                B = tokens.shape[0]
                t_off = jnp.arange(Tv, dtype=jnp.int32)
                flat_tokens = tokens.reshape(B * Tv)
                flat_pos = (positions[:, None] + t_off[None, :]).reshape(
                    B * Tv)
                flat_tables = jnp.repeat(block_tables, Tv, axis=0)
                logits, kv = self.model_mod.decode_forward(
                    params, kv, flat_tokens, flat_pos, flat_tables,
                    statics)
                keys = make_slot_keys(
                    seed, jnp.repeat(seeds, Tv),
                    (steps0[:, None]
                     + t_off.astype(steps0.dtype)[None, :]).reshape(
                         B * Tv))
                toks, logprobs = sample_tokens(
                    logits, keys, jnp.repeat(temperature, Tv),
                    jnp.repeat(top_k, Tv), jnp.repeat(top_p, Tv))
                return (toks.reshape(B, Tv), logprobs.reshape(B, Tv),
                        kv)

            self._verify_jit = jax.jit(verify, donate_argnums=(1,))

        # sequence-parallel long-prompt prefill (ring attention over "sp")
        self._prefill_sp_jit = None
        self._sp = 1
        if self.mesh is not None and self.mesh.shape.get("sp", 1) > 1:
            self._sp = self.mesh.shape["sp"]
            mesh = self.mesh

            def prefill_sp(params, kv, tokens, block_table, true_len,
                           key, temperature, top_k, top_p):
                params = unpack_params(params)
                logits, kv = self.model_mod.prefill_forward_sp(
                    params, kv, tokens, block_table, true_len, statics, mesh)
                tok, logprob = sample_tokens(
                    logits[None, :], key[None], temperature[None],
                    top_k[None], top_p[None])
                return tok[0], logprob[0], kv

            self._prefill_sp_jit = jax.jit(prefill_sp, donate_argnums=(1,))

    # ------------------------------------------------------------ lifecycle
    def ensure_started(self) -> None:
        if self._dead is not None:
            # a fatal loop error already failed every pending request;
            # silently restarting would re-serve them (round-5 review)
            raise RuntimeError(
                f"engine loop died: {self._dead!r} — create a new "
                f"EngineCore") from self._dead
        if self._loop_task is None or self._loop_task.done():
            self._stopping = False
            # worker-thread hooks (disk-evict → remote promotion) need a
            # handle to reach the loop via call_soon_threadsafe
            self._loop = asyncio.get_running_loop()
            # a fresh wakeup event per (re)start: asyncio primitives
            # loop-bind on first wait, and a core restarted on a NEW
            # loop (module-scoped test fixtures, embedders re-running
            # asyncio.run) would otherwise die on the old loop's event
            self._work_event = asyncio.Event()
            self._loop_task = self._loop.create_task(
                self._run_loop(), name="engine-core-loop")
            self.flight.start_lag_probe()

    async def stop(self) -> None:
        self._stopping = True
        self.flight.stop_lag_probe()
        self._work_event.set()
        if self._loop_task is not None:
            try:
                await asyncio.wait_for(self._loop_task, timeout=5)
            except asyncio.TimeoutError:
                self._loop_task.cancel()
            except asyncio.CancelledError:
                # wait_for re-raises the LOOP task's cancellation (process
                # shutdown cancels every task) — that alone must not
                # abort stop(): the remaining cleanup (incl. the host→
                # disk flush) is the point of a graceful stop. Only
                # re-raise when stop() itself was cancelled.
                if not self._loop_task.done():
                    raise
            except Exception:  # noqa: BLE001 — fatal loop death is a
                # supported state (_fail_pending already failed every
                # pending request and logged the exception); stop()'s
                # remaining cleanup must still run
                pass
            self._loop_task = None
        if self._admissions:              # finish deferred admissions
            self._complete_admissions()
        if self._onboard_tasks:           # in-flight onboard preps
            for t in list(self._onboard_tasks):
                t.cancel()
            await asyncio.gather(*list(self._onboard_tasks),
                                 return_exceptions=True)
        if self._stream_tasks:            # in-flight layer-stream onboards
            for t in list(self._stream_tasks):
                t.cancel()
            await asyncio.gather(*list(self._stream_tasks),
                                 return_exceptions=True)
        if self._onboards:                # release reserved onboard blocks
            for req, slot, plan, _prepped, _rvals in self._onboards:
                self.slots[slot] = None
                self.kv_manager.pool.release(plan.all_blocks)
                self.kv_manager.host_pool.unpin(plan.host_slots)
                if plan.disk_hashes:
                    self.disk_store.unpin(plan.disk_hashes)
                if plan.remote_hashes:
                    self.remote_store.unpin(plan.remote_hashes)
                self._finish_request(req, FinishReason.CANCELLED)
            self._onboards = []
        if self._pending is not None:     # drain the pipelined dispatch
            self._harvest(self._pending)
            self._pending = None
        if self._ragged_pending is not None:  # the ragged form of same
            prev, self._ragged_pending = self._ragged_pending, None
            self._harvest_ragged(prev)
        if self.offload_engine is not None:
            await self.offload_engine.stop()
        if self.spill_engine is not None:
            # graceful persist: everything still host-resident goes to
            # disk so the next engine pointed at kv_disk_dir warm-starts
            # with the full working set (kill -9 keeps only what the
            # write-behind pump had already acknowledged)
            try:
                await asyncio.wait_for(self.flush_host_to_disk(),
                                       timeout=30)
            except asyncio.TimeoutError:
                logger.warning("host→disk flush timed out on stop")
            await self.spill_engine.stop()
            self.disk_store.close()
        if self.remote_spill_engine is not None:
            # drain AFTER the disk pump: the flush above may have forced
            # disk evictions whose promotion jobs are still queued
            await self.remote_spill_engine.stop()
            self.remote_store.close()

    @property
    def wire_kv_heads(self) -> int:
        """Head count for the head-major KV wire format (block_copy
        to/from_wire_format): int8 pools and MLA latent pools ship whole
        rows as ONE opaque "head" (in-row scales / latent+rope lanes
        have no head structure to split), so handoff/offload round trips
        are bit-exact; full-precision llama pools use the real KV head
        count (which the dst-tp>src-tp reshard slices per rank)."""
        return (1 if self.cfg.kv_quantization != "none" or self.is_mla
                else self.model_cfg.num_kv_heads)

    def _check_kv_payload_layout(self, lanes: int, dtype,
                                 kind: str) -> None:
        """A disagg KV payload must match this pool's row layout exactly:
        same lane width (int8 rows bundle their tp-shard scale groups, so
        width also encodes the prefill engine's tp) and same dtype.
        DEVICE-plane payloads with a differing kv_quantization were
        already repacked (_maybe_repack_kv_payload) before this check;
        anything still mismatched here — wire-plane cross-quant, int8
        across differing tp — fails loudly."""
        pool = next(iter(self.kv.values()))   # key-agnostic: llama
        # pools are {"k","v"}, MLA latent pools are {"kv"}
        if lanes != pool.shape[-1] or np.dtype(dtype) != pool.dtype:
            raise ValueError(
                f"disagg {kind} KV payload layout mismatch: payload rows "
                f"have {lanes} lanes of {np.dtype(dtype)}, this pool has "
                f"{pool.shape[-1]} lanes of {pool.dtype} — prefill and "
                f"decode engines must share kv_quantization (and tp, for "
                f"int8 pools)")

    def _check_layer_stream_layout(self, manifest) -> None:
        """Layer-stream manifests announce geometry before any bulk
        frame: per-layer wire shape [H, n, bs, D] plus layer count and
        dtype — validated against the pool like a monolithic payload,
        plus the layer axis (a stream describing a different depth could
        otherwise scatter past the pool's layer extent)."""
        import ml_dtypes  # noqa: F401 — registers bf16 et al. for np.dtype
        h, _n, bs, d = (manifest.shape + [0, 0, 0, 0])[:4]
        self._check_kv_payload_layout(h * d, manifest.dtype, "wire")
        pool = next(iter(self.kv.values()))
        if manifest.num_layers != pool.shape[0]:
            raise ValueError(
                f"disagg wire KV payload layout mismatch: layer stream "
                f"announces {manifest.num_layers} layers, this pool has "
                f"{pool.shape[0]}")
        if bs != self.cfg.kv_block_size:
            raise ValueError(
                f"disagg wire KV payload layout mismatch: layer stream "
                f"block size {bs} != pool block size "
                f"{self.cfg.kv_block_size}")

    def _maybe_repack_kv_payload(self, pc):
        """Scale-aware repack of a DEVICE-plane disagg payload whose
        kv_quantization differs from this pool's (round 5, VERDICT r4
        item 4; reference analog: block_copy.cu's cross-layout reshard,
        lib/llm/src/kernels/block_copy.cu:558-728): int8 payload rows
        dequantize, bf16 rows requantize into THIS pool's group/section
        layout — all on device, before admission. Same-layout payloads
        pass through untouched (bit-exact as before). Still refused:
        int8 payloads whose tp-shard GROUP COUNT differs from this
        pool's (a group re-split must reshuffle head ownership), and
        every wire-plane mismatch (the wire is the compatibility
        fallback; its head-major format carries no scale structure to
        convert in place)."""
        import jax.numpy as jnp

        from ..engine.attention import (dequant_kv_rows,
                                        dequant_kv_rows_sections,
                                        kv_row_groups, quantize_kv_rows,
                                        quantize_kv_rows_sections)
        pool = next(iter(self.kv.values()))
        want_w, want_dt = pool.shape[-1], pool.dtype
        sample = next(iter(pc.stacked.values()))
        have_w, have_dt = sample.shape[-1], sample.dtype
        if have_w == want_w and have_dt == want_dt:
            return pc
        src_q = have_dt == jnp.int8
        dst_q = want_dt == jnp.int8
        if not (src_q or dst_q):
            return pc          # width-only mismatch: the tp reshard path
        if self.is_mla:
            sections = (self.model_cfg.kv_lora_rank,
                        self.model_cfg.qk_rope_head_dim)
            C = sum(sections)
        else:
            sections = None
            C = self.model_cfg.num_kv_heads * self.model_cfg.head_dim
        if src_q and dst_q:
            raise ValueError(
                f"disagg KV repack across two int8 layouts ({have_w} -> "
                f"{want_w} lanes) is not supported: the scale GROUP "
                f"counts encode each engine's tp, and re-splitting "
                f"groups must reshuffle head ownership")

        def convert(arr):
            lead = arr.shape[:-1]
            rows = arr.reshape((-1, arr.shape[-1]))
            if src_q:
                mid = jnp.bfloat16 if dst_q else want_dt
                rows = (dequant_kv_rows_sections(rows, sections, mid)
                        if sections is not None
                        else dequant_kv_rows(rows, C, mid))
            if dst_q:
                x = rows[..., :C].astype(jnp.bfloat16)
                rows = (quantize_kv_rows_sections(x, sections)
                        if sections is not None
                        else quantize_kv_rows(
                            x, kv_row_groups(want_w, C)))
            return rows.reshape(lead + (rows.shape[-1],))

        # jit per payload layout (ADVICE r5): the eager version walked
        # every row un-fused on the event loop; the jitted dispatch
        # returns immediately and the caller awaits readiness off-loop
        key = (have_w, str(have_dt))
        fn = self._repack_jits.get(key)
        if fn is None:
            fn = jax.jit(convert)
            self._repack_jits[key] = fn
        import dataclasses as _dc
        new_stacked = {k: fn(v) for k, v in pc.stacked.items()}
        logger.info("disagg KV payload repacked %s/%d -> %s/%d lanes "
                    "for %s", have_dt, have_w, want_dt,
                    new_stacked[next(iter(new_stacked))].shape[-1],
                    pc.request_id)
        return _dc.replace(pc, stacked=new_stacked)

    # ------------------------------------------------------------- frontend
    async def submit(self, req: EngineRequest) -> None:
        if req.precomputed is not None:
            # validate the payload layout HERE, synchronously: the caller
            # gets the error; a raise inside the engine loop's admission
            # path would kill the loop and hang every in-flight request
            from ..llm.kv_transport import DeviceKvPayload
            pc = req.precomputed
            if isinstance(pc, DeviceKvPayload):
                repacked = self._maybe_repack_kv_payload(pc)
                if repacked is not pc:
                    # await device completion in an executor so a long
                    # cross-quant repack never stalls the event loop (and
                    # with it the in-flight decode schedule) — ADVICE r5
                    await asyncio.to_thread(
                        jax.block_until_ready,
                        list(repacked.stacked.values()))
                req.precomputed = pc = repacked
                sample = next(iter(pc.stacked.values()))
                self._check_kv_payload_layout(sample.shape[-1],
                                              sample.dtype, "device")
            else:
                from ..llm.kv.stream import LayerStreamPayload
                if isinstance(pc, LayerStreamPayload):
                    # layer stream: the manifest announced the geometry
                    # up front — validate before any frame is scattered
                    self._check_layer_stream_layout(pc.manifest)
                else:
                    sample = next(iter(pc.values.values()))
                    self._check_kv_payload_layout(
                        sample.shape[1] * sample.shape[4], sample.dtype,
                        "wire")
        if req.trace is None:
            # bind the ambient request trace (frontend-opened for
            # in-process pipelines, ingress-opened child for the request
            # plane) so engine phases land in the fleet tree
            from ..runtime.tracing import current_trace
            req.trace = current_trace()
        self.ensure_started()
        self._inflight_reqs[id(req)] = req
        await self.waiting.put(req)
        self._work_event.set()

    def reannounce_kv(self) -> int:
        """Replay every stored-block announcement into the KV event
        publisher — the lease-reclaim recovery hook (KNOWN_ISSUES
        kv-router staleness): after a transient lease expiry the router
        wiped this worker's radix index; the reclaim replays discovery
        keys but not content events, so the pool re-announces them."""
        if self.kv_event_publisher is None:
            return 0
        n = self.kv_manager.pool.reannounce(
            self.kv_event_publisher.publish_stored)
        # disk (G3) bring-up: a warm-started store holds prefixes the
        # device pool has never seen — announce them tier-tagged so the
        # router's radix index can route matching prompts here for a
        # promote instead of a cold recompute elsewhere
        if self.disk_store is not None:
            for h, th, ph in self.disk_store.registered_entries():
                if not self.kv_manager.pool.peek_prefix([h]):
                    self.kv_event_publisher.publish_stored(
                        -1, h, th, ph, tier="disk")
                    n += 1
        # remote (G4) object tier: durable blocks THIS worker can fetch
        # back (peer-held hashes are the peer's to announce)
        if self.remote_store is not None:
            for h, th, ph in self.remote_store.registered_entries():
                if (not self.kv_manager.pool.peek_prefix([h])
                        and not (self.disk_store is not None
                                 and self.disk_store.contains(h))):
                    self.kv_event_publisher.publish_stored(
                        -1, h, th, ph, tier="remote")
                    n += 1
        return n

    async def flush_host_to_disk(self) -> int:
        """Persist every host-resident block to the disk tier NOW and
        wait for the writes to be acknowledged (fsync'd manifest) — the
        llmctl ``kv flush`` barrier, also run on graceful stop(). Returns
        the number of blocks newly offered to the spill queue."""
        if self.spill_engine is None:
            return 0
        from ..llm.kv.diskstore import SpillJob
        host = self.kv_manager.host_pool
        n = 0
        for h, th, ph, slot in host.resident_entries():
            if self.disk_store.contains(h):
                continue
            if self.spill_engine.offer(SpillJob(
                    seq_hash=h, tokens_hash=th, parent_hash=ph,
                    values=host.row_copy(slot))):
                n += 1
        await self.spill_engine.drain()
        return n

    def _dma_copies_per_wave(self) -> float:
        """Decode-DMA issues per wave over the CURRENT batch state — the
        host-side mirror of the kernel's wave walk (attention.
        dma_copy_counts), fed to nv_llm_kv_attn_dma_copies_per_wave.
        chunk× on a fully fragmented pool, 1-2 on a contiguous one."""
        from .attention import dma_copy_counts
        seq_lens = np.where(
            np.array([s is not None and s.ready for s in self.slots]),
            self._positions + 1, 0).astype(np.int32)
        if not seq_lens.any():
            return 0.0
        counts = dma_copy_counts(
            self._block_tables, seq_lens,
            block_size=self.cfg.kv_block_size,
            pool_blocks=self.cfg.num_kv_blocks,
            dual_stream=not self.is_mla,
            coalesce=self.cfg.kv_contig_alloc)
        return counts["copies_per_wave"]

    def metrics(self) -> ForwardPassMetrics:
        active = sum(1 for s in self.slots if s is not None)
        total_blocks = self.cfg.num_kv_blocks - 1
        used = self.kv_manager.pool.used_blocks
        host = self.kv_manager.host_pool
        disk = self.disk_store
        pool = self.kv_manager.pool
        tier_kw = {
            "kv_frag_ratio": pool.frag_ratio(),
            "kv_contig_runs": pool.contig_runs,
            "kv_contiguity_ratio": pool.contiguity_ratio(),
            "kv_defrag_moves_total": pool.defrag_moves_total,
            "attn_dma_copies_per_wave": self._dma_copies_per_wave(),
        }
        if host is not None:
            tier_kw.update(
                host_stored_total=host.stored_blocks_total,
                host_evicted_total=host.evicted_blocks_total,
                host_hit_rate=host.hit_rate())
        if self.offload_engine is not None:
            tier_kw.update(offload_dropped_jobs_total=self
                           .offload_engine.dropped_jobs_total)
        if self.cfg.ragged_dispatch:
            # ragged dispatch (docs/ragged_attention.md): how full each
            # unified dispatch runs, how often prefill and decode share
            # one, and the split-path dispatches the packing saved
            tier_kw.update(
                ragged_fill_ratio=(
                    self.ragged_rows_total
                    / (self.ragged_dispatches
                       * self.cfg.ragged_max_tokens)
                    if self.ragged_dispatches else 0.0),
                ragged_mixed_ratio=(
                    self.ragged_mixed_dispatches / self.ragged_dispatches
                    if self.ragged_dispatches else 0.0),
                ragged_dispatches_saved_total=self.ragged_dispatches_saved,
                # cross-sequence wave prefetch: first waves a
                # predecessor's last wave covered (host mirror of the
                # kernel's parity chain) / draft rows that rode ragged
                ragged_prefetch_hit_ratio=(
                    self.ragged_prefetched_waves
                    / self.ragged_first_waves
                    if self.ragged_first_waves else 0.0),
                ragged_spec_rows_total=self.ragged_spec_rows)
        if self.pp > 1:
            from ..parallel.pipeline_parallel import (
                pp_bubble_fraction, pp_dispatch_utilization)
            K = self.cfg.decode_steps_per_dispatch
            tier_kw.update(
                pp_stages=self.pp,
                pp_microbatch=self.B // self.pp,
                pp_utilization=pp_dispatch_utilization(self.pp, K),
                pp_bubble_fraction=pp_bubble_fraction(self.pp, K))
        if disk is not None:
            tier_kw.update(
                disk_used_blocks=disk.used_blocks,
                disk_capacity_blocks=disk.capacity,
                disk_stored_total=disk.stored_blocks_total,
                disk_evicted_total=disk.evicted_blocks_total,
                disk_hit_rate=disk.hit_rate(),
                disk_bytes_used=disk.bytes_used,
                disk_spill_dropped_total=self
                .spill_engine.dropped_jobs_total,
                disk_spill_shed_total=self
                .spill_engine.shed_writes_total)
        if self.remote_store is not None or self.kv_fabric is not None:
            # remote (G4) fabric: tier occupancy + the measured link
            # model the router's NetKV scoring consumes (kv_router/
            # scoring.py network_adjusted_overlap)
            tier_kw.update(prefill_published_blocks_total=self
                           .prefill_published_blocks)
            if self.kv_fabric is not None:
                tier_kw.update(self.kv_fabric.metrics())
            else:
                rs = self.remote_store
                tier_kw.update(
                    remote_used_blocks=rs.used_blocks,
                    remote_capacity_blocks=rs.capacity,
                    remote_peer_blocks=rs.peer_block_count(),
                    remote_stored_total=rs.stored_blocks_total,
                    remote_hit_rate=rs.hit_rate(),
                    remote_fetch_failures_total=rs.fetch_failures_total,
                    remote_admission_rejects_total=rs
                    .admission_rejects_total)
        if self.tenant_admitted:
            # per-tenant serving stats (llm/tenancy.py; the
            # nv_llm_tenant_* labeled-gauge feed): admitted requests,
            # resident KV blocks across tiers, and prefix hit rate
            ledger = self.tenancy
            tier_kw["tenant_stats"] = {
                t: {"admitted": n,
                    "throttled": 0,
                    "kv_blocks": (ledger.blocks(t)
                                  if ledger is not None else 0),
                    "hit_rate": (self.tenant_hits.get(t, 0)
                                 / max(self.tenant_queries.get(t, 0), 1))}
                for t, n in sorted(self.tenant_admitted.items())}
        _stream_wall = (self.disagg_stream_hidden_s
                        + self.disagg_stream_exposed_s)
        tier_kw.update(
            # streaming layer-wise KV handoff (llm/kv/stream.py): the
            # nv_llm_disagg_stream_* gauge feed. disagg_stream_layers is
            # the MEASURED streaming depth the router's overlap credit
            # prices with (scoring.network_adjusted_overlap) — 0 until
            # the first streamed admission proves the plane is live.
            disagg_stream_layers_total=self.disagg_stream_layers_scattered,
            disagg_stream_fallbacks_total=self.disagg_stream_fallbacks,
            disagg_stream_overlap_ratio=(
                self.disagg_stream_hidden_s / _stream_wall
                if _stream_wall > 0 else 0.0),
            disagg_stream_layers=(
                self.model_cfg.num_layers
                if self.disagg_stream_admits > 0 else 0))
        from ..runtime.tracing import tracer as _tracer
        return ForwardPassMetrics(
            requests_cancelled_total=self.requests_cancelled_total,
            requests_deadline_exceeded_total=self
            .requests_deadline_exceeded_total,
            kv_bytes_per_block=self.kv_bytes_per_block(),
            kv_block_size=self.cfg.kv_block_size,
            prefill_tok_per_s=self.measured_prefill_tok_per_s(),
            trace_dropped_log_lines_total=_tracer.dropped_log_lines,
            loop_lag_ms=self.flight.loop_lag_ms,
            loop_lag_max_ms=self.flight.loop_lag_max_ms,
            **tier_kw,
            request_active_slots=active,
            request_total_slots=self.B,
            kv_active_blocks=used,
            kv_total_blocks=total_blocks,
            num_requests_waiting=self.waiting.qsize(),
            gpu_cache_usage_perc=used / max(total_blocks, 1),
            gpu_prefix_cache_hit_rate=self.kv_manager.pool.hit_rate(),
            spec_drafted_total=self.spec_drafted_tokens,
            spec_accepted_total=self.spec_accepted_tokens,
            spec_acceptance_rate=(
                self.spec_accepted_tokens / self.spec_drafted_tokens
                if self.spec_drafted_tokens else 0.0),
            spec_accepted_per_step=(
                self.spec_accepted_tokens / self.spec_dispatches
                if self.spec_dispatches else 0.0),
        )

    # ------------------------------------------------------------ scheduler
    def _free_slot_index(self) -> int:
        for i, s in enumerate(self.slots):
            if s is None:
                return i
        return -1

    def _blocks_needed(self, n_tokens: int) -> int:
        bs = self.cfg.kv_block_size
        return (n_tokens + bs - 1) // bs

    async def _run_loop(self) -> None:
        try:
            await self._run_loop_inner()
        except asyncio.CancelledError:
            raise
        except Exception as e:   # noqa: BLE001 — fatal loop error
            # Round-5 postmortem: an exception here used to kill the
            # loop task SILENTLY, leaving every pending request awaiting
            # an out_queue forever (observed as a test hang, not a
            # failure). Fail them all loudly instead, then re-raise.
            logger.exception("engine loop died; failing %d active + %d "
                             "waiting requests", 
                             sum(1 for x in self.slots if x is not None),
                             self.waiting.qsize())
            self._fail_pending(e)
            raise

    def _fail_pending(self, exc: BaseException) -> None:
        from ..llm.protocols.common import FinishReason
        self._dead = exc
        for rid, req in list(self._inflight_reqs.items()):
            req.out_queue.put_nowait((FINISH_SENTINEL,
                                      FinishReason.ERROR))
        self._inflight_reqs.clear()
        # free every admitted request's KV allocation (ADVICE r5): the
        # core itself is unrecoverable (_dead gates ensure_started), but
        # the pool object may outlive it — a recovery path that rebuilds
        # the loop around the same kv_manager must not inherit leaked
        # refcounts. Slot release, not _release_slot: no offload
        # write-back or sampler-state care is owed to a dead loop.
        for req in self.slots:
            if req is not None and req.blocks:
                self.kv_manager.pool.release(req.blocks)
                req.blocks = []
        for req, _slot, plan, _prepped, _rvals in self._onboards:
            self.kv_manager.pool.release(plan.all_blocks)
            if self.kv_manager.host_pool is not None:
                self.kv_manager.host_pool.unpin(plan.host_slots)
            if plan.disk_hashes and self.disk_store is not None:
                self.disk_store.unpin(plan.disk_hashes)
            if plan.remote_hashes and self.remote_store is not None:
                self.remote_store.unpin(plan.remote_hashes)
        self._onboards = []
        # clear scheduler state so nothing can be re-served even if a
        # caller pokes internals
        self.slots = [None] * len(self.slots)
        while not self.waiting.empty():
            try:
                self.waiting.get_nowait()
            except asyncio.QueueEmpty:
                break

    async def _run_loop_inner(self) -> None:
        # the loop task is created from the FIRST submit()'s context and
        # would inherit that request's ambient trace forever — detach;
        # per-request trace identity rides EngineRequest.trace instead
        from ..runtime.tracing import detach_trace
        detach_trace()
        logger.info("engine loop starting: %d slots, %d KV blocks, block=%d",
                    self.B, self.cfg.num_kv_blocks, self.cfg.kv_block_size)
        while not self._stopping:
            progressed = False
            # 0) opportunistic KV compaction: only when no admission is
            # queued and no dispatch is un-harvested (the pass inserts
            # one small device copy ahead of the next decode dispatch)
            if (self.waiting.empty() and self._pending is None
                    and self._ragged_pending is None):
                self._maybe_defrag()
            # 0.5) cancellation/deadline sweep: vacate slots and purge
            # the waiting queue for requests whose client stopped caring
            # — one loop tick, no waiting for the next emit
            if self._sweep_cancelled():
                progressed = True
            # 1) admit waiting work into free slots
            while not self.waiting.empty():
                slot = self._free_slot_index()
                if slot < 0:
                    break
                req: EngineRequest = self.waiting.get_nowait()
                if req.cancelled:
                    self._finish_request(req, FinishReason.CANCELLED)
                    continue
                if not self._try_admit(req, slot):
                    # not enough KV blocks — put it back and stop admitting
                    self.waiting._queue.appendleft(req)  # type: ignore[attr-defined]
                    break
                progressed = True
            # 2) run one decode step for whatever is active and ready
            if any(s is not None and s.ready for s in self.slots):
                self._decode_step()
                progressed = True
            elif self._pending is not None:
                # all requests finished mid-harvest with a chained dispatch
                # still in flight: drain it so the dead requests and device
                # buffers don't sit retained across an idle period
                self._harvest(self._pending)
                self._pending = None
                progressed = True
            elif self._ragged_pending is not None:
                # same drain for a pipelined ragged dispatch
                prev, self._ragged_pending = self._ragged_pending, None
                self._harvest_ragged(prev)
                progressed = True
            # 3) deferred admissions: their async fetch overlapped step 2
            if self._admissions:
                self._complete_admissions()
                progressed = True
            # 4) host-tier onboards whose off-thread prep finished
            if self._onboards:
                self._complete_onboards()
                progressed = True
            if not progressed:
                self._work_event.clear()
                try:
                    await asyncio.wait_for(self._work_event.wait(), timeout=0.5)
                except asyncio.TimeoutError:
                    pass
            else:
                await asyncio.sleep(0)  # let producers/consumers run
        logger.info("engine loop stopped")

    # --------------------------------------------------------------- defrag
    def _maybe_defrag(self) -> bool:
        """Background compaction (docs/kv_layout.md): when fragmentation
        exceeds EngineConfig.kv_defrag_threshold, migrate the worst-
        fragmented resident sequence's movable block suffix into a free
        run — an on-device gather+scatter (block_copy.move_blocks)
        followed by pool.relocate, so hash registrations and refcounts
        follow the blocks and the old ids coalesce back into the
        free-run index. Constraints: only blocks owned by ONE sequence
        move (shared prefix-hit blocks stay put), targets come from the
        UNINIT free space only (never evicts cached prefixes), and the
        pass is skipped while a replay recorder is attached (the copy
        is a device program the follower/replay streams don't carry).
        Rate-limited to one pass per 64 decode steps."""
        cfg = self.cfg
        if (not cfg.kv_contig_alloc or cfg.kv_defrag_threshold <= 0
                or self.recorder is not None
                or self._step - self._defrag_last_step < 64):
            return False
        pool = self.kv_manager.pool
        thr = cfg.kv_defrag_threshold
        pool_frag = pool.frag_ratio()
        best = None   # (runs, seq_frag, slot, suffix_start, suffix)
        for i, req in enumerate(self.slots):
            if req is None or not req.ready or len(req.blocks) < 2:
                continue
            rcs = pool.refcounts(req.blocks)
            j = len(req.blocks)
            while j > 0 and rcs[j - 1] == 1:
                j -= 1
            suffix = req.blocks[j:][:cfg.kv_defrag_max_blocks]
            if len(suffix) < 2:
                continue
            runs = pool.count_runs(suffix)
            if runs < 2:
                continue
            seq_frag = (runs - 1) / (len(suffix) - 1)
            if (pool_frag <= thr and seq_frag <= thr):
                continue
            if best is None or runs > best[0]:
                best = (runs, seq_frag, i, j, suffix)
        if best is None or pool.free_uninit_blocks < len(best[4]):
            return False
        runs, _seq_frag, slot, j, old = best
        new = pool.alloc_uninit(len(old))
        if new is None:
            return False
        if pool.count_runs(new) >= runs:
            pool.release(new)       # no layout win — don't thrash
            return False
        from .block_copy import move_blocks
        self.kv = move_blocks(self.kv, old, new, cfg.kv_block_size)
        pool.relocate(zip(old, new))
        req = self.slots[slot]
        req.blocks[j:j + len(old)] = new
        self._block_tables[slot, :] = 0
        self._block_tables[slot, :len(req.blocks)] = req.blocks
        self.defrag_passes += 1
        self._defrag_last_step = self._step
        self.flight.record("defrag", moved=len(old), runs_before=runs)
        logger.debug("defrag: slot %d moved %d blocks (%d runs → %d), "
                     "pool frag %.2f", slot, len(old), runs,
                     pool.count_runs(new), pool_frag)
        return True

    def _sweep_cancelled(self) -> bool:
        """One pass of the end-to-end cancellation contract
        (docs/chaos.md): cancelled/deadline-exceeded requests leave the
        waiting queue before ever taking a slot, and READY slots are
        vacated immediately — blocks released, offload write-back still
        honored via _release_slot. Slots with an un-harvested dispatch
        in flight are left to their harvest's own cancel check (same
        loop tick); non-ready slots (onboard in flight) resolve at
        _complete_onboards."""
        progressed = False
        if not self.waiting.empty():
            survivors: List[EngineRequest] = []
            while not self.waiting.empty():
                try:
                    r: EngineRequest = self.waiting.get_nowait()
                except asyncio.QueueEmpty:
                    break
                if r.cancelled:
                    self._finish_request(r, FinishReason.CANCELLED)
                    progressed = True
                else:
                    survivors.append(r)
            for r in survivors:
                self.waiting.put_nowait(r)
        if self._pending is None and self._ragged_pending is None:
            for req in list(self.slots):
                if req is not None and req.ready and req.cancelled:
                    self._release_slot(req)
                    self._finish_request(req, FinishReason.CANCELLED)
                    progressed = True
        return progressed

    # ---------------------------------------------------------------- admit
    def _try_admit(self, req: EngineRequest, slot: int) -> bool:
        plan = self.kv_manager.prepare_prefill(req.prompt, seq=req.seq,
                                               cold=req.cold_admission)
        if plan is None:
            return False
        if req.tenant:
            # per-tenant admission + prefix-hit accounting (the
            # nv_llm_tenant_* gauge feed; llm/tenancy.py)
            t = req.tenant
            self.tenant_admitted[t] = self.tenant_admitted.get(t, 0) + 1
            self.tenant_queries[t] = (self.tenant_queries.get(t, 0)
                                      + len(plan.all_blocks))
            self.tenant_hits[t] = (self.tenant_hits.get(t, 0)
                                   + len(plan.hit_blocks)
                                   + len(plan.host_slots)
                                   + len(plan.disk_hashes)
                                   + len(plan.remote_hashes))
        if len(plan.all_blocks) > self.M:
            # longer than a block table row — reject rather than overflow
            # the table (external prompts are length-checked upstream, but
            # preemption-grown prompts and misconfigured callers land here)
            self.kv_manager.abort_plan(plan)
            self._finish_request(req, FinishReason.LENGTH)
            return True
        if plan.host_slots or plan.disk_hashes or plan.remote_hashes:
            # host/disk/remote-tier hits: the wire→block-major copies
            # (and the disk file reads / fabric fetches) are pure host
            # work — run them OFF the loop (reference overlaps its tier
            # copies with compute via CopyStream, kv/layer.rs; our
            # analog is a thread + deferred admission) and finish
            # admitting when ready
            self._start_onboard(req, slot, plan)
            return True
        return self._admit_with_plan(req, slot, plan, None)

    def _emit_kv_store(self, items: list) -> None:
        """Offload-pump commit hook → the recorder stream. Multihost
        followers AND the offline replayer mirror the store (gathering
        the same device blocks from their own bit-identical KV), making
        host-tier restores replayable in both
        (replay.exec_kv_store_event). ``spills`` lists the evicted
        hashes this batch's host evictions handed to the disk spill
        queue (the enqueue-accept decision, made synchronously inside
        host_pool.store via _on_host_evict) — followers stage a copy of
        exactly those rows so the later "kv_disk_store" commit can apply
        the leader's literal placements from bit-identical bytes."""
        spills, self._pending_spills = self._pending_spills, []
        if self.recorder is not None:
            self.recorder.rec("kv_store", items=items, spills=spills)

    # ------------------------------------------------------- disk (G3) tier
    def _on_host_evict(self, seq_hash: int, tokens_hash, parent_hash,
                       values: dict) -> None:
        """Host-pool eviction hook (fires on the loop, inside the offload
        pump's store, with a fresh copy of the arena row): offer the
        block to the disk spill queue — async write-behind, never
        stalling the loop; saturation drops with a counter."""
        from ..llm.kv.diskstore import SpillJob
        accepted = self.spill_engine.offer(SpillJob(
            seq_hash=seq_hash, tokens_hash=tokens_hash,
            parent_hash=parent_hash, values=values))
        if accepted:
            self._pending_spills.append(seq_hash)

    def _emit_kv_disk_store(self, items: list) -> None:
        """Spill-pump commit hook: [(hash, tokens_hash, parent, evicted)]
        per durably-acknowledged disk put. Streams the literal placement
        decisions to multihost followers (replay.exec_kv_disk_store_event
        applies them from the staged row copies) and announces the
        spilled prefixes to the router's radix index with a "disk" tier
        tag — unless the hash is still device-registered (its device
        announce stands at full weight)."""
        if self.recorder is not None:
            self.recorder.rec("kv_disk_store", items=items)
        pub = self.kv_event_publisher
        if pub is None:
            return
        for h, th, ph, evicted in items:
            for gone in evicted:
                self._publish_tier_removed(gone)
            if not self.kv_manager.pool.peek_prefix([h]):
                pub.publish_stored(-1, h, th, ph, tier="disk")

    # ---------------------------------------------------- remote (G4) tier
    def _on_disk_evict(self, seq_hash: int, tokens_hash, parent_hash,
                       values: dict) -> None:
        """Disk-tier capacity-eviction hook: offer the block to the
        remote promotion pump (object-store write-behind) so a prefix
        leaving this worker's disk survives in the fleet. Fires on the
        spill pump's WORKER thread (inside DiskKvStore.put's eviction) —
        hop to the loop before touching the asyncio queue."""
        if self.remote_spill_engine is None or self._loop is None:
            return
        from ..llm.kv.diskstore import SpillJob
        job = SpillJob(seq_hash=seq_hash, tokens_hash=tokens_hash,
                       parent_hash=parent_hash, values=values)
        try:
            self._loop.call_soon_threadsafe(self._offer_remote_spill, job)
        except RuntimeError:
            pass                           # loop already closed (shutdown)

    def _offer_remote_spill(self, job) -> None:
        self.remote_spill_engine.offer(job)

    def _emit_kv_remote_store(self, items: list) -> None:
        """Remote promotion commit hook: [(hash, tokens_hash, parent,
        evicted)] per durably-acknowledged object put. Announces the
        promoted prefixes tier="remote" — unless a warmer tier still
        holds the hash (its announce stands at a better weight). The
        remote tier is NOT mirrored to multihost followers: the object
        store is fleet-shared state, not per-rank state, and followers
        never run the admission cascade."""
        pub = self.kv_event_publisher
        if pub is None:
            return
        host = self.kv_manager.host_pool
        for h, th, ph, evicted in items:
            for gone in evicted:
                self._publish_tier_removed(gone)
            if self.kv_manager.pool.peek_prefix([h]):
                continue
            if host is not None and host.contains(h):
                continue
            if self.disk_store is not None and self.disk_store.contains(h):
                continue
            pub.publish_stored(-1, h, th, ph, tier="remote")

    def enable_tenancy(self, ledger=None) -> None:
        """Attach a per-tenant block ledger (llm/tenancy.py
        TenantBlockLedger) and thread it through every present KV tier:
        device pool eviction prefers over-quota tenants' blocks, and
        the host/disk/remote stores account + quota-prefer likewise.
        Idempotent; untenanted engines never pay for any of it."""
        from ..llm.tenancy import TenantBlockLedger
        if ledger is None:
            ledger = self.tenancy or TenantBlockLedger()
        self.tenancy = ledger
        self.kv_manager.pool.tenancy = ledger
        self.kv_manager.tenancy = ledger
        host = self.kv_manager.host_pool
        if host is not None:
            host.tenancy = ledger
        if self.disk_store is not None:
            self.disk_store.tenancy = ledger
        if self.remote_store is not None:
            self.remote_store.tenancy = ledger

    def attach_kv_fabric(self, fabric) -> None:
        """Wire an attached fleet fabric (llm/kv/fabric.py KvFabric):
        its RemoteKvStore becomes the cascade's G4 rung. Engine-side
        construction (kv_remote_dir) may already have built an
        object-backed store — the fabric wraps that same store, so this
        is idempotent on the manager side."""
        self.kv_fabric = fabric
        self.remote_store = fabric.store
        self.kv_manager.remote_store = fabric.store

    def kv_bytes_per_block(self) -> int:
        """Wire bytes one KV block moves (all layers/streams) — the
        admission gate's and the router's transfer-cost unit."""
        total = sum(int(np.prod(v.shape)) * v.dtype.itemsize
                    for v in self.kv.values())
        return max(total // max(self.cfg.num_kv_blocks, 1), 1)

    def measured_prefill_tok_per_s(self) -> float:
        """MEASURED prefill rate — the recompute side of the fabric's
        fetch-vs-recompute model. AGE-WEIGHTED (llm/kv/fabric.
        PrefillRateEstimator): the first admissions — which include XLA
        compile on a young engine — are excluded, and later ones decay-
        average, so the gate prices recompute at the warmed-up rate.
        0.0 while young/unknown (the gate treats unknown as admit)."""
        return self.prefill_rate_estimator.rate()

    async def publish_prefix_to_remote(self, seq) -> int:
        """Prefill-as-a-Service publish (components/prefill_service.py):
        push every still-registered FULL block of ``seq``'s chain from
        the device pool to the durable remote (object) tier, keyed by
        the same chained hashes every other tier uses. Any decode fleet
        pointed at the same object root then admits the prefix through
        the existing cascade, priced by its own measured AdmissionGate
        crossover — no new decode path, no handoff stream.

        The device gather dispatches on the loop (ordered before any
        later donated KV update by the single device stream); the host
        fetch, npz pack, and object puts run off-thread (DL001: file
        I/O never rides the engine loop). Already-resident objects are
        skipped (content-addressed no-op). Returns blocks published."""
        rs = self.remote_store
        if rs is None or rs.object is None:
            return 0
        pool = self.kv_manager.pool
        # longest still-registered run of the chain, with refcount holds
        # so the blocks cannot be evicted under the gather (works on both
        # the Python and the native C++ pool)
        bids = pool.match_prefix(seq.sequence_hashes)
        if not bids:
            return 0
        entries = [(bids[j], seq.sequence_hashes[j], seq.block_hashes[j],
                    seq.sequence_hashes[j - 1] if j > 0 else None)
                   for j in range(len(bids))]
        try:
            from .block_copy import fetch_wire, gather_blocks_dispatch
            stacked = gather_blocks_dispatch(
                self.kv, [bid for bid, _h, _t, _p in entries],
                self.cfg.kv_block_size)

            def publish_all() -> int:
                from ..runtime.faults import hit as _fault
                values = fetch_wire(stacked, len(entries),
                                    self.wire_kv_heads)
                n = 0
                for i, (_bid, h, th, ph) in enumerate(entries):
                    if rs.object.contains(h):
                        continue           # content-addressed no-op
                    try:
                        _fault("prefill.publish")   # enospc/delay chaos
                        rs.put(h, {k: np.ascontiguousarray(v[:, :, i])
                                   for k, v in values.items()},
                               tokens_hash=th, parent_hash=ph)
                    except OSError as e:
                        # a refusing object tier (full bucket, chaos)
                        # forfeits THIS block's publish and keeps going:
                        # decode fleets simply recompute what never
                        # landed — publish is an optimization, not a
                        # correctness dependency
                        logger.warning("prefix publish of %x failed: %s",
                                       h & 0xFFFFFFFFFFFFFFFF, e)
                        continue
                    n += 1
                return n

            n = await asyncio.to_thread(publish_all)
        finally:
            pool.release(bids)
        self.prefill_published_blocks += n
        return n

    def _publish_tier_removed(self, seq_hash: int) -> None:
        """Removed-from-disk announce, suppressed while any warmer OR
        colder tier still holds the hash (the router would otherwise
        lose a prefix this worker can still serve). A disk eviction
        whose block was promoted to the durable remote tier DEMOTES the
        announce to tier="remote" instead."""
        pub = self.kv_event_publisher
        if pub is None:
            return
        host = self.kv_manager.host_pool
        if self.kv_manager.pool.peek_prefix([seq_hash]):
            return
        if host is not None and host.contains(seq_hash):
            return
        if (self.remote_store is not None
                and self.remote_store.holds_durable(seq_hash)):
            pub.publish_stored(-1, seq_hash, None, None, tier="remote")
            return
        pub.publish_removed([seq_hash])

    def _on_block_stored(self, bid: int, seq_hash: int, tokens_hash: int,
                         parent_hash) -> None:
        """Device-pool stored hook → tier-tagged router event (default
        tier "device")."""
        if self.kv_event_publisher is not None:
            self.kv_event_publisher.publish_stored(
                bid, seq_hash, tokens_hash, parent_hash)

    def _on_block_removed(self, seq_hashes: list) -> None:
        """Device-pool removed hook. A hash still resident in a colder
        tier is DEMOTED (re-announced with the tier tag) instead of
        removed — the router's radix index keeps the prefix visible at a
        discounted depth (kv_router/scoring.py TIER_WEIGHTS) rather than
        forgetting this worker can still serve it without recompute."""
        pub = self.kv_event_publisher
        if pub is None:
            return
        host = self.kv_manager.host_pool
        gone = []
        for h in seq_hashes:
            if host is not None and host.contains(h):
                th, ph = host.meta_for(h)
                pub.publish_stored(-1, h, th, ph, tier="host")
            elif self.disk_store is not None and self.disk_store.contains(h):
                pub.publish_stored(-1, h, None, None, tier="disk")
            elif (self.remote_store is not None
                  and self.remote_store.holds_durable(h)):
                pub.publish_stored(-1, h, None, None, tier="remote")
            else:
                gone.append(h)
        if gone:
            pub.publish_removed(gone)

    def _start_onboard(self, req: EngineRequest, slot: int, plan) -> None:
        """Reserve the slot, then prepare the host/disk-tier values
        off-thread; the loop's onboard step completes the admission (the
        decode batch keeps stepping during the copies). Disk hits promote
        through the SAME path — the tier-2 analog of the CopyStream
        overlap the host tier already implements; the matched disk
        entries were pinned at match time (prepare_prefill) and unpin in
        _complete_onboards."""
        req.slot = slot
        req.ready = False
        self.slots[slot] = req            # reserve (skipped by dispatch)
        self.host_onboards += 1
        if plan.disk_hashes:
            self.disk_onboards += 1
            self.disk_onboarded_blocks += len(plan.disk_hashes)
        if plan.remote_hashes:
            self.remote_onboards += 1
            self.remote_onboarded_blocks += len(plan.remote_hashes)
        host_pool = self.kv_manager.host_pool
        disk = self.disk_store
        remote = self.remote_store
        host_pool.pin(plan.host_slots)    # offload stores must not evict

        # trace identity travels BY VALUE into the prep thread (contextvars
        # don't cross to_thread): fabric RPCs forward it so the serving
        # peer's read lands in the same fleet tree
        trace_ctx = (req.trace.wire_context()
                     if req.trace is not None else None)

        # the recorder's kv_remote_restore event ships the FETCHED bytes
        # (the fleet-shared tier cannot be re-walked by a follower);
        # captured here only when a recorder is attached — otherwise the
        # bulk values are dropped as soon as they are scattered
        rec_remote: dict = {}

        async def prepare() -> None:
            prepped = None
            _t_prep0 = time.monotonic()
            fetch_ms = {"host": 0.0, "disk": 0.0, "remote": 0.0}
            try:
                def prep():
                    from ..runtime.faults import hit as _fault
                    from .block_copy import prep_host_values
                    _fault("engine.onboard")   # chaos: tier prep fails
                    parts = []
                    if plan.host_slots:
                        _t = time.monotonic()
                        parts.append(host_pool.fetch(plan.host_slots))
                        fetch_ms["host"] = 1e3 * (time.monotonic() - _t)
                    if plan.disk_hashes:
                        _t = time.monotonic()
                        parts.append(disk.fetch(plan.disk_hashes))
                        fetch_ms["disk"] = 1e3 * (time.monotonic() - _t)
                    if plan.remote_hashes:
                        # G4 fetch: peer RPC / object read. Unreachable
                        # (peer died, object torn) is NOT an error — drop
                        # the remote tail from the plan and the engine
                        # recomputes those tokens (graceful fallback:
                        # the fabric must never make serving worse than
                        # a cold prefill)
                        _t = time.monotonic()
                        try:
                            fetched = remote.fetch(plan.remote_hashes,
                                                   trace_ctx=trace_ctx)
                            parts.append(fetched)
                            if self.recorder is not None:
                                rec_remote["values"] = fetched
                        except Exception:  # noqa: BLE001
                            logger.warning(
                                "remote KV fetch of %d block(s) failed "
                                "for %s — recomputing the tail",
                                len(plan.remote_hashes), req.rid,
                                exc_info=True)
                            self.remote_fetch_failures += 1
                            self.remote_onboarded_blocks -= len(
                                plan.remote_hashes)
                            remote.unpin(plan.remote_hashes)
                            plan.remote_hashes = []
                        fetch_ms["remote"] = 1e3 * (time.monotonic() - _t)
                    if not parts:
                        # every tier hit fell away: admit with no onboard
                        return [], {}
                    n_onboard = (len(plan.host_slots)
                                 + len(plan.disk_hashes)
                                 + len(plan.remote_hashes))
                    targets = plan.new_blocks[:n_onboard]
                    vals = (parts[0] if len(parts) == 1 else
                            {k: np.concatenate([p[k] for p in parts],
                                               axis=2)
                             for k in parts[0]})
                    return prep_host_values(targets, vals)

                prepped = await asyncio.to_thread(prep)
            except asyncio.CancelledError:
                raise      # stop(): finally below records the dead onboard
            except Exception:  # noqa: BLE001
                logger.exception("host-tier onboard prep failed for %s",
                                 req.rid)
            finally:
                _t_prep1 = time.monotonic()
                self.flight.record(
                    "onboard", rid=req.rid,
                    host_blocks=len(plan.host_slots),
                    disk_blocks=len(plan.disk_hashes),
                    remote_blocks=len(plan.remote_hashes),
                    host_ms=round(fetch_ms["host"], 3),
                    disk_ms=round(fetch_ms["disk"], 3),
                    fabric_fetch_ms=round(fetch_ms["remote"], 3),
                    total_ms=round(1e3 * (_t_prep1 - _t_prep0), 3))
                if req.trace is not None:
                    req.trace.add_span(
                        "kv.onboard", _t_prep0, _t_prep1,
                        host_blocks=len(plan.host_slots),
                        disk_blocks=len(plan.disk_hashes),
                        remote_blocks=len(plan.remote_hashes),
                        fabric_fetch_ms=round(fetch_ms["remote"], 3))
                # pins release in _complete_onboards, AFTER the admission
                # records hit_transfer: an offload-pump eviction of these
                # slots must not be stream-ordered before the event, or a
                # multihost follower's mirror restore would read the
                # clobbered slot (the leader scatters prefetched values
                # and would not notice the divergence)
                self._onboards.append((req, slot, plan, prepped,
                                       rec_remote.get("values")))
                self._work_event.set()

        task = asyncio.get_running_loop().create_task(
            prepare(), name=f"kv-onboard-{req.rid}")
        self._onboard_tasks.add(task)
        task.add_done_callback(self._onboard_tasks.discard)

    def _complete_onboards(self) -> None:
        pending, self._onboards = self._onboards, []
        for req, slot, plan, prepped, remote_values in pending:
            self.slots[slot] = None       # _admit_with_plan re-reserves
            try:
                if req.cancelled or prepped is None:
                    self.kv_manager.pool.release(plan.all_blocks)
                    if req.cancelled:
                        self._finish_request(req, FinishReason.CANCELLED)
                    elif not req.cold_admission:
                        # tier onboard prep failed (dead disk, torn
                        # fetch, chaos injection): re-admit COLD — skip
                        # the offload cascade and recompute the prefix.
                        # A broken cache tier must degrade to a cold
                        # prefill, never to a failed request.
                        self.onboard_cold_retries += 1
                        req.cold_admission = True
                        req.slot = -1
                        req.ready = True
                        logger.warning(
                            "onboard prep failed for %s — retrying as a "
                            "cold admission (recompute)", req.rid)
                        self.waiting.put_nowait(req)
                        self._work_event.set()
                    else:
                        self._finish_request(req, FinishReason.ERROR)
                    continue
                self._admit_with_plan(req, slot, plan, prepped,
                                      remote_values=remote_values)
            finally:
                # _start_onboard pinned these; safe to evict only now
                # that hit_transfer (if any) is on the stream. A failed
                # remote fetch already unpinned and cleared remote_hashes
                # inside the prep (graceful fallback).
                self.kv_manager.host_pool.unpin(plan.host_slots)
                if plan.disk_hashes:
                    self.disk_store.unpin(plan.disk_hashes)
                if plan.remote_hashes:
                    self.remote_store.unpin(plan.remote_hashes)

    def _admit_with_plan(self, req: EngineRequest, slot: int, plan,
                         onboard, remote_values=None) -> bool:
        n_prompt = len(req.prompt)
        _t_admit = time.monotonic()
        if req.trace is not None:
            # queue-wait phase on the request's fleet trace: enqueue →
            # the moment a slot + KV plan existed for it
            req.trace.add_span("engine.queue_wait", req.enqueue_time,
                               _t_admit)
        req.slot = slot
        req.blocks = plan.all_blocks
        req.seq = plan.seq
        # host-tier hits: scatter the prepared (block-major, padded) values
        # into their device slots before prefill (reference
        # prepare_prefill_offload; the +40% TTFT multi-turn win,
        # docs/architecture.md:91)
        n_onboard = (len(plan.host_slots) + len(plan.disk_hashes)
                     + len(plan.remote_hashes))
        if n_onboard:
            from .block_copy import scatter_prepped
            ids, vals = onboard
            self.kv = scatter_prepped(self.kv, ids, vals,
                                      self.cfg.kv_block_size)
            targets = plan.new_blocks[:n_onboard]
            # onboarded blocks now hold valid registered content
            n_dev = len(plan.hit_blocks)
            for i, bid in enumerate(targets):
                j = n_dev + i
                parent = plan.seq.sequence_hashes[j - 1] if j > 0 else None
                self.kv_manager.pool.register(
                    bid, plan.seq.sequence_hashes[j],
                    plan.seq.block_hashes[j], parent)
        req.prefix_hit_tokens = (plan.hit_tokens + plan.host_hit_tokens
                                 + plan.disk_hit_tokens
                                 + plan.remote_hit_tokens)
        n_already = len(plan.hit_blocks) + n_onboard
        if self.recorder is not None and req.prefix_hit_tokens > 0:
            # before the prefill record: read rights over the shared
            # prefix. host_hit + host_slots/targets let multihost
            # followers and the offline replayer re-execute the h2d
            # restore above from their mirror pools
            # (replay.exec_host_restore_event); disk_hashes/disk_targets
            # do the same for the G3 promote (the follower fetches the
            # hashes from its own mirror disk store)
            n_host = len(plan.host_slots)
            n_hd = n_host + len(plan.disk_hashes)
            if plan.remote_hashes:
                # fleet-shared (G4) tier: followers never run the
                # admission cascade, so a remote-assisted admission
                # streams as its OWN event carrying the fetched hashes
                # AND the fetched bytes — recorded BEFORE hit_transfer
                # so the replayed restore marks the remote targets
                # written before the hit walk reads them. Followers and
                # the offline replayer scatter the literal bytes
                # (replay.exec_kv_remote_restore_event); a follower
                # whose OWN remote store holds the hashes may fetch
                # them instead (fetch-or-bytes — the object tier is
                # content-addressed, so the bytes are identical by
                # construction). This retired the round-6 refusal.
                if remote_values is None:
                    raise RuntimeError(
                        "recorded remote onboarding without captured "
                        "fetch values — prep/recorder wiring drifted")
                self.recorder.rec(
                    "kv_remote_restore", rid=req.rid,
                    remote_hashes=list(plan.remote_hashes),
                    remote_targets=list(
                        plan.new_blocks[n_hd:n_hd
                                        + len(plan.remote_hashes)]),
                    values={k: np.asarray(v)
                            for k, v in remote_values.items()})
            self.recorder.rec("hit_transfer", rid=req.rid,
                              hit=req.prefix_hit_tokens,
                              host_hit=plan.host_hit_tokens,
                              disk_hit=plan.disk_hit_tokens,
                              blocks=list(plan.all_blocks),
                              # multihost followers replay the h2d restore
                              # from their mirror pool at these slots into
                              # these device blocks (run_follower)
                              host_slots=list(plan.host_slots),
                              host_targets=list(
                                  plan.new_blocks[:n_host]),
                              disk_hashes=list(plan.disk_hashes),
                              disk_targets=list(
                                  plan.new_blocks[n_host:n_hd]))
        t0 = time.monotonic()
        suffix_len = n_prompt - req.prefix_hit_tokens
        if (self._ragged_jit is not None and req.handoff is None
                and req.precomputed is None and suffix_len > 0):
            # ragged serving: EVERY normal admission rides the ragged
            # batch as a prefill lane — no dedicated prefill dispatch,
            # continuous batching is the only code path. Disagg
            # handoff/precomputed admissions keep the prefill program
            # (their gather/scatter contracts are prefill-shaped).
            self._admit_lane(req, slot, n_already)
            return True
        if (self.cfg.lane_prefill_max_tokens > 0
                and self._decode_k_jit is not None
                and req.handoff is None and req.precomputed is None
                and 0 < suffix_len <= self.cfg.lane_prefill_max_tokens
                and any(s is not None and s.ready for s in self.slots)):
            # lane prefill: the engine is already decoding — ride the
            # decode batch instead of stalling it with a prefill dispatch
            self._admit_lane(req, slot, n_already)
            return True
        defer = False
        remote_admit = req.precomputed is not None
        if remote_admit:
            from ..llm.kv.stream import LayerStreamPayload
            if (isinstance(req.precomputed, LayerStreamPayload)
                    and not req.precomputed.complete):
                # streaming layer-wise handoff: admit NOW (slot reserved,
                # decode-invisible) and scatter layers as frames land —
                # the request becomes decode-ready the tick the last
                # layer arrives (llm/kv/stream.py; _stream_onboard)
                return self._admit_stream(req, slot, plan, n_already,
                                          _t_admit)
        if req.precomputed is not None:
            tok, logprob = self._admit_precomputed(req, n_already)
            # device payloads ship the first token as a device scalar (the
            # prefill side never fetched it — one round-trip saved); defer
            # our fetch behind the next decode dispatch like a local
            # admission
            defer = (self.cfg.overlap_admission_fetch
                     and hasattr(tok, "copy_to_host_async"))
            if not defer:
                if hasattr(tok, "copy_to_host_async"):  # device, not host
                    self.host_roundtrips += 1
                _t0 = time.monotonic()
                tok, logprob = int(tok), float(logprob)
                self.host_stall_s += time.monotonic() - _t0
        else:
            # prefill only the un-matched suffix — the prefix KV is already
            # in the pool's blocks (this is the TTFT win of prefix reuse)
            chunk = req.prompt[req.prefix_hit_tokens:]
            bucket = self.cfg.bucket_for(len(chunk))
            table = np.zeros((self.M,), np.int32)
            table[:len(req.blocks)] = req.blocks
            key = make_slot_keys(self.cfg.seed,
                                 jnp.asarray([req.sampling.seed]),
                                 jnp.asarray(req.key_step))[0]
            use_sp = (self._prefill_sp_jit is not None
                      and req.prefix_hit_tokens == 0
                      and len(chunk) >= self.cfg.sp_min_prefill_tokens
                      and bucket % self._sp == 0
                      # ring attention supports neither score soft-capping
                      # nor sliding-window layers (gemma2)
                      and self.model_cfg.attn_logit_softcap is None
                      and self.model_cfg.sliding_window is None)
            if use_sp:
                padded = np.zeros((bucket,), np.int32)
                padded[:len(chunk)] = chunk
                if self.recorder is not None:
                    # streamable like plain prefill (start_pos is always 0
                    # on the sp path) — multihost followers replay it
                    req._pf_seq = self.recorder.next_dispatch_id()
                    self.recorder.rec(
                        "prefill_sp", pf_seq=req._pf_seq, rid=req.rid,
                        slot=slot, padded=padded.copy(), table=table.copy(),
                        true_len=len(chunk), samp_seed=req.sampling.seed,
                        key_step=req.key_step,
                        temp=req.sampling.temperature,
                        top_k=req.sampling.top_k, top_p=req.sampling.top_p)
                tok, logprob, self.kv = self._prefill_sp_jit(
                    self.params, self.kv, jnp.asarray(padded),
                    jnp.asarray(table), jnp.asarray(len(chunk), jnp.int32),
                    key,
                    jnp.asarray(req.sampling.temperature, jnp.float32),
                    jnp.asarray(req.sampling.top_k, jnp.int32),
                    jnp.asarray(req.sampling.top_p, jnp.float32))
            elif (self.cfg.prefill_chunk > 0
                    and len(chunk) > self.cfg.prefill_chunk):
                tok, logprob = self._chunked_prefill(req, chunk, table, key,
                                                     slot=slot)
            else:
                padded = np.zeros((bucket,), np.int32)
                padded[:len(chunk)] = chunk
                if self.recorder is not None:
                    req._pf_seq = self._rec_prefill(
                        req, slot, padded, table,
                        start_pos=req.prefix_hit_tokens,
                        true_len=len(chunk))
                tok, logprob, self.kv = self._prefill_jit(
                    self.params, self.kv, jnp.asarray(padded),
                    jnp.asarray(table),
                    jnp.asarray(req.prefix_hit_tokens, jnp.int32),
                    jnp.asarray(len(chunk), jnp.int32),
                    key,
                    jnp.asarray(req.sampling.temperature, jnp.float32),
                    jnp.asarray(req.sampling.top_k, jnp.int32),
                    jnp.asarray(req.sampling.top_p, jnp.float32))
            self.total_prefill_tokens += len(chunk)
            # measured prefill rate (fabric admission gate + the
            # router's NetKV recompute model): wall time from plan to
            # dispatched prefill — an upper bound on the true compute
            # cost, so the modeled recompute stays conservative
            admit_wall_s = time.monotonic() - t0
            self.prefill_wall_s += admit_wall_s
            self.prefill_rate_estimator.observe(len(chunk), admit_wall_s)
            # defer the device→host fetch of the first token: it overlaps
            # the next decode dispatch instead of stalling the loop. Wire
            # handoff needs the host value immediately; DEVICE handoff
            # never needs it at all — the token rides the payload as a
            # device scalar and the decode side defers its own fetch.
            defer = (self.cfg.overlap_admission_fetch
                     and req.handoff is None)
            if not defer and not req.handoff_device:
                self.host_roundtrips += 1
                _t0 = time.monotonic()
                tok, logprob = int(tok), float(logprob)
                self.host_stall_s += time.monotonic() - _t0
        if req.handoff is not None:
            defer = False
        req.pos = n_prompt
        req.generated = 1
        req.key_step += 1
        # the prompt's full blocks now hold valid KV — register for reuse
        req.registered_blocks = self.kv_manager.register_full_blocks(
            req.blocks, plan.seq, already_registered=n_already,
            tenant=req.tenant or None)
        if self.recorder is not None:
            self.recorder.rec(
                "admit", rid=req.rid, slot=slot, pos=req.pos,
                key_step=req.key_step, blocks=list(req.blocks),
                hit=req.prefix_hit_tokens, prompt=list(req.prompt))
        if req.handoff is not None:
            self._handoff_and_finish(req, tok, logprob)
            return True
        if not defer:
            req.last_token = int(tok)
            req.first_token_time = time.monotonic()
            if self.recorder is not None:
                self.recorder.rec("first_token", rid=req.rid,
                                  pf_seq=getattr(req, "_pf_seq", None),
                                  tok=req.last_token)
        else:
            req.ready = False
            req.last_token = -1
            for a in (tok, logprob):
                if hasattr(a, "copy_to_host_async"):
                    a.copy_to_host_async()
            self._admissions.append((req, tok, logprob))
        self.slots[slot] = req
        # host mirrors
        self._block_tables[slot, :] = 0
        self._block_tables[slot, :len(req.blocks)] = req.blocks
        self._samp["temperature"][slot] = req.sampling.temperature
        self._samp["top_k"][slot] = req.sampling.top_k
        self._samp["top_p"][slot] = req.sampling.top_p
        self._seeds[slot] = req.sampling.seed
        logger.debug(
            "admitted %s into slot %d (prompt=%d, hit=%d+%dhost+%ddisk+"
            "%dremote, handoff=%s, %.1fms)", req.rid, slot, n_prompt,
            plan.hit_tokens, plan.host_hit_tokens, plan.disk_hit_tokens,
            plan.remote_hit_tokens, remote_admit,
            1e3 * (time.monotonic() - t0))
        now = time.monotonic()
        self.flight.record(
            "prefill", rid=req.rid, prompt=n_prompt,
            planned_tokens=suffix_len, batch_fill=sum(
                1 for s in self.slots if s is not None),
            hit_device=plan.hit_tokens, hit_host=plan.host_hit_tokens,
            hit_disk=plan.disk_hit_tokens,
            hit_remote=plan.remote_hit_tokens,
            precomputed=remote_admit,
            host_ms=round(1e3 * (now - t0), 3),
            queue_wait_ms=round(1e3 * (_t_admit - req.enqueue_time), 3))
        if req.trace is not None:
            req.trace.add_span(
                "engine.prefill", t0, now, suffix=suffix_len,
                hit=req.prefix_hit_tokens,
                tiers={"device": plan.hit_tokens,
                       "host": plan.host_hit_tokens,
                       "disk": plan.disk_hit_tokens,
                       "remote": plan.remote_hit_tokens})
        if req.ready:
            self._emit(req, tok, float(logprob))
            self._maybe_finish_after_emit(req)
        return True

    def _admit_lane(self, req: EngineRequest, slot: int,
                    n_already: int) -> None:
        """Continuous-batching admission: no prefill dispatch — the prompt
        rides the decode batch as planned tokens (see EngineConfig.
        lane_prefill_max_tokens). Blocks are allocated (done by the caller's
        plan) but NOT registered yet: their KV is written step by step, so
        registration follows harvest progress exactly like decode."""
        self.lane_admissions += 1
        n_prompt = len(req.prompt)
        hit = req.prefix_hit_tokens
        # the first generated token comes from the decode program here
        # (an uncontended run derives it via the prefill program) — a
        # numeric boundary for the exactness contract
        req.numeric_boundaries.append(req.emitted_total)
        req.lane_prompt = list(req.prompt)
        req.pos = hit
        req.generated = 0
        # sampling-key parity with the prefill path: the step consuming the
        # last prompt token samples the first generation and must use the
        # request's CURRENT key_step; planned steps before it burn earlier
        # (negative-offset) key values whose samples are discarded anyway
        req.key_step -= n_prompt - hit - 1
        req.last_token = req.prompt[hit]       # step-0 planned input
        req.ready = True
        # hash chain restarts from the hit prefix and grows per input token
        req.seq = TokenBlockSequence(self.cfg.kv_block_size,
                                     req.prompt[:hit])
        req.registered_blocks = n_already
        self.slots[slot] = req
        self._block_tables[slot, :] = 0
        self._block_tables[slot, :len(req.blocks)] = req.blocks
        self._samp["temperature"][slot] = req.sampling.temperature
        self._samp["top_k"][slot] = req.sampling.top_k
        self._samp["top_p"][slot] = req.sampling.top_p
        self._seeds[slot] = req.sampling.seed
        if self.recorder is not None:
            self.recorder.rec(
                "admit", rid=req.rid, slot=slot, pos=req.pos,
                key_step=req.key_step, blocks=list(req.blocks),
                hit=hit, prompt=list(req.prompt), lane=True)
        logger.debug("lane-admitted %s into slot %d (prompt=%d, hit=%d)",
                     req.rid, slot, n_prompt, hit)

    def _rec_prefill(self, req: "EngineRequest", slot: int,
                     padded: np.ndarray, table: np.ndarray, *,
                     start_pos: int, true_len: int) -> int:
        """Record one plain-prefill event (the ONE home of its field set —
        whole-prompt admissions and each chunk of a chunked admission both
        go through here). Returns the event's pf_seq."""
        pf = self.recorder.next_dispatch_id()
        self.recorder.rec(
            "prefill", pf_seq=pf, rid=req.rid, slot=slot,
            padded=padded.copy(), table=table.copy(),
            start_pos=start_pos, true_len=true_len,
            samp_seed=req.sampling.seed, key_step=req.key_step,
            temp=req.sampling.temperature,
            top_k=req.sampling.top_k, top_p=req.sampling.top_p)
        return pf

    def _chunked_prefill(self, req: EngineRequest, chunk: list,
                         table: np.ndarray, key, *, slot: int) -> tuple:
        """Prompt prefill as a sequence of fixed-size chunk dispatches
        (EngineConfig.prefill_chunk): each chunk continues at
        ``start_pos`` against the KV already written — the same mechanism
        as prefix-reuse continuation — so one compiled chunk shape serves
        any prompt length, bounding both compile count and per-dispatch
        activation memory (SURVEY.md §7 "blockwise prefill chunks"). Only
        the final chunk's sampled token matters. Each chunk records as a
        plain "prefill" event (it IS one), so chunked runs replay and
        stream to multihost followers."""
        C = self.cfg.prefill_chunk
        off = req.prefix_hit_tokens
        tok = logprob = None
        for lo in range(0, len(chunk), C):
            piece = chunk[lo:lo + C]
            # the tail pads to C too: exactly ONE compiled prefill shape
            # regardless of prompt length or bucket list
            padded = np.zeros((C,), np.int32)
            padded[:len(piece)] = piece
            if self.recorder is not None:
                pf = self._rec_prefill(req, slot, padded, table,
                                       start_pos=off, true_len=len(piece))
                if lo + C >= len(chunk):
                    req._pf_seq = pf      # final chunk samples the token
            tok, logprob, self.kv = self._prefill_jit(
                self.params, self.kv, jnp.asarray(padded),
                jnp.asarray(table),
                jnp.asarray(off, jnp.int32),
                jnp.asarray(len(piece), jnp.int32),
                key,
                jnp.asarray(req.sampling.temperature, jnp.float32),
                jnp.asarray(req.sampling.top_k, jnp.int32),
                jnp.asarray(req.sampling.top_p, jnp.float32))
            off += len(piece)
        return tok, logprob

    def _complete_admissions(self) -> None:
        """Finish deferred admissions: the async device→host copies have
        been in flight across a decode dispatch; fetch, emit the first
        token, and make the slot decodable."""
        pending, self._admissions = self._admissions, []
        if pending:
            # the async copies were issued at admission and usually land
            # during the intervening dispatch harvest — host_stall_s
            # records what the fetches below ACTUALLY cost (often ~0)
            self.host_roundtrips += 1
        for req, tok_dev, logprob_dev in pending:
            _t0 = time.monotonic()
            tok = int(np.asarray(tok_dev))
            logprob = float(np.asarray(logprob_dev))
            self.host_stall_s += time.monotonic() - _t0
            req.last_token = tok
            req.first_token_time = time.monotonic()
            req.ready = True
            if self.recorder is not None:
                self.recorder.rec("first_token", rid=req.rid,
                                  pf_seq=getattr(req, "_pf_seq", None),
                                  tok=tok)
            if self.slots[req.slot] is not req:
                continue               # raced away (shutdown edge)
            self._emit(req, tok, logprob)
            self._maybe_finish_after_emit(req)

    def _admit_precomputed(self, req: EngineRequest,
                           n_already: int) -> tuple:
        """Admission from a remote-prefill KV payload: scatter the shipped
        block values into this engine's paged pool instead of running the
        prefill program (the decode half of PD disaggregation; reference
        examples/llm/components/worker.py remote-prefill path). Blocks the
        decode engine already had (device/host prefix hits) are skipped —
        only the remainder is written."""
        pc = req.precomputed
        n_prompt_blocks = self._blocks_needed(len(req.prompt))
        targets = req.blocks[n_already:n_prompt_blocks]
        from ..llm.kv_transport import (DeviceKvPayload,
                                        scatter_blocks_device)
        if isinstance(pc, DeviceKvPayload) and self.recorder is not None:
            # device payloads are NOT copied onto the stream — their
            # arrays are device-resident. Each follower rank's co-located
            # prefill-engine replica parked its own shard of this payload
            # under the request id ("handoff_gather" park=True); stream
            # only the admission metadata and let each rank scatter its
            # local deposit (multihost.run_follower
            # "precomputed_device_admit"). Streamed even with empty
            # targets (full prefix hit): the followers must still CLAIM
            # and drop their parked shard or it would pin HBM forever.
            self.recorder.rec(
                "precomputed_device_admit", rid=req.rid,
                targets=list(targets), skip=n_already,
                n_needed=n_prompt_blocks)
        if targets:
            # (payload layout was validated at submit() — a raise here
            # would kill the engine loop)
            if isinstance(pc, DeviceKvPayload):
                # device bulk plane: blocks hop prefill-devices →
                # decode-devices (ICI, resharding under our mesh) with no
                # host staging
                self.kv = scatter_blocks_device(
                    self.kv, targets, pc, n_already, n_prompt_blocks,
                    mesh=self.mesh)
            else:
                vals = {k: v[:, :, n_already:n_prompt_blocks]
                        for k, v in pc.values.items()}
                if self.recorder is not None:
                    # wire-plane payload: stream the (global-head) values
                    # so multihost followers and the offline replayer can
                    # apply the identical scatter — recorded BEFORE the
                    # device op, like every streamed program
                    self.recorder.rec(
                        "precomputed_admit", rid=req.rid,
                        targets=list(targets),
                        values={k: np.asarray(v) for k, v in vals.items()})
                self.kv = scatter_blocks_from_host(
                    self.kv, targets, vals, self.cfg.kv_block_size)
        # drop the payload now: nothing reads it after the scatter, and a
        # DeviceKvPayload would otherwise pin the whole gathered KV stack
        # in the PREFILL engine's HBM for this request's lifetime
        req.precomputed = None
        return pc.first_token, pc.first_logprob

    def _admit_stream(self, req: EngineRequest, slot: int, plan,
                      n_already: int, t_admit: float) -> bool:
        """Admission against a still-arriving LayerStreamPayload
        (llm/kv/stream.py): the slot is reserved with the admission-time
        bookkeeping of a precomputed admit (pos/key_step/mirrors — so the
        later decode stream is bit-identical to the monolithic handoff),
        but the request stays ``ready=False`` — dispatches aim it at the
        trash block — while _stream_onboard scatters layers as they land.
        First-token emit, block registration, and the ``first_token``
        record all defer to stream completion; a dead stream re-admits
        COLD (the same graceful rung as a failed tier onboard)."""
        n_prompt = len(req.prompt)
        n_prompt_blocks = self._blocks_needed(n_prompt)
        req.pos = n_prompt
        req.generated = 1
        req.key_step += 1
        req.ready = False
        req.last_token = -1
        self.disagg_stream_admits += 1
        if self.recorder is not None:
            self.recorder.rec(
                "admit", rid=req.rid, slot=slot, pos=req.pos,
                key_step=req.key_step, blocks=list(req.blocks),
                hit=req.prefix_hit_tokens, prompt=list(req.prompt))
        self.slots[slot] = req
        self._block_tables[slot, :] = 0
        self._block_tables[slot, :len(req.blocks)] = req.blocks
        self._samp["temperature"][slot] = req.sampling.temperature
        self._samp["top_k"][slot] = req.sampling.top_k
        self._samp["top_p"][slot] = req.sampling.top_p
        self._seeds[slot] = req.sampling.seed
        logger.debug(
            "stream-admitted %s into slot %d (prompt=%d, hit=%d, "
            "%d layers inbound)", req.rid, slot, n_prompt,
            req.prefix_hit_tokens, req.precomputed.num_layers)
        self.flight.record(
            "prefill", rid=req.rid, prompt=n_prompt,
            planned_tokens=0, batch_fill=sum(
                1 for s in self.slots if s is not None),
            hit_device=plan.hit_tokens, hit_host=plan.host_hit_tokens,
            hit_disk=plan.disk_hit_tokens,
            hit_remote=plan.remote_hit_tokens,
            precomputed=True,
            queue_wait_ms=round(1e3 * (t_admit - req.enqueue_time), 3))
        task = asyncio.get_running_loop().create_task(
            self._stream_onboard(req, plan, n_already, n_prompt_blocks),
            name=f"kv-stream-onboard-{req.rid}")
        self._stream_tasks.add(task)
        task.add_done_callback(self._stream_tasks.discard)
        return True

    async def _stream_onboard(self, req: EngineRequest, plan,
                              n_already: int,
                              n_prompt_blocks: int) -> None:
        """Progressive onboard of a layer stream: per layer, await the
        frame, prep OFF-thread (the existing tier-onboard discipline —
        the wire→block-major transpose never stalls the loop), then
        record ``kv_layer_stream`` and dispatch the scatter ADJACENTLY
        (no await between them, so recorder order equals device
        submission order — the bit-exact replay/follower contract)."""
        from .block_copy import (prep_layer_values, scatter_layer_prepped,
                                 slice_local_lanes)
        pc = req.precomputed
        t_wait = t_busy = 0.0
        try:
            for layer in range(pc.num_layers):
                _t0 = time.monotonic()
                vals = await pc.wait_layer(layer)
                _t1 = time.monotonic()
                t_wait += _t1 - _t0
                if req.cancelled or self.slots[req.slot] is not req:
                    return      # swept/raced away; blocks already handled
                # defrag may relocate this request's blocks between
                # layers (it copies content, so earlier layers move with
                # them) — re-read the live suffix targets each layer
                targets = req.blocks[n_already:n_prompt_blocks]
                if targets:
                    sliced = slice_local_lanes(
                        self.kv,
                        {k: v[:, n_already:n_prompt_blocks]
                         for k, v in vals.items()})
                    ids, prepped = await asyncio.to_thread(
                        prep_layer_values, targets, sliced)
                    if (req.cancelled
                            or self.slots[req.slot] is not req):
                        return
                    if self.recorder is not None:
                        self.recorder.rec(
                            "kv_layer_stream", rid=req.rid, layer=layer,
                            num_layers=pc.num_layers,
                            targets=list(targets),
                            values={k: np.asarray(v)
                                    for k, v in sliced.items()})
                    self.kv = scatter_layer_prepped(
                        self.kv, layer, ids, prepped,
                        self.cfg.kv_block_size)
                self.disagg_stream_layers_scattered += 1
                t_busy += time.monotonic() - _t1
        except asyncio.CancelledError:
            raise
        except Exception as e:  # noqa: BLE001 — dead stream → cold rung
            self.disagg_stream_fallbacks += 1
            self.disagg_stream_hidden_s += t_busy
            self.disagg_stream_exposed_s += t_wait
            if self.slots[req.slot] is not req:
                return
            logger.warning(
                "kv layer stream failed for %s (%s) — re-admitting as a "
                "cold recompute", req.rid, e)
            self._release_slot(req)
            if req.cancelled:
                self._finish_request(req, FinishReason.CANCELLED)
                return
            # restore pre-admission sampling state so the local
            # recompute samples exactly what an uncontended run would
            # (no key was consumed: the first token was the producer's)
            req.key_step -= 1
            req.pos = 0
            req.generated = 0
            req.precomputed = None
            req.seq = None
            req.slot = -1
            req.registered_blocks = 0
            req.prefix_hit_tokens = 0
            req.ready = True
            req.cold_admission = True
            self.waiting.put_nowait(req)
            self._work_event.set()
            return
        # completion: the pool now holds the full prompt KV — register,
        # surface the producer's first token, join the decode batch
        self.disagg_stream_hidden_s += t_busy
        self.disagg_stream_exposed_s += t_wait
        if pc.fallback_monolithic:
            self.disagg_stream_fallbacks += 1
        req.precomputed = None
        if req.cancelled or self.slots[req.slot] is not req:
            return
        req.registered_blocks = self.kv_manager.register_full_blocks(
            req.blocks, plan.seq, already_registered=n_already,
            tenant=req.tenant or None)
        tok, logprob = int(pc.first_token), float(pc.first_logprob)
        req.last_token = tok
        req.first_token_time = time.monotonic()
        req.ready = True
        if self.recorder is not None:
            self.recorder.rec("first_token", rid=req.rid, pf_seq=None,
                              tok=tok)
        self._emit(req, tok, logprob)
        self._maybe_finish_after_emit(req)
        self._work_event.set()

    def _handoff_and_finish(self, req: EngineRequest, tok: int,
                            logprob: float) -> None:
        """Prefill-worker epilogue: dispatch an on-device gather of the
        prompt's blocks (ordered before any later donated decode step by
        the device's program order), then ship device→DRAM→TCP off-thread
        so the engine loop keeps stepping during the DMA + DCN transfer."""
        from .block_copy import fetch_wire, gather_blocks_dispatch
        n_blocks = self._blocks_needed(req.pos)
        ids = req.blocks[:n_blocks]
        if self.recorder is not None:
            # a multihost PREFILL engine must stream the gather — it is a
            # device program, and an unstreamed dispatch would deadlock
            # followers at the next collective. park=True additionally
            # tells each follower rank to hold its shard of the gather
            # output in the process bridge so a co-located multihost
            # DECODE engine's follower can claim it on the leader's
            # "precomputed_device_admit" (multihost.run_follower)
            self.recorder.rec("handoff_gather", rid=req.rid,
                              ids=list(ids), n_blocks=n_blocks,
                              park=bool(req.handoff_device))
        stacked = gather_blocks_dispatch(self.kv, ids, self.cfg.kv_block_size)
        seq_hashes = list(req.seq.sequence_hashes[:req.registered_blocks])
        handoff = req.handoff
        kvh = self.wire_kv_heads

        if req.handoff_device:
            # device bulk plane: ship the gather output as device arrays —
            # no host fetch; the decode engine device_puts + scatters
            async def send() -> None:
                await handoff(tok, logprob,
                              {"stacked": stacked, "n_blocks": n_blocks},
                              seq_hashes)
        elif req.handoff_layered and all(
                getattr(v, "is_fully_addressable", True)
                for v in stacked.values()):
            # streaming layer-wise handoff (llm/kv/stream.py): hand the
            # worker per-layer fetch handles over the ONE dispatched
            # gather — layer l+1's device→host fetch overlaps layer l's
            # wire send, and the decode side scatters as frames land.
            # Multi-controller gathers keep the monolithic path (their
            # per-rank shards are assembled whole by fetch_wire).
            from .block_copy import fetch_wire_layer
            from ..llm.kv.stream import LayeredHarvest
            num_layers = next(iter(stacked.values())).shape[0]

            async def send() -> None:
                harvest = LayeredHarvest(
                    num_layers=num_layers,
                    fetch_layer=lambda l: fetch_wire_layer(
                        stacked, n_blocks, kvh, l),
                    fetch_all=lambda: fetch_wire(stacked, n_blocks, kvh))
                await handoff(tok, logprob, harvest, seq_hashes)
        else:
            async def send() -> None:
                values = await asyncio.to_thread(
                    fetch_wire, stacked, n_blocks, kvh)
                await handoff(tok, logprob, values, seq_hashes)

        task = asyncio.get_running_loop().create_task(
            send(), name=f"kv-handoff-{req.rid}")
        self._handoff_tasks.add(task)
        task.add_done_callback(self._handoff_tasks.discard)
        if not req.handoff_device:
            # device mode keeps tok/logprob as device scalars (the token
            # rides the payload; no host sync here) — emitting them would
            # hand device arrays to a queue whose contract is host values
            self._emit(req, tok, logprob)
        self._release_slot(req)
        self._finish_request(req, FinishReason.LENGTH)

    def _tables_for_dispatch(self) -> np.ndarray:
        """Block tables a dispatch should see: non-ready admissions keep
        their mirror row (written at admission) but the DISPATCH aims them
        at the trash block — copy-on-write so the mirror survives."""
        tables = self._block_tables
        for i, s in enumerate(self.slots):
            if s is not None and not s.ready:
                if tables is self._block_tables:
                    tables = self._block_tables.copy()
                tables[i, :] = 0
        return tables

    # --------------------------------------------------------------- decode
    def _decode_step(self) -> None:
        if self._ragged_jit is not None:
            # ragged serving: ONE dispatch per loop iteration carries
            # every ready slot's work — pending prompt rows and due
            # decode rows together (docs/ragged_attention.md)
            self._ragged_step()
            return
        if self._verify_jit is not None and self._spec_candidates():
            # speculation drafts from HARVESTED state, so the pipelined
            # dispatch (if any) must drain first; spec mode therefore
            # forfeits the harvest/compute overlap — the multi-token
            # emission per dispatch is the bigger lever when drafts land
            if self._pending is not None:
                prev, self._pending = self._pending, None
                self._harvest(prev)
                if not any(s is not None and s.ready for s in self.slots):
                    return
            if self._decode_step_spec():
                return
            # drafter came up dry everywhere: plain decode this step
            # (the k=0 degeneracy — speculation costs nothing when idle)
        if self._decode_k_jit is not None:
            self._decode_step_multi(self.cfg.decode_steps_per_dispatch)
            return
        active_idx = [i for i, s in enumerate(self.slots)
                      if s is not None and s.ready]
        steps = np.zeros((self.B,), np.int64)
        for i in range(self.B):
            s = self.slots[i]
            if s is None or not s.ready:
                self._tokens[i] = 0
                self._positions[i] = 0
                if s is None:
                    self._block_tables[i, :] = 0  # trash block
            else:
                self._tokens[i] = s.last_token
                self._positions[i] = s.pos
                steps[i] = s.key_step
        tables = self._tables_for_dispatch()
        self._step += 1
        keys = make_slot_keys(self.cfg.seed, jnp.asarray(self._seeds),
                              jnp.asarray(steps))
        toks, logprobs, self.kv = self._decode_jit(
            self.params, self.kv,
            jnp.asarray(self._tokens), jnp.asarray(self._positions),
            jnp.asarray(tables), keys,
            jnp.asarray(self._samp["temperature"]),
            jnp.asarray(self._samp["top_k"]),
            jnp.asarray(self._samp["top_p"]))
        toks = np.asarray(toks)
        logprobs = np.asarray(logprobs)
        bs = self.cfg.kv_block_size
        for i in active_idx:
            req = self.slots[i]
            if req is None:
                continue
            if req.cancelled:
                self._release_slot(req)
                self._finish_request(req, FinishReason.CANCELLED)
                continue
            tok = int(toks[i])
            # the step wrote the *input* token's KV into the cache — its
            # block may now be full and registrable for prefix reuse
            if req.seq is not None:
                req.seq.append(int(self._tokens[i]))
                req.registered_blocks = self.kv_manager.register_full_blocks(
                    req.blocks, req.seq, req.registered_blocks,
                    tenant=req.tenant or None)
            req.pos += 1
            req.generated += 1
            req.key_step += 1
            req.last_token = tok
            self.total_decode_tokens += 1
            # grow block table if the *next* token would start a new block
            if (req.pos + 1) > len(req.blocks) * bs:
                if len(req.blocks) >= self.M:       # context capacity
                    self._emit(req, tok, float(logprobs[i]))
                    self._release_slot(req)
                    self._finish_request(req, FinishReason.LENGTH)
                    continue
                new = self.kv_manager.pool.alloc_uninit(1)
                if new is None:
                    # out of KV memory: the sampled token is still valid
                    # (its input's KV was written) — emit it, then finish
                    # if it was terminal anyway (EOS / budget / cancel),
                    # else preempt
                    self._emit(req, tok, float(logprobs[i]))
                    if (req.last_token in req.eos_ids
                            or req.generated >= req.max_new_tokens
                            or req.cancelled):
                        self._maybe_finish_after_emit(req)
                    else:
                        self._preempt_or_finish(req)
                    continue
                req.blocks.extend(new)
                self._block_tables[i, len(req.blocks) - 1] = new[0]
            self._emit(req, tok, float(logprobs[i]))
            self._maybe_finish_after_emit(req)
        _now = time.monotonic()
        self.flight.record(
            "decode", K=1, batch_fill=len(active_idx),
            planned_tokens=len(active_idx),
            emitted=len(active_idx),
            device_ms=0.0,
            host_gap_ms=round(
                1e3 * (_now - self._flight_cycle_end), 3))
        self._flight_cycle_end = _now

    def _decode_step_multi(self, K: int) -> None:
        """K fused decode steps, one dispatch, one host harvest: sampled
        tokens chain into the next step on device (lax.scan), so the
        device→host fetch — the dominant per-step cost on high-latency
        links — is paid once per K tokens. EOS/cancel/max_tokens are
        applied at harvest: device steps past a finish are discarded (the
        documented K-1-steps-of-waste trade, EngineConfig).

        With ``decode_dispatch_pipeline`` the harvest is deferred one
        dispatch: the next K-batch launches chained off the previous
        dispatch's ON-DEVICE tokens, so the device→host copy overlaps the
        next dispatch's compute — steady state max(fetch, compute)
        instead of their sum. Finish reaction widens to ≤2K-1 steps."""
        if self._pending is not None:
            nxt = self._dispatch_pipelined(K)
            prev, self._pending = self._pending, None
            self._harvest(prev)
            if nxt is not None:
                self._pending = nxt
                return
            # couldn't chain (slot churn / growth failure): fall through to
            # a fresh host-fed dispatch against the harvested state
        if not self._prepare_multi(K):
            return
        pending = self._dispatch_multi(K)
        if self.cfg.decode_dispatch_pipeline:
            self._pending = pending
        else:
            self._harvest(pending)

    def _prepare_multi(self, K: int, ahead_mask=None) -> bool:
        """Capacity check + block-table pre-grow for the next K steps.
        ``ahead_mask`` flags slots whose request has K un-harvested steps
        already in flight (pipelined dispatch). Returns False when nothing
        is left to decode — or, with a mask, when the pipeline must drain
        before growth/finish decisions can be made safely (note: blocks
        already grown for earlier slots in the pass stay attached; they
        remain owned by their requests either way)."""
        capacity = self.M * self.cfg.kv_block_size
        for i, s in enumerate(self.slots):
            if s is None or not s.ready:
                continue
            in_flight = bool(ahead_mask is not None and ahead_mask[i])
            pos_eff = s.pos + (K if in_flight else 0)
            if pos_eff + K + 1 > capacity:
                # within K tokens of the context capacity: finish now
                # rather than let the scan write past the block table
                # (bounded early stop, same K-granularity trade as EOS)
                if in_flight:
                    return False
                self._release_slot(s)
                self._finish_request(s, FinishReason.LENGTH)
                continue
            need = self._blocks_needed(pos_eff + K + 1)
            if need > len(s.blocks):
                new = self.kv_manager.pool.alloc_uninit(need - len(s.blocks))
                if new is None:
                    # out of KV memory: preempt (recompute) when other
                    # sequences keep the pool contended, else finish — but
                    # never with un-harvested tokens in flight
                    if in_flight:
                        return False
                    self._preempt_or_finish(s)
                    continue
                s.blocks.extend(new)
                self._block_tables[i, :len(s.blocks)] = s.blocks
        return any(s is not None and s.ready for s in self.slots)

    def _dispatch_pipelined(self, K: int):
        """Steady-state pipelined dispatch: chain off the in-flight batch's
        device tokens. Returns the new pending record, or None when the
        pipeline must drain first.

        Chaining requires the slot→request mapping to be IDENTICAL to the
        in-flight dispatch's: any churn (admission, finish, preemption,
        re-admission) drains the pipeline and restarts it from harvested
        host state. Stable decode phases — where the overlap matters — pay
        nothing; churn costs one un-overlapped dispatch."""
        prev = self._pending
        if prev["K"] != K:
            return None
        now = [s if (s is not None and s.ready) else None
               for s in self.slots]
        if any(now[i] is not prev["reqs"][i] for i in range(self.B)):
            return None
        mask = np.array([s is not None for s in now], dtype=bool)
        if not mask.any():
            return None
        if not self._prepare_multi(K, ahead_mask=mask):
            return None
        return self._dispatch_multi(K, chain=prev["toks"][-1], mask=mask,
                                    chained_from=prev.get("id"))

    def _dispatch_multi(self, K: int, chain=None, mask=None,
                        chained_from=None) -> dict:
        """Launch one K-step scan. ``mask`` flags slots chained off the
        in-flight dispatch: their input token comes from ``chain`` (device)
        and their positions/keys run K steps ahead of harvested host
        state; everything else feeds host-known last_tokens."""
        if mask is None:
            mask = np.zeros((self.B,), dtype=bool)
        steps = np.zeros((self.B,), np.int64)
        for i in range(self.B):
            s = self.slots[i]
            ahead = K if mask[i] else 0
            if s is None or not s.ready:
                self._tokens[i] = 0
                self._positions[i] = 0
                if s is None:
                    self._block_tables[i, :] = 0  # trash block
            else:
                self._tokens[i] = s.last_token
                self._positions[i] = s.pos + ahead
                steps[i] = s.key_step + ahead
        tables = self._tables_for_dispatch()
        # lane-prefill planned inputs: stateless from positions (which
        # already include the pipelined +K lookahead), so chained and
        # host-fed dispatches agree without extra bookkeeping. The common
        # no-lanes case reuses cached device-resident zeros (no per-dispatch
        # host allocation/transfer on the latency-sensitive path).
        planned = pmask = None
        for i, s in enumerate(self.slots):
            if s is None or not s.ready or s.lane_prompt is None:
                continue
            if planned is None:
                planned = np.zeros((K, self.B), np.int32)
                pmask = np.zeros((K, self.B), bool)
            pos0 = int(self._positions[i])
            n_pr = len(s.lane_prompt)
            for k in range(K):
                p = pos0 + k
                if p < n_pr:
                    planned[k, i] = s.lane_prompt[p]
                    pmask[k, i] = True
        self._step += K
        # jnp.array COPIES: jnp.asarray of a numpy buffer may alias it
        # zero-copy on CPU, and these mirrors are mutated by the next
        # iteration while a deferred-harvest dispatch may still be
        # executing — the single-step path never sees this because its
        # harvest blocks before any mutation
        host_tokens = jnp.array(self._tokens)
        tokens_in = (self._merge_jit(chain, host_tokens, jnp.array(mask))
                     if chain is not None else host_tokens)
        did = None
        if self.recorder is not None:
            did = self.recorder.next_dispatch_id()
            self.recorder.rec(
                "dispatch", id=did, K=K,
                chained_from=chained_from if chain is not None else None,
                mask=mask.copy(), tokens=self._tokens.copy(),
                positions=self._positions.copy(), tables=tables.copy(),
                seeds=self._seeds.copy(), steps=steps.copy(),
                temperature=self._samp["temperature"].copy(),
                top_k=self._samp["top_k"].copy(),
                top_p=self._samp["top_p"].copy(),
                **({"planned": planned.copy(),
                    "planned_mask": pmask.copy()}
                   if planned is not None else {}),
                reqs=[s.rid if (s is not None and s.ready) else None
                      for s in self.slots])
        if planned is None:
            planned_dev, pmask_dev = self._planned_zero
        else:
            planned_dev, pmask_dev = jnp.array(planned), jnp.array(pmask)
        toks_k, logprobs_k, self.kv = self._decode_k_jit(
            self.params, self.kv,
            tokens_in, jnp.array(self._positions),
            jnp.array(tables),
            jnp.array(self._seeds), jnp.array(steps),
            jnp.array(self._samp["temperature"]),
            jnp.array(self._samp["top_k"]),
            jnp.array(self._samp["top_p"]),
            planned_dev, pmask_dev)
        return {"toks": toks_k, "logprobs": logprobs_k, "K": K, "id": did,
                "reqs": [s if (s is not None and s.ready) else None
                         for s in self.slots]}

    def _harvest(self, pending: dict) -> None:
        """Apply one dispatch's results: emissions, seq bookkeeping,
        EOS/budget/cancel finishes. Device overrun past a finish — or past
        a slot whose request changed since dispatch — is discarded."""
        from ..runtime.faults import hit as _fault
        _fault("engine.harvest")    # chaos: loop-fatal boundary — an
        # injected error here kills the loop LOUDLY and _fail_pending
        # releases every slot/hold (asserted in tests/test_chaos.py)
        self.host_roundtrips += 1
        _t0 = time.monotonic()
        toks_k = np.asarray(pending["toks"])       # [K, B] — ONE host fetch
        logprobs_k = np.asarray(pending["logprobs"])
        self.host_stall_s += time.monotonic() - _t0
        K = pending["K"]
        applied = []
        for i, req in enumerate(pending["reqs"]):
            if req is None or self.slots[i] is not req:
                continue
            n0 = req.generated
            n_applied = 0
            input_tok = req.last_token
            for k in range(K):
                if req.cancelled:
                    self._release_slot(req)
                    self._finish_request(req, FinishReason.CANCELLED)
                    break
                in_prompt = (req.lane_prompt is not None
                             and req.pos < len(req.lane_prompt))
                if in_prompt:
                    input_tok = req.lane_prompt[req.pos]
                tok = int(toks_k[k, i])
                if req.seq is not None:
                    req.seq.append(input_tok)
                    req.registered_blocks = \
                        self.kv_manager.register_full_blocks(
                            req.blocks, req.seq, req.registered_blocks,
                            tenant=req.tenant or None)
                req.pos += 1
                req.key_step += 1
                n_applied += 1
                if in_prompt and req.pos < len(req.lane_prompt):
                    # mid-prompt planned step: the sampled token is
                    # discarded; the next input comes from the prompt
                    self.total_prefill_tokens += 1
                    continue
                if in_prompt:               # consumed the LAST prompt token
                    self.total_prefill_tokens += 1
                    req.lane_prompt = None  # plain decode from here on
                req.generated += 1
                req.last_token = tok
                self.total_decode_tokens += 1
                if req.first_token_time is None:
                    req.first_token_time = time.monotonic()
                self._emit(req, tok, float(logprobs_k[k, i]))
                self._maybe_finish_after_emit(req)
                if self.slots[i] is not req:
                    break                      # finished: drop device overrun
                input_tok = tok
            applied.append((i, req.rid, n_applied))
        if self.recorder is not None and pending.get("id") is not None:
            self.recorder.rec("harvest", id=pending["id"],
                              toks=toks_k.copy(), applied=applied)
        # flight record: one line per dispatch-harvest cycle. device_ms is
        # the measured host stall on the fetch (what the loop actually
        # waited for the device); host_gap_ms is everything since the last
        # cycle ended that was NOT that wait — scheduling, admissions,
        # python glue. Together they answer "device-bound or host-bound?"
        _now = time.monotonic()
        _stall = self.host_stall_s - self._flight_prev_stall_s
        self._flight_prev_stall_s = self.host_stall_s
        self.flight.record(
            "decode", K=K,
            batch_fill=len(applied),
            planned_tokens=K * len(applied),
            emitted=sum(n for _i, _r, n in applied),
            device_ms=round(1e3 * _stall, 3),
            host_gap_ms=round(
                max(1e3 * (_now - self._flight_cycle_end - _stall), 0.0),
                3))
        self._flight_cycle_end = _now

    # --------------------------------------------------------------- ragged
    def _ragged_step(self) -> None:
        """One unified ragged dispatch (engine/ragged.py): pack every
        ready slot's pending work — mid-prompt lanes contribute up to
        ragged_max_seq_rows prompt rows, decoding slots one chained
        token row or, with spec_k, a [1+k]-row speculative span — into
        a single token-capacity-filled batch, dispatch the ONE compiled
        ragged program, harvest.

        With ``decode_dispatch_pipeline`` a pure-decode dispatch defers
        its harvest one iteration: the next dispatch chains off the
        in-flight device tokens (the chained-sample merge — each
        chained row takes the previous dispatch's token at its slot's
        sample row), so the device→host fetch overlaps the next
        dispatch's compute exactly like the fused decode pipeline. Any
        churn — admissions, prefill lanes, spec drafts (which draft
        from HARVESTED history, the split path's rule), slot turnover,
        growth failure — drains the pipeline first and costs one
        un-overlapped dispatch.

        Block growth runs BEFORE packing at each slot's maximum
        possible row count this dispatch (the packer only ever shrinks
        a span, and over-grown blocks stay owned by their request —
        the _prepare_multi precedent); a slot that cannot grow preempts
        or finishes exactly as the split path would."""
        if self._ragged_pending is not None:
            nxt = self._ragged_dispatch_pipelined()
            prev, self._ragged_pending = self._ragged_pending, None
            self._harvest_ragged(prev)
            if nxt is not None:
                self._ragged_pending = nxt
                return
            if not any(s is not None and s.ready for s in self.slots):
                return
            # couldn't chain (churn / drafts due / growth failure):
            # fall through to a fresh host-fed dispatch against the
            # harvested state
        pending = self._ragged_dispatch_fresh()
        if pending is None:
            return
        if (self.cfg.decode_dispatch_pipeline
                and all(sq.mode == "decode"
                        for sq in pending["batch"].seqs)):
            # pure-decode dispatch: defer the harvest so the next
            # iteration can chain off it (prefill/spec spans harvest
            # synchronously — their bookkeeping gates the next packing)
            self._ragged_pending = pending
        else:
            self._harvest_ragged(pending)

    def _ragged_draft(self) -> Dict[int, tuple]:
        """Host-side n-gram drafts for every decoding slot with a live
        spec budget — the spec spans this dispatch will carry. Drafting
        reads HARVESTED history only (the _decode_step_spec rule), so
        the caller must have drained any pipelined dispatch."""
        drafts: Dict[int, tuple] = {}
        if self.drafter is None:
            return drafts
        for i, s in enumerate(self.slots):
            if (s is None or not s.ready or s.seq is None
                    or s.last_token < 0):
                continue
            if s.lane_prompt is not None and s.pos < len(s.lane_prompt):
                continue               # mid-prompt: decode hasn't begun
            k = self._req_spec_k(s)
            if k <= 0:
                continue
            d = self.drafter.draft(list(s.seq.tokens) + [s.last_token],
                                   k)
            if d:
                drafts[i] = (s, [int(t) for t in d[:k]])
        return drafts

    def _ragged_dispatch_fresh(self) -> Optional[dict]:
        """Draft, grow, pack and launch one host-fed ragged dispatch.
        Returns the pending record (un-harvested), or None when nothing
        was dispatched."""
        from .ragged import build_ragged_batch
        cfg = self.cfg
        Lmax = cfg.ragged_max_seq_rows
        capacity = self.M * cfg.kv_block_size
        drafts = self._ragged_draft()
        for i, s in enumerate(self.slots):
            if s is None or not s.ready:
                continue
            in_prompt = (s.lane_prompt is not None
                         and s.pos < len(s.lane_prompt))
            ent = drafts.get(i)
            n_draft = (len(ent[1]) if ent is not None and ent[0] is s
                       else 0)
            want = (min(len(s.lane_prompt) - s.pos, Lmax) if in_prompt
                    else 1 + n_draft)
            if s.pos + want + 1 > capacity:
                self._release_slot(s)
                self._finish_request(s, FinishReason.LENGTH)
                continue
            need = self._blocks_needed(s.pos + want + 1)
            if need > len(s.blocks):
                new = self.kv_manager.pool.alloc_uninit(
                    need - len(s.blocks))
                if new is None:
                    self._preempt_or_finish(s)
                    continue
                s.blocks.extend(new)
                self._block_tables[i, :len(s.blocks)] = s.blocks

        decode_rows = []
        prefill_lanes = []
        spec_lanes = []
        for i, s in enumerate(self.slots):
            if s is None or not s.ready:
                continue
            if s.lane_prompt is not None and s.pos < len(s.lane_prompt):
                prefill_lanes.append(
                    (i, s.lane_prompt[s.pos:s.pos + Lmax], s.pos))
                continue
            ent = drafts.get(i)
            # growth may have preempted/finished the drafted request —
            # keep drafts only for slots that still hold it
            if ent is not None and ent[0] is s:
                spec_lanes.append((i, [s.last_token] + ent[1], s.pos))
            else:
                decode_rows.append((i, s.last_token, s.pos))
        batch = build_ragged_batch(cfg.ragged_max_tokens, self.B,
                                   decode_rows, prefill_lanes, Lmax,
                                   spec_lanes=spec_lanes)
        if batch is None:
            return None
        return self._ragged_dispatch(batch)

    def _ragged_dispatch_pipelined(self) -> Optional[dict]:
        """Steady-state pipelined ragged dispatch: chain off the
        in-flight dispatch's device tokens. Returns the new pending
        record, or None when the pipeline must drain first (the
        _dispatch_pipelined contract: any churn restarts from harvested
        host state)."""
        prev = self._ragged_pending
        now = [s if (s is not None and s.ready) else None
               for s in self.slots]
        if any(now[i] is not prev["reqs"][i] for i in range(self.B)):
            return None
        live = [i for i in range(self.B) if now[i] is not None]
        if not live:
            return None
        for i in live:
            s = now[i]
            if s.lane_prompt is not None and s.pos < len(s.lane_prompt):
                return None        # admission churn mid-flight
            if (self.drafter is not None and s.seq is not None
                    and self._req_spec_k(s) > 0):
                # speculation drafts from HARVESTED state — drain, the
                # next fresh dispatch carries the spec span (the split
                # path forfeits the overlap the same way)
                return None
        # capacity/growth one token ahead; never finish/preempt with an
        # un-harvested token in flight — drain instead
        capacity = self.M * self.cfg.kv_block_size
        from .ragged import build_ragged_batch
        for i in live:
            s = now[i]
            if s.pos + 1 + 2 > capacity:
                return None
            need = self._blocks_needed(s.pos + 1 + 2)
            if need > len(s.blocks):
                new = self.kv_manager.pool.alloc_uninit(
                    need - len(s.blocks))
                if new is None:
                    return None
                s.blocks.extend(new)
                self._block_tables[i, :len(s.blocks)] = s.blocks
        batch = build_ragged_batch(
            self.cfg.ragged_max_tokens, self.B,
            [(i, now[i].last_token, now[i].pos + 1) for i in live],
            [], self.cfg.ragged_max_seq_rows)
        if batch is None:
            return None
        return self._ragged_dispatch(batch, chain=prev, ahead=1)

    def _ragged_dispatch(self, batch, chain: Optional[dict] = None,
                         ahead: int = 0) -> dict:
        """Launch one ragged dispatch over ``batch``. ``chain`` is the
        in-flight pending record whose device tokens feed this
        dispatch's decode rows (the chained-sample merge); ``ahead``
        is how many un-harvested tokens each chained slot runs ahead
        of host state (positions/key_steps were already advanced by
        the caller's packing). Returns the pending record."""
        cfg = self.cfg
        seeds = np.zeros((self.B + 1,), np.int64)
        temp = np.zeros((self.B + 1,), np.float32)
        top_k = np.zeros((self.B + 1,), np.int32)
        top_p = np.ones((self.B + 1,), np.float32)
        seeds[:self.B] = self._seeds
        temp[:self.B] = self._samp["temperature"]
        top_k[:self.B] = self._samp["top_k"]
        top_p[:self.B] = self._samp["top_p"]
        if self._ragged_row_sampled:
            # ROW steps: row r of a span keys at key_step + r — the
            # verify program's lockstep discipline; at a span's last
            # row this is the slot-sampled key by the skew convention
            steps = np.zeros((cfg.ragged_max_tokens,), np.int64)
            for sq in batch.seqs:
                s = self.slots[sq.slot]
                steps[sq.start:sq.start + sq.length] = (
                    s.key_step + ahead + np.arange(sq.length))
        else:
            steps = np.zeros((self.B + 1,), np.int64)
            for sq in batch.seqs:
                s = self.slots[sq.slot]
                # the LAST row of a span samples at the key_step the
                # split path would use there: lane's skew convention
                # makes that key_step + len - 1 (== key_step for
                # decode rows)
                steps[sq.slot] = s.key_step + ahead + sq.length - 1
        tables = np.zeros((self.B + 1, self.M), np.int32)
        tables[:self.B] = self._tables_for_dispatch()
        mask = srows = None
        if chain is not None:
            # chained-sample merge: each chained row takes the previous
            # dispatch's device token at its slot's sample row
            prev_batch = chain["batch"]
            mask = np.zeros((cfg.ragged_max_tokens,), bool)
            srows = np.zeros((cfg.ragged_max_tokens,), np.int32)
            for sq in batch.seqs:
                mask[sq.start] = True
                srows[sq.start] = (
                    int(prev_batch.sample_rows[sq.slot])
                    if self._ragged_row_sampled else sq.slot)
        self._step += 1
        did = None
        if self.recorder is not None:
            did = self.recorder.next_dispatch_id()
            self.recorder.rec(
                "ragged", id=did, tokens=batch.tokens.copy(),
                positions=batch.positions.copy(),
                row_slot=batch.row_slot.copy(),
                starts=batch.seq_starts.copy(),
                counts=batch.seq_counts.copy(),
                sample_rows=batch.sample_rows.copy(),
                tables=tables.copy(), seeds=seeds.copy(),
                steps=steps.copy(), temperature=temp.copy(),
                top_k=top_k.copy(), top_p=top_p.copy(),
                seqs=batch.seqs_meta(),
                chained_from=(chain["id"] if chain is not None
                              else None),
                mask=(mask.copy() if mask is not None else None),
                srows=(srows.copy() if srows is not None else None),
                reqs=[s.rid if (s is not None and s.ready) else None
                      for s in self.slots])
        # jnp.array COPIES the host mirrors (the _dispatch_multi
        # aliasing note): a deferred-harvest dispatch may still be
        # executing while the next iteration mutates them
        host_tokens = jnp.array(batch.tokens)
        if chain is not None:
            tokens_in = self._ragged_merge_jit(
                chain["toks"], jnp.array(srows), host_tokens,
                jnp.array(mask))
        else:
            tokens_in = host_tokens
        toks, logprobs, self.kv = self._ragged_jit(
            self.params, self.kv,
            tokens_in, jnp.array(batch.positions),
            jnp.array(tables), jnp.array(batch.row_slot),
            jnp.array(batch.seq_starts),
            jnp.array(batch.seq_counts),
            jnp.array(batch.sample_rows),
            jnp.array(seeds), jnp.array(steps),
            jnp.array(temp), jnp.array(top_k), jnp.array(top_p))
        self.ragged_dispatches += 1
        self.ragged_rows_total += batch.rows_used
        self.ragged_prefill_rows_total += batch.prefill_rows
        self.ragged_decode_rows_total += (batch.rows_used
                                          - batch.prefill_rows)
        if batch.mixed:
            self.ragged_mixed_dispatches += 1
        self.ragged_dispatches_saved += batch.dispatches_replaced - 1
        if batch.n_spec:
            self.spec_dispatches += 1
            self.spec_drafted_tokens += batch.spec_rows
            self.ragged_spec_rows += batch.spec_rows
        # cross-sequence wave prefetch accounting: the host-side mirror
        # of the kernel's parity chain over THIS dispatch's geometry
        # (attention.ragged_prefetch_counts — honest on CPU, where the
        # XLA fallback runs no kernel; the global-layer walk)
        from .attention import ragged_prefetch_counts
        pf = ragged_prefetch_counts(
            batch.seq_counts, batch.positions[batch.sample_rows] + 1,
            block_size=cfg.kv_block_size, blocks_per_table=self.M)
        self.ragged_first_waves += pf["first_waves"]
        self.ragged_prefetched_waves += pf["prefetched"]
        return {"batch": batch, "toks": toks, "logprobs": logprobs,
                "id": did, "prefetch": pf, "chained": chain is not None,
                "reqs": [s if (s is not None and s.ready) else None
                         for s in self.slots]}

    def _harvest_ragged(self, pending: dict) -> None:
        """Apply one ragged dispatch: per span, the consumed prompt
        rows' bookkeeping (hash chain, registration, pos/key_step —
        exactly the lane harvest's per-token walk) and, when the span
        ends in a sample (decode row, or the row consuming the LAST
        prompt token), the emission + finish checks of one decode
        step. Speculative spans walk their rows with LOCKSTEP
        acceptance (the _harvest_verify discipline verbatim: rejected
        draft rows roll back by rewind — pos never advances over them,
        and later dispatches rewrite every stale row before any query
        attends it).

        ``applied`` entries are (slot, rid, rows_applied, emitted) —
        emitted is a COUNT (spec spans emit one token per applied
        row)."""
        self.host_roundtrips += 1
        _t0 = time.monotonic()
        # [B+1] slot samples, or [capacity] row samples in the
        # spec-enabled row-sampled variant — ONE fetch either way
        toks = np.asarray(pending["toks"])
        logprobs = np.asarray(pending["logprobs"])
        self.host_stall_s += time.monotonic() - _t0
        batch = pending["batch"]
        row_sampled = self._ragged_row_sampled
        applied = []
        for sq in batch.seqs:
            i = sq.slot
            req = pending["reqs"][i]
            if req is None or self.slots[i] is not req:
                continue
            if req.cancelled:
                self._release_slot(req)
                self._finish_request(req, FinishReason.CANCELLED)
                continue
            if sq.mode == "spec":
                # lockstep-acceptance walk over the span's rows: row t
                # wrote inputs[t]'s KV — one decode step's bookkeeping;
                # reaching row t>0 accepted draft t
                inputs = batch.tokens[sq.start:sq.start + sq.length]
                n_applied = 0
                for t in range(sq.length):
                    tok = int(toks[sq.start + t])
                    req.seq.append(int(inputs[t]))
                    req.registered_blocks = \
                        self.kv_manager.register_full_blocks(
                            req.blocks, req.seq, req.registered_blocks,
                            tenant=req.tenant or None)
                    req.pos += 1
                    req.key_step += 1
                    req.generated += 1
                    req.last_token = tok
                    n_applied += 1
                    self.total_decode_tokens += 1
                    self.spec_emitted_tokens += 1
                    if t > 0:
                        self.spec_accepted_tokens += 1
                    if req.first_token_time is None:
                        req.first_token_time = time.monotonic()
                    self._emit(req, tok, float(logprobs[sq.start + t]))
                    self._maybe_finish_after_emit(req)
                    if self.slots[i] is not req:
                        break      # finished: drop the overrun rows
                    if (t + 1 < sq.length
                            and tok != int(inputs[t + 1])):
                        break      # draft rejected: rewind-rollback
                applied.append((i, req.rid, n_applied, n_applied))
                continue
            if sq.mode == "prefill":
                for t in range(sq.length):
                    req.seq.append(req.lane_prompt[req.pos])
                    req.registered_blocks = \
                        self.kv_manager.register_full_blocks(
                            req.blocks, req.seq, req.registered_blocks,
                            tenant=req.tenant or None)
                    req.pos += 1
                    req.key_step += 1
                self.total_prefill_tokens += sq.length
                if req.pos < len(req.lane_prompt):
                    applied.append((i, req.rid, sq.length, 0))
                    continue               # still mid-prompt: no sample
                req.lane_prompt = None     # plain decode from here on
            else:
                req.seq.append(int(req.last_token))
                req.registered_blocks = \
                    self.kv_manager.register_full_blocks(
                        req.blocks, req.seq, req.registered_blocks,
                        tenant=req.tenant or None)
                req.pos += 1
                req.key_step += 1
                self.total_decode_tokens += 1
            sample = (sq.start + sq.length - 1) if row_sampled else i
            tok = int(toks[sample])
            req.generated += 1
            req.last_token = tok
            if req.first_token_time is None:
                req.first_token_time = time.monotonic()
            self._emit(req, tok, float(logprobs[sample]))
            self._maybe_finish_after_emit(req)
            applied.append((i, req.rid, sq.length, 1))
        if self.recorder is not None and pending.get("id") is not None:
            self.recorder.rec("ragged_harvest", id=pending["id"],
                              toks=toks.copy(), applied=applied)
        _now = time.monotonic()
        _stall = self.host_stall_s - self._flight_prev_stall_s
        self._flight_prev_stall_s = self.host_stall_s
        # per-dispatch mode mix rides the flight recorder ring — the
        # /debug + llmctl trace dump view of how full, how mixed, how
        # speculative, and how well-prefetched each ragged dispatch ran
        pf = pending.get("prefetch") or {}
        self.flight.record(
            "ragged", rows=batch.rows_used,
            capacity=batch.capacity,
            fill=round(batch.fill_ratio, 4),
            prefill_rows=batch.prefill_rows,
            decode_rows=batch.rows_used - batch.prefill_rows,
            n_prefill=batch.n_prefill, n_decode=batch.n_decode,
            n_spec=batch.n_spec, spec_rows=batch.spec_rows,
            prefetch_first_waves=pf.get("first_waves", 0),
            prefetch_hits=pf.get("prefetched", 0),
            chained=bool(pending.get("chained")),
            mixed=batch.mixed,
            emitted=sum(e for _i, _r, _n, e in applied),
            device_ms=round(1e3 * _stall, 3),
            host_gap_ms=round(
                max(1e3 * (_now - self._flight_cycle_end - _stall),
                    0.0), 3))
        self._flight_cycle_end = _now

    # ---------------------------------------------------------- speculation
    def _req_spec_k(self, req: EngineRequest) -> int:
        """Effective draft budget for one request: its own knob (-1 =
        engine default, live-tunable via llmctl spec set-k) clamped to
        the compiled verify program's shape."""
        k = self.spec_k_live if req.spec_k < 0 else req.spec_k
        return max(0, min(int(k), self.cfg.spec_k))

    def _spec_candidates(self) -> bool:
        """True when a verify dispatch could be worth attempting. A
        mid-lane-prefill slot vetoes the whole batch: lanes feed planned
        prompt tokens through the K-step scan and the verify program has
        no planned-token plumbing — lanes last a handful of steps, after
        which speculation resumes."""
        any_spec = False
        for s in self.slots:
            if s is None or not s.ready:
                continue
            if s.lane_prompt is not None:
                return False
            if s.seq is not None and self._req_spec_k(s) > 0:
                any_spec = True
        return any_spec

    def _decode_step_spec(self) -> bool:
        """One speculative step: draft per slot (host-side n-gram lookup
        over the request's own history), score every slot's k drafts + 1
        bonus position in ONE verify dispatch, harvest with lockstep
        acceptance. Slots without drafts ride along as 1-row decode.
        Returns False when no slot drafted anything — the caller then
        runs the plain decode path (k=0 degeneracy)."""
        drafts: Dict[int, tuple] = {}
        for i, s in enumerate(self.slots):
            if (s is None or not s.ready or s.seq is None
                    or s.last_token < 0):
                continue
            k = self._req_spec_k(s)
            if k <= 0:
                continue
            d = self.drafter.draft(list(s.seq.tokens) + [s.last_token], k)
            if d:
                drafts[i] = (s, [int(t) for t in d[:k]])
        if not drafts:
            return False
        Tv = self.cfg.spec_k + 1
        if not self._prepare_multi(Tv):
            return True            # capacity churn consumed the step
        steps = np.zeros((self.B,), np.int64)
        tokens = np.zeros((self.B, Tv), np.int32)
        n_rows = np.zeros((self.B,), np.int32)
        dmap: Dict[int, List[int]] = {}
        for i in range(self.B):
            s = self.slots[i]
            if s is None or not s.ready:
                self._tokens[i] = 0
                self._positions[i] = 0
                if s is None:
                    self._block_tables[i, :] = 0  # trash block
                continue
            ent = drafts.get(i)
            # _prepare_multi may have finished/preempted the drafted
            # request — only keep drafts whose slot still holds it
            d = ent[1] if (ent is not None and ent[0] is s) else []
            self._tokens[i] = s.last_token
            self._positions[i] = s.pos
            steps[i] = s.key_step
            tokens[i, 0] = s.last_token
            if d:
                tokens[i, 1:1 + len(d)] = d
                dmap[i] = d
            n_rows[i] = 1 + len(d)
        if not dmap:
            return False           # every drafted slot churned away
        tables = self._tables_for_dispatch()
        self._step += 1
        did = None
        if self.recorder is not None:
            did = self.recorder.next_dispatch_id()
            self.recorder.rec(
                "verify", id=did, Tv=Tv, tokens=tokens.copy(),
                positions=self._positions.copy(), tables=tables.copy(),
                seeds=self._seeds.copy(), steps=steps.copy(),
                temperature=self._samp["temperature"].copy(),
                top_k=self._samp["top_k"].copy(),
                top_p=self._samp["top_p"].copy(),
                n_rows=n_rows.copy(),
                reqs=[s.rid if (s is not None and s.ready) else None
                      for s in self.slots])
        toks_T, lps_T, self.kv = self._verify_jit(
            self.params, self.kv, jnp.asarray(tokens),
            jnp.asarray(self._positions), jnp.asarray(tables),
            jnp.asarray(self._seeds), jnp.asarray(steps),
            jnp.asarray(self._samp["temperature"]),
            jnp.asarray(self._samp["top_k"]),
            jnp.asarray(self._samp["top_p"]))
        self.spec_dispatches += 1
        self.spec_drafted_tokens += sum(len(d) for d in dmap.values())
        self._harvest_verify({
            "toks": toks_T, "logprobs": lps_T, "drafts": dmap, "id": did,
            "reqs": [s if (s is not None and s.ready) else None
                     for s in self.slots]})
        return True

    def _harvest_verify(self, pending: dict) -> None:
        """Apply one verify dispatch: walk each slot's sampled rows with
        lockstep acceptance (spec/drafter.py accept_lockstep semantics,
        inlined here because each accepted row also carries one decode
        step's bookkeeping). Rejected draft rows roll back by REWIND:
        ``pos`` never advances over them, and every later dispatch
        rewrites a stale row before any query attends it (the same
        write-then-read ordering plain decode relies on)."""
        self.host_roundtrips += 1
        _t0 = time.monotonic()
        toks_T = np.asarray(pending["toks"])       # [B, Tv] — ONE fetch
        lps_T = np.asarray(pending["logprobs"])
        self.host_stall_s += time.monotonic() - _t0
        applied = []
        for i, req in enumerate(pending["reqs"]):
            if req is None or self.slots[i] is not req:
                continue
            d = pending["drafts"].get(i, [])
            inputs = [req.last_token] + d
            n_applied = 0
            accepted = 0
            for t in range(len(inputs)):
                if req.cancelled:
                    self._release_slot(req)
                    self._finish_request(req, FinishReason.CANCELLED)
                    break
                tok = int(toks_T[i, t])
                # row t wrote inputs[t]'s KV at this position — the
                # bookkeeping of exactly one decode step
                if req.seq is not None:
                    req.seq.append(int(inputs[t]))
                    req.registered_blocks = \
                        self.kv_manager.register_full_blocks(
                            req.blocks, req.seq, req.registered_blocks,
                            tenant=req.tenant or None)
                req.pos += 1
                req.key_step += 1
                req.generated += 1
                req.last_token = tok
                n_applied += 1
                self.total_decode_tokens += 1
                self.spec_emitted_tokens += 1
                if t > 0:          # reaching row t>0 accepted draft t
                    self.spec_accepted_tokens += 1
                    accepted += 1
                if req.first_token_time is None:
                    req.first_token_time = time.monotonic()
                self._emit(req, tok, float(lps_T[i, t]))
                self._maybe_finish_after_emit(req)
                if self.slots[i] is not req:
                    break          # finished: drop the overrun rows
                if t + 1 < len(inputs) and tok != int(inputs[t + 1]):
                    break          # draft rejected: rewind-rollback
            applied.append((i, req.rid, n_applied, accepted))
        if self.recorder is not None and pending.get("id") is not None:
            self.recorder.rec("spec_harvest", id=pending["id"],
                              toks=toks_T.copy(), applied=applied)
        self.flight.record(
            "verify", batch_fill=len(applied),
            spec_k=self.cfg.spec_k,
            emitted=sum(n for _i, _r, n, _a in applied),
            accepted=sum(a for _i, _r, _n, a in applied))

    # ----------------------------------------------------------- preemption
    def _preempt_or_finish(self, req: EngineRequest) -> None:
        """KV exhaustion policy: recompute preemption (vLLM-style) when the
        pool is contended, else finish.

        The preempted request releases its blocks and goes back to the
        waiting queue with every emitted token appended to its prompt — on
        re-admission the prefill recomputes (prefix reuse recovers whatever
        survived in the pool) and the next sampled token seamlessly
        continues the client's stream. With no other active sequence,
        recompute couldn't allocate any more than the request already holds,
        so the request finishes with LENGTH instead (the pool simply is too
        small for it)."""
        others = any(s is not None and s is not req for s in self.slots)
        budget_left = req.max_new_tokens - req.generated
        in_prompt = (req.lane_prompt is not None
                     and req.pos < len(req.lane_prompt))
        emitted_len = (0 if in_prompt or req.seq is None
                       else len(req.seq.tokens) - len(req.prompt))
        new_len = len(req.prompt) + emitted_len + 1
        bs = self.cfg.kv_block_size
        fits = (new_len < self.cfg.max_model_len
                and self._blocks_needed(new_len + bs) <= self.M)
        if not others or budget_left <= 0 or not fits:
            # no contention to wait out, no budget left, or the grown
            # prompt wouldn't fit a block table on re-admission
            self._release_slot(req)
            self._finish_request(req, FinishReason.LENGTH)
            return
        self.preemptions += 1
        logger.info("preempting %s after %d tokens (KV exhausted; "
                    "recompute on re-admission)", req.rid, req.generated)
        self.flight.record("preempt", rid=req.rid,
                           generated=req.generated)
        if req.trace is not None:
            # marks the trace for tail-based retention (the collector
            # keeps full trees for preempted requests)
            req.trace.event("engine.preempted", generated=req.generated)
        if self.recorder is not None:
            self.recorder.rec("preempt", rid=req.rid,
                              generated=req.generated)
        if in_prompt:
            # lane preempted mid-prompt: nothing was emitted — requeue
            # with the original prompt unchanged (progress recomputes; no
            # recompute boundary is recorded because no sampled token
            # depended on a re-derived state)
            self._release_slot(req)
            req.key_step += len(req.lane_prompt) - req.pos - 1  # undo skew
        else:
            req.numeric_boundaries.append(req.emitted_total)
            emitted = req.seq.tokens[len(req.prompt):] if req.seq else []
            self._release_slot(req)
            req.prompt = list(req.prompt) + list(emitted) + [req.last_token]
        req.lane_prompt = None
        req.max_new_tokens = budget_left
        req.seq = None               # admission rebuilds the hash chain
        req.precomputed = None       # any shipped KV described the old prompt
        req.slot = -1
        req.pos = 0
        req.generated = 0
        req.registered_blocks = 0
        req.prefix_hit_tokens = 0
        self.waiting.put_nowait(req)
        self._work_event.set()

    # ------------------------------------------------------------- finishes
    def _emit(self, req: EngineRequest, token: int, logprob: float) -> None:
        req.emitted_total += 1
        req.out_queue.put_nowait((token, logprob))

    def _maybe_finish_after_emit(self, req: EngineRequest) -> None:
        if req.last_token in req.eos_ids:
            self._release_slot(req)
            self._finish_request(req, FinishReason.EOS)
        elif req.generated >= req.max_new_tokens:
            self._release_slot(req)
            self._finish_request(req, FinishReason.LENGTH)
        elif req.cancelled:
            self._release_slot(req)
            self._finish_request(req, FinishReason.CANCELLED)

    def _release_slot(self, req: EngineRequest) -> None:
        if req.slot >= 0 and self.slots[req.slot] is req:
            self.slots[req.slot] = None
            self._block_tables[req.slot, :] = 0
            # reset sampler state: stale top_p/top_k would keep the
            # whole-batch `need_filter` predicate true and defeat the
            # sampler's sort-free fast path
            self._samp["temperature"][req.slot] = 0.0
            self._samp["top_k"][req.slot] = 0
            self._samp["top_p"][req.slot] = 1.0
        # write registered prefix blocks back to the host tier before the
        # device copies can be evicted; the extra hold keeps them pinned
        # until the async copy lands (released by the offload engine)
        if (self.offload_engine is not None and req.registered_blocks > 0
                and req.seq is not None):
            n = req.registered_blocks
            pinned = req.blocks[:n]
            self.kv_manager.pool.hold(pinned)
            try:
                self.offload_engine.enqueue(OffloadJob(
                    block_ids=list(pinned),
                    seq_hashes=list(req.seq.sequence_hashes[:n]),
                    tokens_hashes=list(req.seq.block_hashes[:n])))
            except Exception:
                # a failed enqueue must not strand the extra hold — the
                # pump only releases holds for jobs it actually received
                self.kv_manager.pool.release(pinned)
                raise
        if self.recorder is not None and req.blocks:
            self.recorder.rec("release", rid=req.rid,
                              blocks=list(req.blocks))
        self.kv_manager.pool.release(req.blocks)
        req.blocks = []

    def _finish_request(self, req: EngineRequest,
                        reason: FinishReason) -> None:
        if reason == FinishReason.CANCELLED:
            # client-stop vs deadline-budget-exhausted, counted apart
            # (nv_llm_requests_cancelled_total / _deadline_exceeded_total)
            ctx = req.ctx
            if (ctx is not None and not ctx.is_stopped
                    and getattr(ctx, "deadline_exceeded", False)):
                self.requests_deadline_exceeded_total += 1
            else:
                self.requests_cancelled_total += 1
            if req.trace is not None:
                req.trace.event("engine.cancelled",
                                generated=req.generated)
        self._inflight_reqs.pop(id(req), None)
        req.out_queue.put_nowait((_FINISH, reason))


FINISH_SENTINEL = _FINISH
