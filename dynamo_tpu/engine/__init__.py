"""TPU inference engine: JAX/XLA/Pallas models, paged KV, continuous batching."""

from .config import EngineConfig, ModelConfig
from .core import EngineCore, EngineRequest, ForwardPassMetrics

__all__ = ["EngineConfig", "ModelConfig", "EngineCore", "EngineRequest",
           "ForwardPassMetrics"]
