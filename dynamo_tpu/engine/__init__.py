"""TPU inference engine: JAX/XLA/Pallas models, paged KV, continuous batching."""

from .config import EngineConfig, ModelConfig
from .core import BlockAllocator, EngineCore, EngineRequest, ForwardPassMetrics

__all__ = ["EngineConfig", "ModelConfig", "EngineCore", "EngineRequest",
           "BlockAllocator", "ForwardPassMetrics"]
