"""Batched in-graph sampling: temperature / top-k / top-p / greedy, per-slot
parameters so one jitted decode step serves heterogeneous requests.

The reference carries these as SamplingOptions (protocols/common.rs) into the
external engine; here they become dense per-slot arrays so the whole sampler
lives inside the decode XLA program (no logits transfer off-device — only
sampled ids and chosen logprobs leave HBM).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

NEG_INF = -1e30


@dataclasses.dataclass
class SlotSampling:
    """Host-side staging of per-slot sampling params (converted to arrays)."""

    temperature: float = 0.0  # 0 → greedy
    top_k: int = 0            # 0 → disabled
    top_p: float = 1.0
    seed: int = 0

    @classmethod
    def from_options(cls, opts, default_temperature: float = 0.7) -> "SlotSampling":
        if opts is None:
            return cls(temperature=default_temperature)
        if getattr(opts, "greedy", False):
            return cls(temperature=0.0, seed=opts.seed or 0)
        t = opts.temperature if opts.temperature is not None else default_temperature
        return cls(temperature=float(t),
                   top_k=int(opts.top_k or 0),
                   top_p=float(opts.top_p if opts.top_p is not None else 1.0),
                   seed=int(opts.seed or 0))


def pack_sampling(slots: list) -> dict:
    """[SlotSampling] → dict of np arrays for the jitted sampler."""
    return {
        "temperature": np.array([s.temperature for s in slots], np.float32),
        "top_k": np.array([s.top_k for s in slots], np.int32),
        "top_p": np.array([s.top_p for s in slots], np.float32),
    }


def sample_tokens(logits: jax.Array, keys: jax.Array,
                  temperature: jax.Array, top_k: jax.Array,
                  top_p: jax.Array) -> tuple[jax.Array, jax.Array]:
    """logits: [B, V]; keys: [B] PRNG keys; per-slot params [B].
    Returns (tokens [B] int32, logprobs [B] float32 of the chosen token
    under the unscaled distribution)."""
    B, V = logits.shape
    logprobs_all = jax.nn.log_softmax(logits, axis=-1)

    greedy_tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    temp = jnp.maximum(temperature, 1e-6)[:, None]
    scaled = logits / temp
    gumbel = jax.vmap(
        lambda k: jax.random.gumbel(k, (V,), dtype=jnp.float32))(keys)

    def _plain(_):
        # no top-k/top-p anywhere in the batch: Gumbel-argmax IS exact
        # temperature sampling, and skips the [B, V] argsort that would
        # otherwise dominate the decode step at 100k+ vocabs
        return jnp.argmax(scaled + gumbel, axis=-1).astype(jnp.int32)

    def _filtered(_):
        order = jnp.argsort(-scaled, axis=-1)                   # [B, V] desc
        sorted_logits = jnp.take_along_axis(scaled, order, axis=-1)
        sorted_probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(sorted_probs, axis=-1)
        keep_p = (cum - sorted_probs) < top_p[:, None]
        k_eff = jnp.where(top_k > 0, top_k, V)[:, None]
        keep_k = jnp.arange(V)[None, :] < k_eff
        keep = keep_p & keep_k
        keep = keep.at[:, 0].set(True)
        masked = jnp.where(keep, sorted_logits, NEG_INF)
        sorted_gumbel = jnp.take_along_axis(gumbel, order, axis=-1)
        choice_sorted = jnp.argmax(masked + sorted_gumbel, axis=-1)
        return jnp.take_along_axis(
            order, choice_sorted[:, None], axis=-1)[:, 0].astype(jnp.int32)

    need_filter = jnp.any((top_p < 1.0) | (top_k > 0))
    sampled_tok = jax.lax.cond(need_filter, _filtered, _plain, None)

    tok = jnp.where(temperature <= 0.0, greedy_tok, sampled_tok)
    chosen_logprob = jnp.take_along_axis(
        logprobs_all, tok[:, None], axis=-1)[:, 0]
    return tok, chosen_logprob


def make_slot_keys(base_seed: int, slot_seeds: jax.Array,
                   steps: jax.Array) -> jax.Array:
    """Deterministic per-(request-seed, request-step) PRNG keys: a request
    with an explicit seed reproduces its stream regardless of which slot it
    lands in or what else is batched with it. `steps` is each slot's OWN
    generated-token count (not a global counter)."""
    base = jax.random.PRNGKey(base_seed)
    steps = jnp.broadcast_to(jnp.asarray(steps), slot_seeds.shape)

    def mk(seed, step):
        return jax.random.fold_in(jax.random.fold_in(base, seed), step)

    return jax.vmap(mk)(slot_seeds, steps)
