"""Pallas grouped-dequant matmul: x @ W for packed-int4 weights.

The XLA lowering of the grouped-int4 contraction is a batched dot whose
per-group partial [N, D/128, F] MATERIALIZES in HBM — measured ~17 GB of
activation traffic per 70B-shard decode step (21.4 ms, slower than
int8). This kernel is the reason int4 wins: it streams the PACKED
weights (two signed nibbles per int8 byte, quant.pack_int4_rows) from
HBM at 0.5 B/elem, splits nibbles on the VPU in VMEM, runs two MXU dots
per 128-row group (even/odd contraction rows — no interleave needed),
and folds the per-(group, out-channel) scale into the f32 accumulator.
Nothing but x and y ever touches HBM at full width.

Reference analog: the CUDA ecosystem's weight-only-quant GEMMs (AWQ /
Marlin kernels) that the reference reaches through its engines; here it
is a first-class Pallas kernel, the same way attention.py owns paged
attention.

Grid: (n_tiles, f_tiles, d_steps), d innermost/sequential — each d step
covers GD groups (so every block meets Mosaic's >=8x128 tiling; GD is
the largest of 8/4/2 dividing the group count), the f32 accumulator
lives in VMEM scratch across the d sweep, and the output writes once
per (n, f) tile. Scales ride as one full-row [nd, TF] block per f tile
(tiny) with a dynamic sublane load per group. Pallas double-buffers the
HBM block fetches.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jax<0.5 names it TPUCompilerParams
_CompilerParams = getattr(pltpu, "CompilerParams",
                          getattr(pltpu, "TPUCompilerParams", None))

GROUP = 128          # contraction rows per scale group (quant.GROUP_SIZE)
_HG = GROUP // 2     # packed bytes (and even/odd x columns) per group

__all__ = ["grouped_int4_matmul", "grouped_kernel_eligible"]


def _kernel(xe_ref, xo_ref, w_ref, s_ref, o_ref, acc_ref,
            *, nd_steps: int, gd: int):
    """One (n, f, d) grid step: for each of the gd groups in this step,
    acc += (xe_g @ lo_g + xo_g @ hi_g) * s_row_g.

    xe/xo: [TN, gd*_HG] this step's even/odd contraction rows of x;
    w: [gd*_HG, TF] packed bytes; s: [nd, TF] ALL group scales for this
    f tile; o: [TN, TF]; acc scratch [TN, TF] f32.
    """
    d = pl.program_id(2)

    @pl.when(d == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    xe = xe_ref[...]
    xo = xo_ref[...]
    # nibble split in-register: Mosaic has no int8 shifts (arith.shli on
    # i8 fails to legalize) — widen the tile to i32 for the shifts and
    # narrow straight into the dot dtype
    w = w_ref[...].astype(jnp.int32)
    lo = (jnp.left_shift(w, 28) >> 28).astype(xe.dtype)
    hi = (w >> 4).astype(xo.dtype)
    acc = acc_ref[...]
    for g in range(gd):
        sl = slice(g * _HG, (g + 1) * _HG)
        part = (jax.lax.dot(xe[:, sl], lo[sl],
                            preferred_element_type=jnp.float32)
                + jax.lax.dot(xo[:, sl], hi[sl],
                              preferred_element_type=jnp.float32))
        srow = s_ref[pl.ds(d * gd + g, 1), :]          # [1, TF] dynamic
        acc = acc + part * srow
    acc_ref[...] = acc

    @pl.when(d == nd_steps - 1)
    def _flush():
        o_ref[...] = acc_ref[...].astype(o_ref.dtype)


def _gd_for(nd: int) -> int:
    for gd in (8, 4, 2):
        if nd % gd == 0:
            return gd
    return 0


def grouped_kernel_eligible(n: int, d: int, f: int, group: int) -> bool:
    """Shapes the kernel tiles: the group-128 encoding, an even group
    count (so x/w blocks reach 128 lanes), and a lane-aligned output
    width. Everything else takes the XLA path."""
    return (group == GROUP and d % GROUP == 0 and f % 128 == 0
            and _gd_for(d // GROUP) > 0)


def grouped_int4_matmul(x: jax.Array, packed: jax.Array, scale: jax.Array,
                        *, interpret: bool = False) -> jax.Array:
    """x [N, D] @ packed-int4 W: packed [D/2, F] int8 (pack_int4_rows
    layout: byte d holds rows 2d/2d+1), scale [D/GROUP, F] f32.
    Returns [N, F] in x.dtype."""
    N, D = x.shape
    _half, F = packed.shape
    nd = D // GROUP
    gd = _gd_for(nd)

    # even/odd contraction rows, laid out group-major so each grid step
    # reads one contiguous [gd*_HG] span: [N, nd*_HG]
    xs = x.reshape(N, nd, _HG, 2)
    xe = xs[..., 0].reshape(N, D // 2)
    xo = xs[..., 1].reshape(N, D // 2)

    TN = min(256, max(8, ((N + 7) // 8) * 8))
    Np = ((N + TN - 1) // TN) * TN
    if Np > N:
        pad = Np - N
        xe = jnp.concatenate([xe, jnp.zeros((pad, D // 2), xe.dtype)])
        xo = jnp.concatenate([xo, jnp.zeros((pad, D // 2), xo.dtype)])
    # widest lane tile that divides F (measured on v5e at the 70B shard
    # gate/up shape: TF=1024 0.154 ms/layer-matmul vs 512's 0.171)
    TF = next(t for t in (1024, 512, 256, 128) if F % t == 0)

    grid = (Np // TN, F // TF, nd // gd)
    out = pl.pallas_call(
        functools.partial(_kernel, nd_steps=nd // gd, gd=gd),
        grid=grid,
        in_specs=[
            pl.BlockSpec((TN, gd * _HG), lambda n, f, d: (n, d)),
            pl.BlockSpec((TN, gd * _HG), lambda n, f, d: (n, d)),
            pl.BlockSpec((gd * _HG, TF), lambda n, f, d: (d, f)),
            pl.BlockSpec((nd, TF), lambda n, f, d: (0, f)),
        ],
        out_specs=pl.BlockSpec((TN, TF), lambda n, f, d: (n, f)),
        out_shape=jax.ShapeDtypeStruct((Np, F), x.dtype),
        scratch_shapes=[pltpu.VMEM((TN, TF), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(xe, xo, packed, scale.astype(jnp.float32))
    return out[:N]
