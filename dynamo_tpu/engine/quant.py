"""Weight-only int8/int4 quantization for the serving engine.

The reference's headline configs serve quantized models through its
external engines (BASELINE: R1-Distill-Llama-70B FP8 on vLLM/TRT-LLM;
docs/architecture.md benchmarks; AWQ/int4 checkpoints via vLLM). Our
engine owns the model, so the analog is native: weights are stored
int8/int4 and dequantized inside the matmul — XLA reads the narrow dtype
from HBM and fuses the convert+scale into the MXU op, cutting the
per-decode-step weights-read floor (the dominant cost at small batch)
2×/4× vs bf16. int4 HBM streaming measured real on v5e: ~0.5 B/elem
effective, 1.9× the int8 read rate (PERF.md int4 probe).

int8 scheme: symmetric absmax per output channel (the last axis of a
stacked [L, D, F] weight; per row for the [V, D] embedding so the token
gather dequantizes cheaply and a tied lm head reuses the same scales per
column; per (layer, expert, out-channel) for the stacked MoE expert
tensors — for mixtral-class models the experts are the bulk of the
weights). Norms, biases, and the MoE router stay in the load dtype.

int4 scheme (AWQ-style group quantization, minus the activation-aware
calibration which needs calibration data): one scale per
(stack axes, contraction GROUP of 128, out-channel) — per-channel-only
int4 is too coarse for real checkpoints' outlier channels. The grouped
matmul contracts per group and applies scales between the two einsums
(:func:`mm`). Applied to the dense layer matmuls + lm_head; the
embedding stays int8 (its per-row gather scheme is already cheap) and
MoE experts stay int8 (the grouped expert-einsum generalization isn't
worth its complexity until a MoE config is weights-read-bound at int8).

int4 STORAGE is packed — two signed nibbles per int8 byte, adjacent
contraction rows paired — because S4 jax.Arrays cannot cross the jit
boundary on the axon/TPU backend (a relayout device_put recursion bug;
measured). Each jitted program calls :func:`unpack_params` ONCE at its
top: bitcast int8→int4 ([.., D/2, F] → [.., D/2, F, 2]), un-interleave,
and an optimization_barrier pins the unpacked S4 buffer, so a K-step
decode dispatch pays one ~weights-pass unpack and then K steps read S4
at packed (0.5 B/elem) bandwidth. Measured on v5e (8192×14336, B=32,
K=32): 0.040 ms/step incl. amortized unpack vs int8's 0.093 — the win
scales with decode_steps_per_dispatch.
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp

__all__ = ["QuantizedArray", "quantize_array", "quantize_array_grouped",
           "quantize_params", "mm", "qeinsum", "GROUP_SIZE",
           "unpack_params", "pack_int4_rows", "unpack_int4_rows"]

# int4 contraction-group width (AWQ convention; divides every serving
# model's hidden/intermediate dims — falls back to one whole-axis group
# for tiny test geometries)
GROUP_SIZE = 128


@jax.tree_util.register_pytree_node_class
class QuantizedArray:
    """int8/int4 tensor + f32 scale; dequantizes as q * scale.

    ``group`` == 0: scale is broadcast-shaped against q (per-channel
    int8). ``group`` > 0: logical q is [..., D, F] with one scale per
    (contraction group, out-channel) — scale [..., D/group, F] — the
    grouped-int4 encoding (module docstring). ``packed4``: q holds two
    signed nibbles per byte, [..., D/2, F] int8 — unpack with
    :func:`unpack_int4_rows` (or the tree-level :func:`unpack_params`)
    before computing. ``no_kernel``: the Pallas grouped matmul
    (quant_matmul.py) must not serve this leaf — set by shard_params
    under any multi-device mesh, where pallas_call has no GSPMD
    partitioning rule."""

    def __init__(self, q: jax.Array, scale: jax.Array, group: int = 0,
                 packed4: bool = False, no_kernel: bool = False):
        self.q = q
        self.scale = scale
        self.group = group
        self.packed4 = packed4
        self.no_kernel = no_kernel

    @property
    def shape(self):           # the LOGICAL (unpacked) shape
        if self.packed4:
            s = self.q.shape
            return s[:-2] + (s[-2] * 2, s[-1])
        return self.q.shape

    @property
    def dtype(self):           # the *logical* dtype callers compute in
        return self.scale.dtype

    def __getitem__(self, idx) -> "QuantizedArray":
        """LEADING-axis (layer) indexing only: q and every scale layout
        share their leading dims (per-channel [L, 1, F], grouped
        [L, D/g, F], expert [L, E, 1, F]), so the same index applies to
        both. Used by the deepseek hybrid scans, which split stacked
        weights into a dense prefix and a MoE suffix."""
        return QuantizedArray(self.q[idx], self.scale[idx],
                              group=self.group, packed4=self.packed4,
                              no_kernel=self.no_kernel)

    def unpacked(self) -> "QuantizedArray":
        if not self.packed4:
            return self
        return QuantizedArray(unpack_int4_rows(self.q), self.scale,
                              group=self.group)

    def dequantize(self, dtype=None) -> jax.Array:
        w = self.unpacked()
        if w.group:
            s = jnp.repeat(w.scale, w.group, axis=-2)
            out = w.q.astype(w.scale.dtype) * s
        else:
            out = w.q.astype(w.scale.dtype) * w.scale
        return out.astype(dtype) if dtype is not None else out

    def tree_flatten(self):
        return (self.q, self.scale), (self.group, self.packed4,
                                      self.no_kernel)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children, group=aux[0], packed4=aux[1],
                   no_kernel=aux[2])

    def __repr__(self):
        return (f"QuantizedArray(q={self.q.shape}, "
                f"scale={self.scale.shape}, group={self.group}, "
                f"packed4={self.packed4})")


def quantize_array(w: jax.Array, *,
                   keep_axes: tuple = (-1,)) -> QuantizedArray:
    """Symmetric absmax int8, one scale per coordinate of ``keep_axes``
    (reduced over every other axis; scale stays broadcast-shaped). Stacked
    per-layer weights pass keep_axes=(0, -1) so each (layer, out-channel)
    pair gets its own scale."""
    w32 = w.astype(jnp.float32)
    keep = {a % w.ndim for a in keep_axes}
    reduce_axes = tuple(a for a in range(w.ndim) if a not in keep)
    absmax = jnp.max(jnp.abs(w32), axis=reduce_axes, keepdims=True)
    scale = jnp.maximum(absmax, 1e-12) / 127.0
    q = jnp.clip(jnp.round(w32 / scale), -127, 127).astype(jnp.int8)
    return QuantizedArray(q, scale.astype(jnp.float32))


def pack_int4_rows(q: jax.Array) -> jax.Array:
    """int4-valued int8 [..., D, F] (D even) -> packed int8 [..., D/2, F]:
    adjacent contraction rows 2d/2d+1 become the low/high nibble of one
    byte — the layout jax.lax.bitcast_convert_type(int8 -> int4)
    reverses (low nibble first; verified identical on CPU and TPU)."""
    # all-int8 arithmetic: wider intermediates would materialize int32
    # copies of the whole weight tensor during streaming init (an OOM at
    # 70B scale); int8 shifts wrap to exactly the bit patterns we want
    lo = q[..., 0::2, :] & jnp.int8(0xF)
    hi = jnp.left_shift(q[..., 1::2, :], 4)
    return lo | hi


def unpack_int4_rows(packed: jax.Array) -> jax.Array:
    """Inverse of :func:`pack_int4_rows`: packed int8 [..., D/2, F] ->
    int4 [..., D, F]. A bitcast (free view of the packed bytes) plus one
    un-interleave — call OUTSIDE per-step loops so a K-step dispatch
    pays it once (module docstring)."""
    # arithmetic nibble split instead of bitcast_convert_type(int8→int4):
    # the bitcast lowering is broken on jax 0.4.x CPU (rank verifier
    # rejects it); int8 shifts sign-extend, so lo/hi land already signed
    lo = jnp.right_shift(jnp.left_shift(packed, 4), 4)      # low nibble
    hi = jnp.right_shift(packed, 4)                         # high nibble
    un = jnp.stack([lo, hi], axis=-2)                       # [.., D/2, 2, F]
    s = packed.shape
    return un.reshape(s[:-2] + (s[-2] * 2, s[-1])).astype(jnp.int4)


def _kernel_serves(w: "QuantizedArray") -> bool:
    """True when the Pallas grouped matmul (quant_matmul.py) will
    consume this packed leaf directly — the ONE gate shared by
    unpack_params (which then leaves it packed) and mm (which then calls
    the kernel), so the two can't disagree.

    Default ON (DYN_INT4_KERNEL=0 falls back to the XLA grouped path):
    the XLA path materializes a [T, D/128, F] partial that grows with
    prefill length — measured 14 GB at a 7.7K-token 8B prefill, an OOM
    on the exact capacity/long-context configs int4 exists for — while
    the kernel streams with no partial. The kernel is ~15-20% slower at
    decode than the XLA grouped form (PERF.md int4 sections), a fair
    price for actually fitting."""
    import os
    if os.environ.get("DYN_INT4_KERNEL", "1") == "0":
        return False
    from .attention import _on_tpu
    from .quant_matmul import grouped_kernel_eligible
    if not (w.packed4 and not w.no_kernel and _on_tpu()):
        return False
    *_lead, d, f = w.shape
    return grouped_kernel_eligible(0, d, f, w.group)


def unpack_params(params: Dict[str, object]) -> Dict[str, object]:
    """Unpack packed-int4 leaves of a params tree into their S4 form,
    behind an optimization_barrier so XLA materializes the unpacked
    buffer once per program instead of re-deriving it per use. Call at
    the TOP of each jitted model program (engine/core.py does); outside
    jit the packed tree is the one that crosses boundaries (S4 arrays
    cannot — module docstring). Leaves the grouped Pallas kernel will
    serve stay PACKED — the kernel streams the packed bytes itself, so
    no unpack pass (or S4 copy) exists at all on that path."""
    out: Dict[str, object] = {}
    for k, v in params.items():
        if isinstance(v, QuantizedArray) and v.packed4 \
                and not _kernel_serves(v):
            u = v.unpacked()
            out[k] = QuantizedArray(jax.lax.optimization_barrier(u.q),
                                    u.scale, group=u.group)
        else:
            out[k] = v
    return out


def quantize_array_grouped(w: jax.Array, group: int = GROUP_SIZE,
                           bits: int = 4) -> QuantizedArray:
    """Symmetric absmax with one scale per (leading stack axes,
    contraction group, out-channel): w [..., D, F] -> logical q
    [..., D, F] int4/int8, scale [..., D/group, F] f32. When ``group``
    does not divide D the whole axis becomes one group (tiny test
    geometries). bits=4 with even D returns PACKED storage
    (pack_int4_rows); odd-D tiny geometries stay unpacked int8-held."""
    *_lead, D, F = w.shape
    if D % group != 0:
        group = D
    gn = D // group
    qmax = 2 ** (bits - 1) - 1
    w32 = w.astype(jnp.float32).reshape(w.shape[:-2] + (gn, group, F))
    absmax = jnp.max(jnp.abs(w32), axis=-2)            # [..., gn, F]
    scale = jnp.maximum(absmax, 1e-12) / qmax
    q = jnp.clip(jnp.round(w32 / scale[..., None, :]), -qmax, qmax)
    q = q.reshape(w.shape).astype(jnp.int8)
    scale = scale.astype(jnp.float32)
    if bits == 4 and D % 2 == 0:
        return QuantizedArray(pack_int4_rows(q), scale, group=group,
                              packed4=True)
    return QuantizedArray(q, scale, group=group)


def _mm_grouped(x: jax.Array, w: QuantizedArray) -> jax.Array:
    """x [..., D] @ grouped-quantized w [D, F]: contract per group, then
    fold the [gn, F] scales in a second (tiny) contraction. XLA reads the
    int4/int8 payload from HBM and converts in-register; under a tp mesh
    both contractions partition cleanly (q and scale shard together on
    either axis). Packed weights unpack here for direct callers —
    per-step loops should pre-unpack the whole tree (unpack_params)."""
    if w.packed4 and _kernel_serves(w):
        from .quant_matmul import grouped_int4_matmul
        x2 = x[None, :] if x.ndim == 1 else x
        y = grouped_int4_matmul(x2, w.q, w.scale)
        return y[0] if x.ndim == 1 else y
    if w.packed4:
        w = w.unpacked()
    D = x.shape[-1]
    gn = D // w.group
    xg = x.reshape(x.shape[:-1] + (gn, w.group))
    qg = w.q.astype(x.dtype).reshape(gn, w.group, w.q.shape[-1])
    part = jnp.einsum("...gd,gdf->...gf", xg, qg)
    return jnp.einsum("...gf,gf->...f", part, w.scale.astype(x.dtype))


def mm(x: jax.Array, w) -> jax.Array:
    """x @ w for a plain array or a QuantizedArray (dequant fused into the
    matmul: XLA reads int8/int4 and converts in-register)."""
    if isinstance(w, QuantizedArray):
        if w.group:
            return _mm_grouped(x, w)
        y = x @ w.q.astype(x.dtype)
        return y * w.scale.astype(x.dtype).reshape(w.scale.shape[-1])
    return x @ w


def qeinsum(spec: str, a: jax.Array, w) -> jax.Array:
    """einsum with the same dequant-fuse rule as :func:`mm` for batched
    weights (MoE experts): contract on int8 converted in-register, apply
    the broadcast-shaped scale after the contraction. One owner for the
    dequant semantics — keep in sync with mm by calling, not copying."""
    if isinstance(w, QuantizedArray):
        if w.group:
            raise NotImplementedError(
                "grouped-quantized weights are not supported in qeinsum "
                "(MoE experts stay int8 under --quantization int4; see "
                "module docstring)")
        return jnp.einsum(spec, a, w.q.astype(a.dtype)) \
            * w.scale.astype(a.dtype)
    return jnp.einsum(spec, a, w)


# Weight names quantized (stacked per-layer [L, D, F] → per (L, F) scales).
_LAYER_MATMULS = ("wq", "wk", "wv", "wo", "gate", "up", "down",
                  # qwen2_moe shared expert (dense swiglu; the sigmoid
                  # sh_router stays full precision like the MoE router)
                  "sh_gate", "sh_up", "sh_down",
                  # MLA (models/mla.py): the q-LoRA pair, the latent
                  # down-projection, and the deepseek hybrid dense
                  # prefix — all consumed through mm(). wkv_b stays
                  # full precision DELIBERATELY: the absorbed decode
                  # contracts it raw in einsums (_split_wkv_b), and its
                  # [rank, H*(dn+dv)] bytes are small
                  "wq_a", "wq_b", "wkv_a",
                  "dense_gate", "dense_up", "dense_down")
# MoE expert tensors [L, E, D, F] → per (L, E, out-channel) scales. For
# mixtral-class models the experts ARE the weights, so leaving them bf16
# would forfeit the whole int8 HBM-read win; the router stays full
# precision (tiny, and routing is precision-sensitive).
_MOE_MATMULS = ("moe_gate", "moe_up", "moe_down")


def quantize_params(params: Dict[str, jax.Array],
                    include_embed: bool = True,
                    bits: int = 8) -> Dict[str, object]:
    """Return a params tree with matmul weights quantized.

    bits=8:
    - ``layers.{wq,wk,wv,wo,gate,up,down}``: per-(layer, out-channel).
    bits=4: the same layer matmuls, int4 with per-(group-of-128,
    out-channel) scales (module docstring).
    Either way:
    - ``lm_head`` ([D, V]): int8 per out-channel (vocab widths don't
      lane-align for the int4 kernel; the int8 head keeps its fused
      Pallas kernel).
    - ``embed`` ([V, D], optional): int8 per ROW (= per token vector), so
      the embedding gather dequantizes with one scale per token and a
      TIED lm head (x @ embed.T) gets per-column scales from the same
      tensor.
    - ``layers.{moe_gate,moe_up,moe_down}`` ([L, E, D, F]): int8 per
      (layer, expert, out-channel) — for MoE models the experts are the
      bulk of the weights (models/llama.py moe_mlp dequant-fuses them).
    - norms / biases / MoE router untouched.
    """
    tied = "lm_head" not in params
    out: Dict[str, object] = {}
    for name, w in params.items():
        out.update(_quantize_named(name, w, include_embed, tied, bits))
    return out


def _quantize_named(name: str, w: jax.Array, include_embed: bool,
                    tied: bool, bits: int = 8) -> Dict[str, object]:
    """The per-tensor dispatch shared by quantize_params (whole-tree,
    eager) and init_params_quantized (streaming, one jit per tensor)."""
    suffix = name.split(".", 1)[1] if name.startswith("layers.") else name
    if name.startswith("layers.") and suffix in _LAYER_MATMULS:
        if bits == 4:
            # stacked [L, D, F]: int4, scale [L, D/128, F]
            return {name: quantize_array_grouped(w, bits=4)}
        # stacked [L, D, F]: per (layer, out-channel) → scale [L, 1, F]
        return {name: quantize_array(w, keep_axes=(0, -1))}
    if name.startswith("layers.") and suffix in _MOE_MATMULS:
        # stacked [L, E, D, F]: per (layer, expert, out-channel)
        # → scale [L, E, 1, F], which broadcasts over the expert
        # einsums' batched-N axis after the per-layer slice.
        # (int8 even under bits=4 — module docstring)
        return {name: quantize_array(w, keep_axes=(0, 1, -1))}
    if name == "lm_head":
        # int8 even under bits=4: vocab widths (e.g. 128256/8) don't
        # lane-align for the grouped kernel, the XLA grouped fallback
        # materializes a [N, D/128, V] partial bigger than the int8 read
        # it saves, and int8 keeps the fused Pallas head kernel
        return {name: quantize_array(w, keep_axes=(-1,))}
    if name == "embed" and include_embed:
        # int8 per-row: scale shape [V, 1] (bits=4 keeps the embed int8 —
        # the gather reads one row per token, not the whole tensor)
        out = {name: quantize_array(w, keep_axes=(0,))}
        if tied:
            # tied head: materialize a PRE-TRANSPOSED int8 head —
            # `x @ q.T` of an int8 matrix defeats XLA's transpose
            # fusion and measured 2x slower than the bf16 tied path
            # at small batch; the [D, V] copy reads int8 in natural
            # orientation instead (263MB vs 525MB bf16 per step for
            # llama-1B)
            out["lm_head"] = quantize_array(w.T, keep_axes=(-1,))
        return out
    return {name: w}


def init_params_quantized(cfg, key: jax.Array, dtype=jnp.bfloat16,
                          include_embed: bool = True,
                          bits: int = 8) -> Dict[str, object]:
    """Random-init + quantize one stacked tensor at a time, entirely
    inside a jit, so the full bf16 tree is never materialized.

    init_params followed by quantize_params peaks at the whole bf16 tree
    (16 GB for Llama-3-8B geometry — an OOM on one 16 GB v5e chip before
    quantization even starts). Here each tensor's init→absmax→round
    pipeline is one jitted program whose only output is the int8 payload
    + f32 scales, so XLA frees the bf16/f32 intermediates inside the
    program; peak HBM ≈ quantized-so-far + one tensor's working set.

    Key-splitting order matches init_params exactly, so the quantized
    values equal quantize_params(init_params(...)) for the same seed, up
    to one-step int8 rounding ties (jit fusion may contract the
    round(w/scale) arithmetic differently than the eager two-pass)."""
    from .models.llama import init_one_param, param_shapes
    if cfg.kv_lora_rank > 0:
        # MLA geometry: same init_one_param, different shape map
        from .models.mla import param_shapes

    shapes = param_shapes(cfg)
    tied = "lm_head" not in shapes
    out: Dict[str, object] = {}
    for name, shape in shapes.items():
        key, sub = jax.random.split(key)

        def build(sub, name=name, shape=shape):
            w = init_one_param(cfg, name, shape, sub, dtype)
            return _quantize_named(name, w, include_embed, tied, bits)

        out.update(jax.jit(build)(sub))
    return out
