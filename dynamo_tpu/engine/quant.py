"""Weight-only int8 quantization for the serving engine.

The reference's headline configs serve FP8-quantized models through its
external engines (BASELINE: R1-Distill-Llama-70B FP8 on vLLM/TRT-LLM;
docs/architecture.md benchmarks). Our engine owns the model, so the analog
is native: weights are stored int8 with per-output-channel scales and
dequantized inside the matmul — XLA reads int8 from HBM and fuses the
convert+scale into the MXU op, halving the per-decode-step weights-read
floor (the dominant cost at small batch).

Scheme: symmetric absmax per output channel (the last axis of a stacked
[L, D, F] weight; per row for the [V, D] embedding so the token gather
dequantizes cheaply and a tied lm head reuses the same scales per column;
per (layer, expert, out-channel) for the stacked MoE expert tensors —
for mixtral-class models the experts are the bulk of the weights). Norms,
biases, and the MoE router stay in the load dtype.
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp

__all__ = ["QuantizedArray", "quantize_array", "quantize_params",
           "mm", "qeinsum"]


@jax.tree_util.register_pytree_node_class
class QuantizedArray:
    """int8 tensor + broadcastable f32 scale; dequantizes as q * scale."""

    def __init__(self, q: jax.Array, scale: jax.Array):
        self.q = q
        self.scale = scale

    @property
    def shape(self):
        return self.q.shape

    @property
    def dtype(self):           # the *logical* dtype callers compute in
        return self.scale.dtype

    def dequantize(self, dtype=None) -> jax.Array:
        out = self.q.astype(self.scale.dtype) * self.scale
        return out.astype(dtype) if dtype is not None else out

    def tree_flatten(self):
        return (self.q, self.scale), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    def __repr__(self):
        return f"QuantizedArray(q={self.q.shape}, scale={self.scale.shape})"


def quantize_array(w: jax.Array, *,
                   keep_axes: tuple = (-1,)) -> QuantizedArray:
    """Symmetric absmax int8, one scale per coordinate of ``keep_axes``
    (reduced over every other axis; scale stays broadcast-shaped). Stacked
    per-layer weights pass keep_axes=(0, -1) so each (layer, out-channel)
    pair gets its own scale."""
    w32 = w.astype(jnp.float32)
    keep = {a % w.ndim for a in keep_axes}
    reduce_axes = tuple(a for a in range(w.ndim) if a not in keep)
    absmax = jnp.max(jnp.abs(w32), axis=reduce_axes, keepdims=True)
    scale = jnp.maximum(absmax, 1e-12) / 127.0
    q = jnp.clip(jnp.round(w32 / scale), -127, 127).astype(jnp.int8)
    return QuantizedArray(q, scale.astype(jnp.float32))


def mm(x: jax.Array, w) -> jax.Array:
    """x @ w for a plain array or a QuantizedArray (dequant fused into the
    matmul: XLA reads int8 and converts in-register)."""
    if isinstance(w, QuantizedArray):
        y = x @ w.q.astype(x.dtype)
        return y * w.scale.astype(x.dtype).reshape(w.scale.shape[-1])
    return x @ w


def qeinsum(spec: str, a: jax.Array, w) -> jax.Array:
    """einsum with the same dequant-fuse rule as :func:`mm` for batched
    weights (MoE experts): contract on int8 converted in-register, apply
    the broadcast-shaped scale after the contraction. One owner for the
    dequant semantics — keep in sync with mm by calling, not copying."""
    if isinstance(w, QuantizedArray):
        return jnp.einsum(spec, a, w.q.astype(a.dtype)) \
            * w.scale.astype(a.dtype)
    return jnp.einsum(spec, a, w)


# Weight names quantized (stacked per-layer [L, D, F] → per (L, F) scales).
_LAYER_MATMULS = ("wq", "wk", "wv", "wo", "gate", "up", "down")
# MoE expert tensors [L, E, D, F] → per (L, E, out-channel) scales. For
# mixtral-class models the experts ARE the weights, so leaving them bf16
# would forfeit the whole int8 HBM-read win; the router stays full
# precision (tiny, and routing is precision-sensitive).
_MOE_MATMULS = ("moe_gate", "moe_up", "moe_down")


def quantize_params(params: Dict[str, jax.Array],
                    include_embed: bool = True) -> Dict[str, object]:
    """Return a params tree with matmul weights int8-quantized.

    - ``layers.{wq,wk,wv,wo,gate,up,down}``: per-(layer, out-channel).
    - ``lm_head`` ([D, V]): per out-channel.
    - ``embed`` ([V, D], optional): per ROW (= per token vector), so the
      embedding gather dequantizes with one scale per token and a TIED lm
      head (x @ embed.T) gets per-column scales from the same tensor.
    - ``layers.{moe_gate,moe_up,moe_down}`` ([L, E, D, F]): per
      (layer, expert, out-channel) — for MoE models the experts are the
      bulk of the weights (models/llama.py moe_mlp dequant-fuses them).
    - norms / biases / MoE router untouched.
    """
    tied = "lm_head" not in params
    out: Dict[str, object] = {}
    for name, w in params.items():
        out.update(_quantize_named(name, w, include_embed, tied))
    return out


def _quantize_named(name: str, w: jax.Array, include_embed: bool,
                    tied: bool) -> Dict[str, object]:
    """The per-tensor dispatch shared by quantize_params (whole-tree,
    eager) and init_params_quantized (streaming, one jit per tensor)."""
    suffix = name.split(".", 1)[1] if name.startswith("layers.") else name
    if name.startswith("layers.") and suffix in _LAYER_MATMULS:
        # stacked [L, D, F]: per (layer, out-channel) → scale [L, 1, F]
        return {name: quantize_array(w, keep_axes=(0, -1))}
    if name.startswith("layers.") and suffix in _MOE_MATMULS:
        # stacked [L, E, D, F]: per (layer, expert, out-channel)
        # → scale [L, E, 1, F], which broadcasts over the expert
        # einsums' batched-N axis after the per-layer slice
        return {name: quantize_array(w, keep_axes=(0, 1, -1))}
    if name == "lm_head":
        return {name: quantize_array(w, keep_axes=(-1,))}
    if name == "embed" and include_embed:
        # per-row: scale shape [V, 1]
        out = {name: quantize_array(w, keep_axes=(0,))}
        if tied:
            # tied head: materialize a PRE-TRANSPOSED int8 head —
            # `x @ q.T` of an int8 matrix defeats XLA's transpose
            # fusion and measured 2x slower than the bf16 tied path
            # at small batch; the [D, V] copy reads int8 in natural
            # orientation instead (263MB vs 525MB bf16 per step for
            # llama-1B)
            out["lm_head"] = quantize_array(w.T, keep_axes=(-1,))
        return out
    return {name: w}


def init_params_quantized(cfg, key: jax.Array, dtype=jnp.bfloat16,
                          include_embed: bool = True) -> Dict[str, object]:
    """Random-init + quantize one stacked tensor at a time, entirely
    inside a jit, so the full bf16 tree is never materialized.

    init_params followed by quantize_params peaks at the whole bf16 tree
    (16 GB for Llama-3-8B geometry — an OOM on one 16 GB v5e chip before
    quantization even starts). Here each tensor's init→absmax→round
    pipeline is one jitted program whose only output is the int8 payload
    + f32 scales, so XLA frees the bf16/f32 intermediates inside the
    program; peak HBM ≈ quantized-so-far + one tensor's working set.

    Key-splitting order matches init_params exactly, so the quantized
    values equal quantize_params(init_params(...)) for the same seed, up
    to one-step int8 rounding ties (jit fusion may contract the
    round(w/scale) arithmetic differently than the eager two-pass)."""
    from .models.llama import init_one_param, param_shapes

    shapes = param_shapes(cfg)
    tied = "lm_head" not in shapes
    out: Dict[str, object] = {}
    for name, shape in shapes.items():
        key, sub = jax.random.split(key)

        def build(sub, name=name, shape=shape):
            w = init_one_param(cfg, name, shape, sub, dtype)
            return _quantize_named(name, w, include_embed, tied)

        out.update(jax.jit(build)(sub))
    return out
