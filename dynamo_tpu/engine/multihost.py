"""Multi-host single-engine serving: leader drives, followers live-replay.

The reference runs one engine across hosts with Ray leader/follower
(lib/llm/src/engines/vllm/ray.rs:1-387, vllm.rs:39-87) and sglang's
per-rank subprocess split (lib/llm/src/engines/sglang/worker.rs:304-336).
The TPU-native analog is multi-controller SPMD: every process holds the
same jitted programs over one global ``jax.sharding.Mesh`` (formed by
``parallel.multihost.initialize_multihost``); XLA collectives span hosts
over ICI/DCN. What still needs framework plumbing is HOST control flow:
every process must issue the SAME sequence of device programs with the
SAME host inputs, or the collectives deadlock.

Design: the leader runs the real engine — scheduler, HTTP ingress, KV
manager, detokenizer — exactly as on one host. Its scheduler decisions
already stream through the :class:`engine.replay.Recorder` event format
(every dispatched program's host inputs, in device order). A follower is
a live replay consumer: it receives that stream over TCP and issues the
identical programs against its own EngineCore (same config, same weights
path, same global mesh). Device state (params, KV pool) stays
bit-identical by induction; sampled tokens come back replicated, the
leader harvests them (rank-0 token egress), followers drop theirs.

Lockstep comes for free from XLA: if the leader runs ahead, its programs
wait at the first cross-host collective until the follower catches up;
the leader's event send happens synchronously BEFORE its own dispatch,
so the follower can always make progress.

Wire format: length-prefixed pickle frames of the recorder's numpy-only
event dicts. The stream shares the deployment's trust domain with
``jax.distributed`` itself (same hosts, same network) — it is an
intra-engine control channel, not a public endpoint.

sp ring prefill and chunked prefill ARE streamed (the "prefill_sp"
event; chunks record as plain "prefill" events) — sp's cross-host
ppermute rides ICI on real hardware. Wire-plane disagg onboarding IS
streamed too ("precomputed_admit" forwards the remote prefill's KV
values; each rank scatters its head shard). DEVICE-plane disagg
payloads are streamed as metadata only ("precomputed_device_admit":
rid + target blocks): the payload's arrays are device-resident, so in a
multihost disagg deployment every rank runs an SPMD replica of the
prefill engine, parks its own shard of the payload in its process
bridge (kv_transport.DeviceKvBridge.park), and scatters it when the
leader's admission event arrives — the per-rank routing the wire plane
already uses, without bulk KV on the control stream. This closed the
last multihost refusal (round 4); "prefill_unsupported" remains as a
defensive guard for any future unstreamable path.

The host-KV tier IS streamed: followers keep a MIRROR host pool. The
leader's offload pump emits its literal placement decisions ("kv_store":
hash → slot, eviction, source device block) at commit time — before the
device holds release, so the stream orders the event ahead of any
program that could overwrite a reused block. The follower gathers the
SAME device blocks from its own bit-identical KV and applies the
decisions verbatim (HostKvPool.apply_store) — arena bytes equal by
induction, no bulk KV on the wire. A host-restored admission then
replays h2d locally: "hit_transfer" carries the mirror slots + device
targets and the follower runs the same scatter program the leader ran.

Pipeline parallelism rides this stream UNCHANGED: a pp engine's stage
dispatches are ordinary "prefill"/"dispatch" events — the pp core's
_prefill_jit/_decode_k_jit keep the single-device host contracts
(engine/core._compile_jits_pp), so followers re-issue the recorded
events through their OWN pp-compiled programs and enter the stage
ring's ppermutes in lockstep. The one pp-specific requirement is the
standing one: every rank builds from identical flags (--pp/--tp
included), or the shard_map programs disagree at the first collective.
attach() keeps enforcing decode_steps_per_dispatch > 1, which a pp
config guarantees (EngineConfig refuses pp with K=1).

The disk (G3) tier extends the same contract one rung down: each
"kv_store" event additionally names the evicted hashes the leader's
disk spill queue ACCEPTED ("spills" — the enqueue decision, made
synchronously inside the pool store); the follower stages a copy of
exactly those rows from its mirror arena before the eviction overwrites
them. The spill pump's later durable commit streams "kv_disk_store"
(hash + the leader's literal disk-eviction set) and the follower applies
it verbatim to its OWN local disk store from the staged bytes
(DiskKvStore.apply_put — no LRU policy re-run, no bulk KV on the wire).
A disk-promoted admission rides "hit_transfer"'s disk_hashes/
disk_targets, restored from the follower's mirror disk store.

The remote (G4) fleet tier closed the LAST tier refusal (round 12):
the object store / peer fleet is shared state no follower can re-walk,
so a remote-assisted admission streams as "kv_remote_restore" — the
fetched hashes plus the fetched BYTES — ordered before its
hit_transfer; the follower scatters the literal bytes with the same
program the leader ran (replay.exec_kv_remote_restore_event). A
follower whose own remote store shares the leader's content-addressed
object root may fetch the hashes instead of reading the event's bytes
(fetch-or-bytes): equal hash ⇒ equal bytes by construction.
"""

from __future__ import annotations

import logging
import pickle
import socket
import struct
import time
from collections import OrderedDict
from typing import List

from .replay import Recorder

logger = logging.getLogger("dynamo_tpu.engine.multihost")

__all__ = ["DispatchStreamLeader", "connect_follower", "run_follower"]

# events a follower needs for device-state lockstep; everything else the
# recorder sees (replay.HOST_EVENTS: admit/harvest/first_token/preempt/
# release) is leader-side host bookkeeping. dynalint DL009 holds this
# set equal to run_follower's handled kinds — `ragged` and `verify` were
# missing here while run_follower already handled them, so a ragged or
# speculative leader silently dropped those dispatches on the floor and
# follower device state diverged.
WIRE_EVENTS = frozenset(
    {"prefill", "prefill_sp", "dispatch", "ragged", "verify",
     "hit_transfer", "kv_store", "kv_disk_store", "kv_remote_restore",
     "precomputed_admit", "precomputed_device_admit", "handoff_gather",
     "prefill_unsupported", "kv_layer_stream"})
_SHUTDOWN = {"ev": "__shutdown__"}

_LEN = struct.Struct(">I")


def _send_frame(sock: socket.socket, obj: dict) -> None:
    data = pickle.dumps(obj, protocol=5)
    sock.sendall(_LEN.pack(len(data)) + data)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("dispatch stream closed")
        buf.extend(chunk)
    return bytes(buf)


def _recv_frame(sock: socket.socket) -> dict:
    (n,) = _LEN.unpack(_recv_exact(sock, 4))
    return pickle.loads(_recv_exact(sock, n))


class DispatchStreamLeader(Recorder):
    """Leader-side recorder that forwards device-order events to follower
    sockets instead of buffering them.

    Attach as ``core.recorder``. ``rec`` sends synchronously (blocking
    sendall) so the event is on the wire BEFORE the leader's own jit
    dispatch for that event — the ordering that makes follower progress
    independent of the leader's device state. TCP backpressure bounds
    leader run-ahead naturally.
    """

    def __init__(self, port: int, num_followers: int,
                 host: str = "0.0.0.0", accept_timeout: float = 120.0):
        super().__init__()
        self._listener = socket.create_server((host, port))
        self.port = self._listener.getsockname()[1]
        self.num_followers = num_followers
        self._accept_timeout = accept_timeout
        self.socks: List[socket.socket] = []
        self.sent = 0
        self.broken = False

    def attach(self, core) -> None:
        """Validate the engine is in a configuration whose EVERY device
        program flows through the recorder stream, then become its
        recorder. A program the follower never hears about deadlocks the
        first cross-host collective (the single-step `_decode_jit` path
        taught us this the hard way — it is unrecorded by design)."""
        if core._decode_k_jit is None:
            raise ValueError(
                "multihost serving requires decode_steps_per_dispatch > 1 "
                "(the single-step decode path is not in the dispatch "
                "stream)")
        pool = core.kv_manager.host_pool
        if pool is not None and len(pool) > 0:
            # followers mirror only post-attach stores; a pre-attach
            # offload would later host-hit with slots no follower holds
            raise ValueError(
                "attach the dispatch stream before the engine offloads "
                f"anything (host pool already holds {len(pool)} blocks)")
        if core.disk_store is not None and len(core.disk_store) > 0:
            # same staleness hazard one tier down: a warm-started disk
            # store holds blocks no follower can prove it mirrors
            raise ValueError(
                "multihost serving cannot start from a warm disk KV "
                f"store ({len(core.disk_store)} blocks at "
                f"{core.disk_store.root}) — clear it (llmctl kv flush "
                f"--clear) or point --kv-disk-dir at a fresh directory")
        core.recorder = self

    def wait_for_followers(self) -> None:
        """Block until every follower has connected."""
        self._listener.settimeout(self._accept_timeout)
        while len(self.socks) < self.num_followers:
            try:
                s, addr = self._listener.accept()
            except socket.timeout:
                raise TimeoutError(
                    f"only {len(self.socks)}/{self.num_followers} followers "
                    f"connected within {self._accept_timeout}s")
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self.socks.append(s)
            logger.info("follower %d/%d connected from %s",
                        len(self.socks), self.num_followers, addr)

    def rec(self, ev: str, **kw) -> None:
        if ev not in WIRE_EVENTS:
            return
        if self.broken:
            # fail FAST and deterministically: after any send failure some
            # follower may have missed an event, so device state can no
            # longer be proven bit-identical — serving must stop, not
            # silently diverge
            raise RuntimeError(
                "multihost dispatch stream is broken (a prior event send "
                "failed); the engine cannot guarantee follower lockstep")
        kw["ev"] = ev
        # serialize ONCE: precomputed_admit carries bulk KV values, and
        # per-socket pickling would redo megabytes of work on the loop
        data = pickle.dumps(kw, protocol=5)
        frame = _LEN.pack(len(data)) + data
        try:
            for s in self.socks:
                s.sendall(frame)
        except OSError:
            self.broken = True
            raise
        self.sent += 1

    def close(self) -> None:
        for s in self.socks:
            try:
                _send_frame(s, _SHUTDOWN)
                s.close()
            except OSError:
                pass
        self._listener.close()


def connect_follower(addr: str, timeout: float = 120.0) -> socket.socket:
    """Dial the leader's dispatch stream, retrying while it boots."""
    host, port = addr.rsplit(":", 1)
    deadline = time.monotonic() + timeout
    delay = 0.1
    while True:
        try:
            sock = socket.create_connection((host, int(port)), timeout=5.0)
            sock.settimeout(None)   # connect timeout only — the stream
            # idles for as long as the leader has nothing to dispatch
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            return sock
        except OSError:
            if time.monotonic() + delay > deadline:
                raise
            time.sleep(delay)
            delay = min(delay * 2, 1.0)


def run_follower(core, sock: socket.socket,
                 max_chain_keep: int = 8) -> dict:
    """Consume the leader's dispatch stream against a local EngineCore
    until shutdown. Blocking; run as the follower process's main loop.

    The event→program marshalling is shared with the offline replayer
    (replay.exec_prefill_event / exec_dispatch_event) so the jit-call
    signatures live in exactly one place; this loop only adds the live
    carry (``core.kv``) and a bounded chain window.
    """
    from .replay import (exec_dispatch_event, exec_host_restore_event,
                         exec_kv_disk_store_event,
                         exec_kv_remote_restore_event, exec_kv_store_event,
                         exec_prefill_event, exec_ragged_event,
                         exec_sp_prefill_event, exec_verify_event)

    disp_toks: "OrderedDict[int, object]" = OrderedDict()
    # disk-tier staging: evicted-row copies taken at kv_store replay for
    # the hashes the leader's spill queue accepted, consumed by the
    # matching kv_disk_store commit. Bounded: a leader-side disk-write
    # failure orphans its staged rows, and an unbounded dict would leak.
    spill_stage: "OrderedDict[int, dict]" = OrderedDict()
    MAX_STAGE = 1024
    stats = {"prefills": 0, "dispatches": 0, "kv_stores": 0,
             "host_restores": 0}

    while True:
        ev = _recv_frame(sock)
        kind = ev["ev"]
        logger.debug("follower event %s", kind)
        if kind == "__shutdown__":
            break
        if kind == "prefill_unsupported":
            raise NotImplementedError(
                f"leader used an admission path the multihost follower "
                f"cannot replay ({ev.get('path')}, rid={ev.get('rid')}); "
                f"disable disagg onboarding on a multihost engine")
        if kind == "precomputed_admit":
            # wire-plane disagg admission: the leader forwarded the
            # remote prefill's (global-head) KV values; scatter our
            # shard into the same target blocks
            from .block_copy import scatter_blocks_from_host
            core.kv = scatter_blocks_from_host(
                core.kv, list(ev["targets"]), ev["values"],
                core.cfg.kv_block_size)
            stats["precomputed"] = stats.get("precomputed", 0) + 1
            continue
        if kind == "kv_layer_stream":
            # streaming layer-wise disagg admission (llm/kv/stream.py):
            # one event per arrived layer with its (global-head) suffix
            # values — run the same single-layer scatter the leader ran,
            # slicing our shard's heads; device order is preserved
            # because the leader records adjacent to its own scatter
            from .block_copy import scatter_layer_from_host
            core.kv = scatter_layer_from_host(
                core.kv, list(ev["targets"]), int(ev["layer"]),
                ev["values"], core.cfg.kv_block_size)
            stats["layer_streams"] = stats.get("layer_streams", 0) + 1
            continue
        if kind == "handoff_gather":
            # prefill-engine follower: run the leader's handoff gather (a
            # device program — skipping it would deadlock the next
            # collective). For device-plane handoffs (park=True) hold
            # this rank's shard of the gather output in the process
            # bridge so a co-located decode follower can claim it.
            from .block_copy import gather_blocks_dispatch
            stacked = gather_blocks_dispatch(core.kv, list(ev["ids"]),
                                             core.cfg.kv_block_size)
            if ev.get("park"):
                from ..llm.kv_transport import DeviceKvPayload, bridge
                bridge().park(ev["rid"], DeviceKvPayload(
                    # followers never read the token fields — the scatter
                    # consumes only stacked/n_blocks/block_size
                    request_id=ev["rid"], first_token=None,
                    first_logprob=None, seq_hashes=[],
                    stacked=stacked, n_blocks=int(ev["n_blocks"]),
                    block_size=core.cfg.kv_block_size))
            stats["handoff_gathers"] = stats.get("handoff_gathers", 0) + 1
            continue
        if kind == "precomputed_device_admit":
            # decode-engine follower: the payload's arrays never ride the
            # stream — this rank's prefill-engine replica parked its OWN
            # shard in the process bridge ("handoff_gather" park=True);
            # run the same scatter program the leader ran. The prefill
            # replica consumes a DIFFERENT stream, so rendezvous with a
            # bounded wait rather than assuming it already parked.
            from ..llm.kv_transport import bridge, scatter_blocks_device
            deadline = time.monotonic() + 120.0
            payload = bridge().take_parked(ev["rid"])
            while payload is None and time.monotonic() < deadline:
                time.sleep(0.01)
                payload = bridge().take_parked(ev["rid"])
            if payload is None:
                raise ValueError(
                    f"leader admitted a device-plane payload for "
                    f"rid={ev.get('rid')} but nothing was parked in this "
                    f"rank's bridge within 120s — is the prefill engine "
                    f"replica running on this rank with its dispatch "
                    f"stream attached?")
            if ev["targets"]:
                core.kv = scatter_blocks_device(
                    core.kv, list(ev["targets"]), payload,
                    int(ev["skip"]), int(ev["n_needed"]), mesh=core.mesh)
            # else: full prefix hit — claiming (and dropping) the parked
            # shard was the point; nothing to scatter
            stats["precomputed_device"] = (
                stats.get("precomputed_device", 0) + 1)
            continue
        if kind == "kv_store":
            # mirror the leader's offload commit: gather the SAME device
            # blocks from our bit-identical KV, apply the leader's literal
            # hash→slot placements (no LRU policy re-run on followers) —
            # shared with the offline replayer (replay.exec_kv_store_event)
            pool = core.kv_manager.host_pool
            if pool is None:
                raise ValueError(
                    "leader streams host-KV-tier stores but this follower "
                    "was built with host_kv_blocks=0 — ranks must share "
                    "one engine config")
            exec_kv_store_event(core.kv, ev, pool, core.cfg.kv_block_size,
                                spill_stage=spill_stage)
            while len(spill_stage) > MAX_STAGE:
                spill_stage.popitem(last=False)
            stats["kv_stores"] += 1
            continue
        if kind == "kv_disk_store":
            # mirror the leader's disk-tier spill commit: literal
            # placements, bytes from the staged row copies (or the host
            # mirror, for flush-driven spills) — shared with the offline
            # replayer (replay.exec_kv_disk_store_event)
            if core.disk_store is None:
                raise ValueError(
                    "leader streams disk-tier stores but this follower "
                    "was built with kv_disk_blocks=0 — ranks must share "
                    "one engine config (kv_disk_dir is per-rank local)")
            exec_kv_disk_store_event(ev, core.disk_store,
                                     core.kv_manager.host_pool,
                                     spill_stage)
            stats["kv_disk_stores"] = stats.get("kv_disk_stores", 0) + 1
            continue
        if kind == "kv_remote_restore":
            # remote (G4) tier restore: scatter the leader's fetched
            # bytes (or fetch the hashes from OUR remote store when the
            # event omitted them and this rank shares the leader's
            # content-addressed object root) into the same device
            # targets — shared with the offline replayer
            # (replay.exec_kv_remote_restore_event)
            core.kv = exec_kv_remote_restore_event(
                core.kv, ev, core.cfg.kv_block_size,
                remote_store=core.remote_store)
            stats["remote_restores"] = stats.get("remote_restores", 0) + 1
            continue
        if kind == "hit_transfer":
            if (int(ev.get("host_hit", 0)) > 0
                    or int(ev.get("disk_hit", 0)) > 0):
                # replay the leader's h2d restore from the mirror tiers —
                # shared with the offline replayer
                # (replay.exec_host_restore_event)
                pool = core.kv_manager.host_pool
                if int(ev.get("host_hit", 0)) > 0 and (
                        pool is None or pool._arena is None):
                    raise ValueError(
                        "host restore references slots this follower "
                        "never mirrored (no kv_store seen) — the leader "
                        "must attach the stream before any offloads")
                core.kv = exec_host_restore_event(
                    core.kv, ev, pool, core.cfg.kv_block_size,
                    disk_store=core.disk_store)
                stats["host_restores"] += 1
            continue   # device-hit-only: prefix hits reuse resident KV
        if kind == "prefill":
            _tok, core.kv = exec_prefill_event(core, core.kv, ev)
            stats["prefills"] += 1
        elif kind == "prefill_sp":
            _tok, core.kv = exec_sp_prefill_event(core, core.kv, ev)
            stats["prefills"] += 1
        elif kind == "dispatch":
            chain = (disp_toks[ev["chained_from"]]
                     if ev["chained_from"] is not None else None)
            toks_k, core.kv = exec_dispatch_event(core, core.kv, ev, chain)
            disp_toks[ev["id"]] = toks_k
            while len(disp_toks) > max_chain_keep:
                disp_toks.popitem(last=False)
            stats["dispatches"] += 1
        elif kind == "verify":
            # speculative verify (engine/spec/) is a device program —
            # run the identical dispatch; acceptance is leader-side
            # bookkeeping the follower never needs
            _toks, core.kv = exec_verify_event(core, core.kv, ev)
            stats["verifies"] = stats.get("verifies", 0) + 1
        elif kind == "ragged":
            # unified ragged dispatch (engine/ragged.py) is a device
            # program with the same host contract as dispatch/verify —
            # run the identical packing; span bookkeeping (lane
            # consumption, boundary samples, spec acceptance) is
            # leader-side. Pipelined ragged events chain off the
            # previous ragged dispatch's device tokens, so the follower
            # keeps them in the same bounded chain window.
            chain = (disp_toks.get(ev["chained_from"])
                     if ev.get("chained_from") is not None else None)
            if ev.get("chained_from") is not None and chain is None:
                raise NotImplementedError(
                    f"ragged dispatch {ev['id']} chains from "
                    f"{ev['chained_from']} which left the follower's "
                    f"chain window — raise max_chain_keep")
            toks_r, core.kv = exec_ragged_event(core, core.kv, ev,
                                                chain)
            disp_toks[ev["id"]] = toks_r
            while len(disp_toks) > max_chain_keep:
                disp_toks.popitem(last=False)
            stats["ragged"] = stats.get("ragged", 0) + 1
    logger.info("follower done: %s", stats)
    return stats
