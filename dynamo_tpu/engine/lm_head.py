"""Fused int8 dequant-matmul for the LM head (Pallas).

Why (PERF.md "Decode step budget" + "next wins" 2): the head is the
single largest matmul of a decode step — [B, D] @ [D, V≈128k] — and with
int8 weights its floor is a pure weights-read: ~0.33 GB → ~0.4 ms on
v5e. The XLA paths measured 0.5–1.4 ms and, worse, XLA's int8 matmul
heuristics are batch-dependent (llama.py:_logits: the pre-transposed
int8 head collapses from 4.5 ms to 82 ms between B=16 and B=64). This
kernel pins the schedule instead of relying on heuristics:

- grid over vocab tiles; each step DMAs one [D, TV] int8 weight tile
  (Pallas double-buffers the HBM→VMEM stream automatically),
- converts int8→bf16 in-register, one MXU dot per tile with f32
  accumulation, scales by the per-column quant scale on the way out.

HBM traffic = the int8 weights once + the f32 logits once — the floor.
"""

from __future__ import annotations

import functools
import logging

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

logger = logging.getLogger("dynamo_tpu.engine.lm_head")

__all__ = ["lm_head_int8", "kernel_selftest", "TILE_V"]

TILE_V = 256    # vocab tile; the gate in models/llama.py checks V % TILE_V

_SELFTEST_OK = None


def kernel_selftest() -> bool:
    """Compile + run the kernel once on tiny shapes, EAGERLY (must be
    called outside any jit trace). The engine gates the fused head on
    this at construction so a lowering regression on some backend
    degrades to the XLA paths instead of breaking serving — the kernel
    was developed in interpret mode against a tunnel that was down for
    a whole round, so the first real-TPU lowering happens in the field.
    Result is cached per process."""
    global _SELFTEST_OK
    if _SELFTEST_OK is None:
        try:
            x = jnp.ones((16, 256), jnp.bfloat16)
            q = jnp.ones((256, TILE_V), jnp.int8)
            s = jnp.full((1, TILE_V), 0.5, jnp.float32)
            out = jax.block_until_ready(lm_head_int8(x, q, s))
            # 256 ones × 1 × 0.5 = 128.0 per element
            ok = abs(float(out[0, 0]) - 128.0) < 1.0
            if not ok:
                logger.error("lm-head kernel selftest produced %r, "
                             "expected 128.0 — disabling the fused head",
                             float(out[0, 0]))
            _SELFTEST_OK = ok
        except Exception:  # noqa: BLE001 — any failure means fall back
            logger.exception("fused lm-head kernel failed its selftest; "
                             "serving falls back to the XLA head paths")
            _SELFTEST_OK = False
    return _SELFTEST_OK


def _kernel(x_ref, wq_ref, scale_ref, out_ref):
    w = wq_ref[...].astype(jnp.bfloat16)
    acc = jax.lax.dot_general(
        x_ref[...].astype(jnp.bfloat16), w,
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    out_ref[...] = acc * scale_ref[...]


@functools.partial(jax.jit, static_argnames=("tile_v", "interpret"))
def lm_head_int8(x: jax.Array, q: jax.Array, scale: jax.Array,
                 *, tile_v: int = TILE_V,
                 interpret: bool = False) -> jax.Array:
    """``x[B, D] @ q[D, V](int8) * scale[V] → f32 logits [B, V]``.

    ``scale`` may be [V], [1, V] or [V, 1] (per-output-channel). V must
    divide by ``tile_v`` (the llama vocab 128256 = 501·256); B and D are
    padded to hardware tiles internally.
    """
    squeeze = x.ndim == 1
    if squeeze:
        x = x[None, :]
    B, D = x.shape
    Dw, V = q.shape
    assert D == Dw, (x.shape, q.shape)
    if V % tile_v != 0:
        raise ValueError(f"vocab {V} not divisible by tile_v={tile_v}")
    scale2d = scale.reshape(1, -1).astype(jnp.float32)
    assert scale2d.shape[1] == V, (scale.shape, V)
    # bf16 sublane tile is 16: pad the batch so the MXU rows are aligned
    Bp = max(16, ((B + 15) // 16) * 16)
    if Bp != B:
        x = jnp.pad(x, ((0, Bp - B), (0, 0)))
    grid = (V // tile_v,)
    out = pl.pallas_call(
        _kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((Bp, D), lambda i: (0, 0)),       # activations
            pl.BlockSpec((D, tile_v), lambda i: (0, i)),   # int8 weights
            pl.BlockSpec((1, tile_v), lambda i: (0, i)),   # quant scales
        ],
        out_specs=pl.BlockSpec((Bp, tile_v), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((Bp, V), jnp.float32),
        interpret=interpret,
    )(x, q, scale2d)
    out = out[:B]
    return out[0] if squeeze else out
