"""Multi-head Latent Attention (MLA, deepseek_v2) in pure JAX with the
paged LATENT-KV cache.

The reference serves DeepSeek models through its external engines; here
MLA is an engine-native model definition like models/llama.py. The
design is what makes MLA attractive for serving: the per-token cache is
the COMPRESSED latent row — ``[c_kv (kv_lora_rank) | k_pe
(qk_rope_head_dim)]``, e.g. 512+64 lanes instead of H·(192+128) — and
decode runs the ABSORBED form, contracting queries into latent space so
attention reads only those rows (an MQA-shaped read despite H heads).
The row format drops straight into the block-major paged pool
``[L, NTOK, rank+rope]`` the whole KV subsystem (reuse, offload,
handoff) already speaks.

Conventions pinned against HF ``DeepseekV2Attention`` (transformers
4.57, modeling_deepseek_v2.py:288-400, verified by the parity tests):

- rope is INTERLEAVED complex rotation (pairs (2i, 2i+1), angle
  pos·inv_freq[i]) — NOT llama's half-split convention;
- softmax scale is (qk_nope + qk_rope)^-0.5;
- the cached latent is the POST-RMSNorm compressed kv (k/v expand from
  it with the pure matmul ``kv_b``), and k_pe is cached post-rope;
- q path: plain ``q_proj`` when q_lora_rank == 0 (the -Lite layout),
  else ``q_a → rmsnorm → q_b``.

Scope: dense MLP layers AND the deepseek MoE block (additive shared
experts, first_k_dense hybrid sparsity via split scans, greedy +
group-limited-greedy routing with routed_scaling — all HF-parity
tested); deepseek_v3's sigmoid-scored noaux_tc routing (bias-corrected
top-2-sum group selection, renormalized top-k, and the yarn mscale²
score scale HF applies in DeepseekV3Attention); default AND yarn rope
(incl. the inferred mscale attention factor); EngineCore serves MLA
end-to-end through the model dispatch (core.is_mla), including dp/tp/ep
meshes (parallel/sharding.py: head-sharded projections, replicated
latent pool, expert-parallel MoE stacks), int8 latent-KV pools
(init_kv_cache quantization="int8": in-row scales, one pair per
c_kv/k_pe section), int8 weights (quant._LAYER_MATMULS; wkv_b stays
full precision for the absorbed einsums), the host KV tier (latent
rows ship whole as one opaque wire head — llm/kv/offload.py), both
disagg planes, and sequence-parallel ring prefill (prefill_forward_sp:
the ring moves compressed latent rows and accumulates in rank-space).
Still refusing loudly: int4 weights.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..attention import (dequant_kv_rows_sections,
                         quantize_kv_rows_sections,
                         ragged_paged_attention_pallas)
from ..config import ModelConfig
from ..quant import mm
from .llama import (ModelStatics, _embed, _layer_stack, _logits,
                    flat_token_indices, rms_norm, run_experts_dense,
                    swiglu)

Params = Dict[str, jax.Array]
KVCache = Dict[str, jax.Array]   # {"kv": [L, NTOK, rank + rope]}

NEG_INF = -1e30


def get_mscale(scale: float, m: float = 1.0) -> float:
    """HF yarn_get_mscale — the ONE home for the yarn mscale formula
    (rope_params' cos/sin attention factor AND softmax_scale's v3 score
    correction derive from it)."""
    import math
    if scale <= 1:
        return 1.0
    return 0.1 * m * math.log(scale) + 1.0


# ---------------------------------------------------------------------------
# Rope (interleaved complex convention — HF apply_rotary_emb)
# ---------------------------------------------------------------------------


def rope_params(cfg: ModelConfig):
    """(inv_freq [d/2], attention_scaling) — default rope, or yarn
    (deepseek checkpoints): mirrors HF _compute_yarn_parameters
    (modeling_rope_utils.py:246-365) — NTK interpolation/extrapolation
    blend over a linear ramp between the beta_fast/beta_slow correction
    dims, and the inferred attention factor that multiplies cos/sin
    (mscale; = 1.0 when mscale == mscale_all_dim, the released-V2
    setting)."""
    import math
    d = cfg.qk_rope_head_dim
    base = cfg.rope_theta
    pos_freqs = base ** (np.arange(0, d, 2, dtype=np.float64) / d)
    inv = 1.0 / pos_freqs
    rs = cfg.rope_scaling
    if rs is None:
        return inv.astype(np.float32), 1.0
    if rs.rope_type != "yarn":
        # loud-rejection convention (config.py phi3 longrope): serving a
        # linear/llama3/longrope deepseek checkpoint with unscaled
        # positions would decode garbage past the original context
        raise ValueError(
            f"MLA rope_scaling type {rs.rope_type!r} is not implemented "
            f"(yarn is; remove rope_scaling for base-context models)")
    factor = rs.factor
    if rs.attention_factor:
        # HF priority: an explicit attention_factor overrides inference
        att = rs.attention_factor
    elif rs.mscale and rs.mscale_all_dim:
        att = get_mscale(factor, rs.mscale) / get_mscale(
            factor, rs.mscale_all_dim)
    else:
        att = get_mscale(factor)
    interp = 1.0 / (factor * pos_freqs)

    def corr_dim(num_rot):
        return (d * math.log(rs.original_max_position_embeddings
                             / (num_rot * 2 * math.pi))
                ) / (2 * math.log(base))

    low = max(math.floor(corr_dim(rs.beta_fast)), 0)
    high = min(math.ceil(corr_dim(rs.beta_slow)), d - 1)
    if low == high:
        high += 0.001                    # HF's singularity guard
    ramp = np.clip((np.arange(d // 2, dtype=np.float64) - low)
                   / (high - low), 0, 1)
    extrap = 1.0 - ramp
    inv_freq = interp * (1 - extrap) + inv * extrap
    return inv_freq.astype(np.float32), float(att)


def softmax_scale(cfg: ModelConfig) -> float:
    """Attention score scale. Base = qk_head_dim^-0.5 for both
    generations; deepseek_v3 under yarn additionally multiplies by
    mscale(factor, mscale_all_dim)² (HF DeepseekV3Attention.__init__ —
    v2 applies its attention factor through cos/sin instead, so the two
    corrections never double-apply)."""
    s = (cfg.qk_nope_head_dim + cfg.qk_rope_head_dim) ** -0.5
    rs = cfg.rope_scaling
    if (cfg.model_type == "deepseek_v3" and rs is not None
            and rs.mscale_all_dim):
        m = get_mscale(rs.factor, rs.mscale_all_dim)
        s *= m * m
    return s


def apply_rope_interleaved(x: jax.Array, positions: jax.Array,
                           inv_freq: jax.Array,
                           scaling: float = 1.0) -> jax.Array:
    """x [..., T, d] with the pair (2i, 2i+1) rotated by pos·inv_freq[i]
    (torch.view_as_complex pairing). positions: [T]. ``scaling``
    multiplies cos/sin (yarn attention factor — HF scales freqs_cis)."""
    ang = positions[:, None].astype(jnp.float32) * inv_freq[None, :]
    cos = jnp.cos(ang) * scaling                        # [T, d/2]
    sin = jnp.sin(ang) * scaling
    shape = x.shape
    xp = x.astype(jnp.float32).reshape(shape[:-1] + (shape[-1] // 2, 2))
    # broadcast the [T, d/2] angles over any middle axes (q_pe carries a
    # head axis, k_pe does not)
    for _ in range(xp.ndim - 3):
        cos = cos[:, None]
        sin = sin[:, None]
    x0, x1 = xp[..., 0], xp[..., 1]
    out = jnp.stack([x0 * cos - x1 * sin, x0 * sin + x1 * cos], axis=-1)
    return out.reshape(shape).astype(x.dtype)


# ---------------------------------------------------------------------------
# Parameters / cache
# ---------------------------------------------------------------------------


def param_shapes(cfg: ModelConfig) -> Dict[str, Tuple[int, ...]]:
    L, D, H = cfg.num_layers, cfg.hidden_size, cfg.num_heads
    qk = cfg.qk_nope_head_dim + cfg.qk_rope_head_dim
    shapes: Dict[str, Tuple[int, ...]] = {
        "embed": (cfg.vocab_size, D),
        "final_norm": (D,),
        "layers.ln1": (L, D),
        "layers.ln2": (L, D),
        "layers.wkv_a": (L, D, cfg.kv_lora_rank + cfg.qk_rope_head_dim),
        "layers.kv_norm": (L, cfg.kv_lora_rank),
        "layers.wkv_b": (L, cfg.kv_lora_rank,
                         H * (cfg.qk_nope_head_dim + cfg.v_head_dim)),
        "layers.wo": (L, H * cfg.v_head_dim, D),
    }
    if cfg.num_experts > 0:
        # deepseek hybrid: the first k layers are DENSE (their own
        # intermediate size), the rest are MoE with additive shared
        # experts — two parameter stacks, two scans (_run_layers)
        k = cfg.first_k_dense
        Lm = L - k
        E, F = cfg.num_experts, cfg.intermediate_size
        if k > 0:
            Fd = cfg.dense_intermediate_size or F
            shapes.update({
                "layers.dense_gate": (k, D, Fd),
                "layers.dense_up": (k, D, Fd),
                "layers.dense_down": (k, Fd, D),
            })
        shapes.update({
            "layers.router": (Lm, D, E),
            "layers.moe_gate": (Lm, E, D, F),
            "layers.moe_up": (Lm, E, D, F),
            "layers.moe_down": (Lm, E, F, D),
        })
        if cfg.moe_routing == "sigmoid_noaux":
            # deepseek_v3: the router's e_score_correction_bias buffer —
            # it biases expert CHOICE only, never the mixing weights
            shapes["layers.router_bias"] = (Lm, E)
        if cfg.shared_expert_size > 0:
            Fs = cfg.shared_expert_size
            shapes.update({
                "layers.sh_gate": (Lm, D, Fs),
                "layers.sh_up": (Lm, D, Fs),
                "layers.sh_down": (Lm, Fs, D),
            })
    else:
        shapes.update({
            "layers.gate": (L, D, cfg.intermediate_size),
            "layers.up": (L, D, cfg.intermediate_size),
            "layers.down": (L, cfg.intermediate_size, D),
        })
    if cfg.q_lora_rank > 0:
        shapes.update({
            "layers.wq_a": (L, D, cfg.q_lora_rank),
            "layers.q_a_norm": (L, cfg.q_lora_rank),
            "layers.wq_b": (L, cfg.q_lora_rank, H * qk),
        })
    else:
        shapes["layers.wq"] = (L, D, H * qk)
    if not cfg.tie_word_embeddings:
        shapes["lm_head"] = (D, cfg.vocab_size)
    return shapes


def init_params(cfg: ModelConfig, key: jax.Array,
                dtype=jnp.bfloat16) -> Params:
    from .llama import init_one_param
    params: Params = {}
    for name, shape in param_shapes(cfg).items():
        key, sub = jax.random.split(key)
        params[name] = init_one_param(cfg, name, shape, sub, dtype)
    return params


def latent_row_lanes(cfg: ModelConfig, quantization: str = "none") -> int:
    """Pool row width, PADDED to a 128-lane multiple either way: the
    lane alignment is what makes the latent pool a legal block-DMA
    source for the Pallas paged-attention kernel (decode maps onto it
    as MQA — see decode_forward). Full precision: rank+rope up (e.g.
    512+64 -> 640). int8: the sectioned encode's rank+rope +
    KV_SCALE_LANES, padded (e.g. 576+128 -> 768). Readers slice the
    exact value/scale ranges, so pad lanes are write-only zeros."""
    from ..attention import KV_SCALE_LANES
    C = cfg.kv_lora_rank + cfg.qk_rope_head_dim
    if quantization == "int8":
        C = C + KV_SCALE_LANES
    return -(-C // 128) * 128


def init_kv_cache(cfg: ModelConfig, num_blocks: int,
                  block_size: int, dtype=jnp.bfloat16,
                  quantization: str = "none") -> KVCache:
    """quantization="int8": the latent row quantizes with one in-row
    (e, m) scale pair PER c_kv/k_pe section
    (attention.quantize_kv_rows_sections — both pairs share one
    128-lane pad, and the row then PADS to a 128-lane multiple like
    the full-precision layout: e.g. 576+128 -> 768, wider than the
    unpadded llama encoding). Unlike llama pools there is never a
    per-tp-shard section: the latent pool replicates under tp
    (parallel/sharding.shard_kv), so every rank reads whole rows. Row
    widths: latent_row_lanes."""
    if quantization not in ("none", "int8"):
        raise ValueError(f"unknown kv quantization {quantization!r} "
                         f"(none|int8)")
    W = latent_row_lanes(cfg, quantization)
    return {"kv": jnp.zeros(
        (cfg.num_layers, num_blocks * block_size, W),
        dtype=jnp.int8 if quantization == "int8" else dtype)}


# ---------------------------------------------------------------------------
# Shared layer body
# ---------------------------------------------------------------------------


def _q_proj(lp, hn, cfg: ModelConfig):
    """[N, D] -> (q_nope [N, H, dn], q_pe [N, H, dr])."""
    H = cfg.num_heads
    dn, dr = cfg.qk_nope_head_dim, cfg.qk_rope_head_dim
    if cfg.q_lora_rank > 0:
        qa = rms_norm(mm(hn, lp["wq_a"]), lp["q_a_norm"], cfg.rms_norm_eps)
        q = mm(qa, lp["wq_b"])
    else:
        q = mm(hn, lp["wq"])
    q = q.reshape(hn.shape[0], H, dn + dr)
    return q[..., :dn], q[..., dn:]


def _latent_rows(lp, hn, positions, cfg: ModelConfig):
    """[N, D] -> latent cache rows [N, rank+rope]: post-norm c_kv with
    post-rope k_pe — the format every reader expands from."""
    ckv = mm(hn, lp["wkv_a"])
    c, k_pe = ckv[..., :cfg.kv_lora_rank], ckv[..., cfg.kv_lora_rank:]
    c = rms_norm(c, lp["kv_norm"], cfg.rms_norm_eps)
    inv, att = rope_params(cfg)
    k_pe = apply_rope_interleaved(k_pe, positions, jnp.asarray(inv), att)
    return jnp.concatenate([c, k_pe], axis=-1)


def _moe_mlp(hn, lp, cfg: ModelConfig) -> jax.Array:
    """deepseek routing, both generations (verified by the parity
    tests). v2 (HF DeepseekV2MoEGate): f32 softmax over ALL experts,
    greedy (or group-limited greedy) top-k of the SCORES without
    renormalization, scaled by routed_scaling. v3 (HF
    DeepseekV3TopkRouter, moe_routing == "sigmoid_noaux"): f32 sigmoid
    scores; expert CHOICE uses scores + e_score_correction_bias with
    groups selected by the sum of each group's top-2 corrected scores
    (masked groups ZEROED, matching masked_fill(0.0)); the mixing
    weights are the UNBIASED sigmoid scores of the chosen experts,
    renormalized over the top-k (+1e-20) when norm_topk_prob, then
    scaled. Shared experts are a plain additive swiglu either way.
    Experts run dense-over-E (llama.run_experts_dense)."""
    N, E = hn.shape[0], cfg.num_experts
    logits = (hn.astype(jnp.float32)
              @ lp["router"].astype(jnp.float32))          # [N, E]
    if cfg.moe_routing == "sigmoid_noaux":
        scores = jax.nn.sigmoid(logits)
        choice = scores + lp["router_bias"][None, :].astype(jnp.float32)
        if cfg.n_group > 1:
            g = cfg.n_group
            top2, _i = jax.lax.top_k(choice.reshape(N, g, E // g), 2)
            gscore = top2.sum(axis=-1)                     # [N, g]
            _w, gidx = jax.lax.top_k(gscore, cfg.topk_group)
            gmask = jnp.sum(jax.nn.one_hot(gidx, g, dtype=choice.dtype),
                            axis=1)                        # [N, g]
            choice = (choice.reshape(N, g, E // g)
                      * gmask[..., None]).reshape(N, E)
        _cw, top_idx = jax.lax.top_k(choice, cfg.num_experts_per_tok)
        top_w = jnp.take_along_axis(scores, top_idx, axis=1)
        if cfg.moe_norm_topk:
            top_w = top_w / (top_w.sum(axis=-1, keepdims=True) + 1e-20)
        top_w = top_w * cfg.routed_scaling
    else:
        scores = jax.nn.softmax(logits, axis=-1)
        if cfg.n_group > 1:
            # group-limited greedy (DeepSeek-V2/-Chat): keep only the
            # topk_group groups with the best per-group max score
            g = cfg.n_group
            gmax = scores.reshape(N, g, E // g).max(axis=-1)  # [N, g]
            _w, gidx = jax.lax.top_k(gmax, cfg.topk_group)
            gmask = jnp.sum(jax.nn.one_hot(gidx, g, dtype=scores.dtype),
                            axis=1)                           # [N, g]
            scores = (scores.reshape(N, g, E // g)
                      * gmask[..., None]).reshape(N, E)
        top_w, top_idx = jax.lax.top_k(scores, cfg.num_experts_per_tok)
        # NO renormalization: the HF-native reference never applies
        # norm_topk_prob (from_hf_config rejects true for deepseek_v2)
        top_w = top_w * cfg.routed_scaling
    out = run_experts_dense(hn, lp.get("moe_gate"), lp.get("moe_up"),
                            lp["moe_down"], top_idx, top_w,
                            gateup_w=lp.get("moe_gateup"))
    if cfg.shared_expert_size > 0:
        out = out + swiglu(hn, lp.get("sh_gate"), lp.get("sh_up"),
                           lp["sh_down"], cfg.hidden_act,
                           gateup_w=lp.get("sh_gateup"))
    return out


def _run_layers(params: Params, kv: KVCache, x: jax.Array,
                positions: jax.Array, slots: jax.Array, cfg: ModelConfig,
                attn_fn) -> Tuple[jax.Array, KVCache]:
    """attn_fn(q_nope, q_pe, rows_new, kv_flat, lp, li) -> [N, H*v].

    deepseek hybrid sparsity (first_k_dense): the layer stacks split
    into a dense prefix and a MoE suffix, each its own lax.scan with the
    SAME attention body — the latent pool carries across both, with li
    addressing rows globally."""
    L = cfg.num_layers
    stack = _layer_stack(params)
    NTOK = kv["kv"].shape[1]
    inv_np, att = rope_params(cfg)
    inv = jnp.asarray(inv_np)

    _ATTN = ("ln1", "ln2", "wq", "wq_a", "q_a_norm", "wq_b", "wkv_a",
             "kv_norm", "wkv_b", "wo")

    quantized = kv["kv"].dtype == jnp.int8

    def make_layer(mlp_fn):
        def layer(carry, xs):
            h, pool = carry
            lp, li = xs["lp"], xs["i"]
            hn = rms_norm(h, lp["ln1"], cfg.rms_norm_eps)
            q_nope, q_pe = _q_proj(lp, hn, cfg)
            q_pe = apply_rope_interleaved(q_pe, positions, inv, att)
            rows = _latent_rows(lp, hn, positions, cfg)
            if quantized:
                # in-row (e, m) scales, one pair PER SECTION — the
                # RMSNormed c_kv and the unnormalized post-rope k_pe
                # must not share an absmax (10-50x magnitude skew on
                # real checkpoints would crush the latent's
                # resolution). Every reader dequantizes the same
                # encoding — the pool-reading attn paths gather these
                # rows back, and the sp ring round-trips its fresh rows
                # through the same encode/decode — so the current token
                # sees the same quantized latent later steps do
                enc = quantize_kv_rows_sections(
                    rows, (cfg.kv_lora_rank, cfg.qk_rope_head_dim))
            else:
                enc = rows.astype(pool.dtype)
            pad = pool.shape[2] - enc.shape[1]
            if pad:
                # 128-lane row alignment (latent_row_lanes); attn_fn
                # below must keep seeing the UNPADDED rows
                enc = jnp.pad(enc, ((0, 0), (0, pad)))
            pool = pool.at[li, slots, :].set(enc.astype(pool.dtype),
                                             mode="drop")
            attn = attn_fn(q_nope, q_pe, rows,
                           pool.reshape(L * NTOK, pool.shape[2]), lp, li)
            h = h + mm(attn, lp["wo"])
            hn2 = rms_norm(h, lp["ln2"], cfg.rms_norm_eps)
            h = h + mlp_fn(hn2, lp)
            return (h, pool), None
        return layer

    pool = kv["kv"]
    if cfg.num_experts > 0:
        k = cfg.first_k_dense
        if k > 0:
            dense_lp = {n: stack[n][:k] for n in _ATTN if n in stack}
            dense_lp["down"] = stack["dense_down"]
            if "dense_gateup" in stack:   # fused (fuse_stacked_matmuls)
                dense_lp["gateup"] = stack["dense_gateup"]
            else:
                dense_lp.update({"gate": stack["dense_gate"],
                                 "up": stack["dense_up"]})
            (x, pool), _ = jax.lax.scan(
                make_layer(lambda hn, lp: swiglu(
                    hn, lp.get("gate"), lp.get("up"), lp["down"],
                    cfg.hidden_act, gateup_w=lp.get("gateup"))),
                (x, pool),
                {"lp": dense_lp, "i": jnp.arange(k, dtype=jnp.int32)})
        moe_lp = {n: stack[n][k:] for n in _ATTN if n in stack}
        for n in ("router", "router_bias", "moe_gate", "moe_up",
                  "moe_down", "moe_gateup", "sh_gate", "sh_up",
                  "sh_down", "sh_gateup"):
            if n in stack:
                moe_lp[n] = stack[n]
        (x, pool), _ = jax.lax.scan(
            make_layer(lambda hn, lp: _moe_mlp(hn, lp, cfg)),
            (x, pool),
            {"lp": moe_lp, "i": jnp.arange(k, L, dtype=jnp.int32)})
    else:
        (x, pool), _ = jax.lax.scan(
            make_layer(lambda hn, lp: swiglu(
                hn, lp.get("gate"), lp.get("up"), lp["down"],
                cfg.hidden_act, gateup_w=lp.get("gateup"))),
            (x, pool),
            {"lp": {k: v for k, v in stack.items()
                    if k in _ATTN or k in ("gate", "up", "down",
                                           "gateup")},
             "i": jnp.arange(L, dtype=jnp.int32)})
    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    return x, {"kv": pool}


def _split_wkv_b(lp, cfg: ModelConfig):
    """wkv_b [rank, H*(dn+v)] -> (w_k [H, rank, dn], w_v [H, rank, v])."""
    H, dn, dv = cfg.num_heads, cfg.qk_nope_head_dim, cfg.v_head_dim
    w = lp["wkv_b"].reshape(cfg.kv_lora_rank, H, dn + dv)
    return (jnp.moveaxis(w[..., :dn], 1, 0),
            jnp.moveaxis(w[..., dn:], 1, 0))


# ---------------------------------------------------------------------------
# Prefill: expand k/v from latent rows, dense causal attention
# ---------------------------------------------------------------------------


def prefill_forward(params: Params, kv: KVCache, tokens: jax.Array,
                    block_table: jax.Array, start_pos: jax.Array,
                    true_len: jax.Array, statics: ModelStatics
                    ) -> Tuple[jax.Array, KVCache]:
    """Same contract as llama.prefill_forward: tokens [T] (padded),
    block_table [M], returns (last-token logits [V], new kv). Supports a
    cached prefix (start_pos > 0 — chunked prefill / prefix reuse): the
    chunk's rows are scattered first and attention expands k/v for the
    WHOLE table from the latent pool."""
    cfg, bsz = statics.cfg, statics.block_size
    T = tokens.shape[0]
    H = cfg.num_heads
    rank, dr = cfg.kv_lora_rank, cfg.qk_rope_head_dim
    scale = softmax_scale(cfg)
    positions = start_pos + jnp.arange(T, dtype=jnp.int32)
    valid = jnp.arange(T) < true_len
    slots = jnp.where(
        valid, block_table[positions // bsz] * bsz + positions % bsz, 0)
    seq_len = start_pos + true_len

    def attn(q_nope, q_pe, _rows, kv_flat, lp, li):
        NTOK = kv_flat.shape[0] // cfg.num_layers
        idx = (flat_token_indices(block_table[None, :], bsz)[0]
               + li * NTOK)
        S = idx.shape[0]
        rows = jnp.take(kv_flat, idx, axis=0)            # [S, W]
        if rows.dtype == jnp.int8:
            rows = dequant_kv_rows_sections(rows, (rank, dr),
                                            jnp.float32)
        c, k_pe = rows[..., :rank], rows[..., rank:rank + dr]
        w_k, w_v = _split_wkv_b(lp, cfg)
        # expand: k_nope [H, S, dn], v [H, S, dv]
        k_nope = jnp.einsum("sr,hrd->hsd", c.astype(jnp.float32),
                            w_k.astype(jnp.float32))
        v = jnp.einsum("sr,hrd->hsd", c.astype(jnp.float32),
                       w_v.astype(jnp.float32))
        qn = q_nope.astype(jnp.float32)                  # [T, H, dn]
        qp = q_pe.astype(jnp.float32)                    # [T, H, dr]
        scores = (jnp.einsum("thd,hsd->hts", qn, k_nope)
                  + jnp.einsum("thd,sd->hts", qp,
                               k_pe.astype(jnp.float32))) * scale
        qpos = positions[:, None]
        kpos = jnp.arange(S)[None, :]
        mask = (kpos <= qpos) & (kpos < seq_len)
        scores = jnp.where(mask[None, :, :], scores, NEG_INF)
        probs = jax.nn.softmax(scores, axis=-1)
        out = jnp.einsum("hts,hsd->thd", probs, v)       # [T, H, dv]
        return out.reshape(T, H * cfg.v_head_dim).astype(q_nope.dtype)

    x = _embed(params, tokens, cfg)
    x, kv_new = _run_layers(params, kv, x, positions, slots, cfg, attn)
    last = x[jnp.maximum(true_len - 1, 0)]
    return _logits(params, last, cfg), kv_new


def prefill_forward_sp(params: Params, kv: KVCache, tokens: jax.Array,
                       block_table: jax.Array, true_len: jax.Array,
                       statics: ModelStatics, mesh
                       ) -> Tuple[jax.Array, KVCache]:
    """Sequence-parallel whole-prompt prefill: same contract as
    llama.prefill_forward_sp (start_pos fixed at 0; T divides the sp
    axis). The ring (parallel/ring_attention.ring_attention_mla) is the
    ABSORBED form lifted to prefill: queries drop into latent space
    once, the ICI hops move only the compressed [S/sp, rank+rope] row
    chunks (vs llama's per-head 2·KVH·Dh payload), the softmax
    accumulates in rank-space with the hop streamed in bounded
    sub-chunks (ring_attention.RING_SUB_CHUNK), and w_v applies once
    after the ring. Per-device state is the absorbed form's inherent
    O(T·H·rank / sp) for q_lat/acc; ring traffic is
    O(T·(rank+rope) / sp)."""
    from ...parallel.ring_attention import ring_attention_mla

    cfg, bsz = statics.cfg, statics.block_size
    T = tokens.shape[0]
    H = cfg.num_heads
    rank = cfg.kv_lora_rank
    dr = cfg.qk_rope_head_dim
    scale = softmax_scale(cfg)
    quantized = kv["kv"].dtype == jnp.int8
    positions = jnp.arange(T, dtype=jnp.int32)
    valid = positions < true_len
    slots = jnp.where(
        valid, block_table[positions // bsz] * bsz + positions % bsz, 0)

    def attn(q_nope, q_pe, rows, _kv_flat, lp, _li):
        if quantized:
            # int8-KV invariant (same as the pool-reading paths): this
            # chunk's attention must see exactly the rows decode will
            # read later — round-trip through the sectioned encoding
            rows = dequant_kv_rows_sections(
                quantize_kv_rows_sections(rows, (rank, dr)),
                (rank, dr), jnp.float32)
        w_k, w_v = _split_wkv_b(lp, cfg)
        q_lat = jnp.einsum("thd,hrd->thr", q_nope.astype(jnp.float32),
                           w_k.astype(jnp.float32))
        ctx = ring_attention_mla(
            q_lat, q_pe.astype(jnp.float32), rows.astype(jnp.float32),
            mesh, scale=scale, rank=rank, kv_len=true_len)
        out = jnp.einsum("thr,hrd->thd", ctx.astype(jnp.float32),
                         w_v.astype(jnp.float32))
        return out.reshape(T, H * cfg.v_head_dim).astype(q_nope.dtype)

    x = _embed(params, tokens, cfg)
    x, kv_new = _run_layers(params, kv, x, positions, slots, cfg, attn)
    last = x[jnp.maximum(true_len - 1, 0)]
    return _logits(params, last, cfg), kv_new


# ---------------------------------------------------------------------------
# Decode: the ABSORBED form — attention reads only the latent rows
# ---------------------------------------------------------------------------


def ragged_forward(params: Params, kv: KVCache, tokens: jax.Array,
                   positions: jax.Array, block_tables: jax.Array,
                   row_slot: jax.Array, seq_starts: jax.Array,
                   seq_counts: jax.Array, sample_rows: jax.Array,
                   statics: ModelStatics, max_rows: int = 8,
                   sample_all_rows: bool = False
                   ) -> Tuple[jax.Array, KVCache]:
    """MLA form of llama.ragged_forward (same metadata contract): one
    ragged [TT] token batch serves prefill chunks and decode steps in
    one absorbed-attention dispatch. Per row this is decode_forward's
    math over row-expanded tables (bit-exact per row with MLA decode);
    on TPU the full-precision latent pool takes the sequence-grouped
    ragged kernel as MQA with v-aliases-k (one latent-row stream per
    sequence for ALL its rows). int8 latent pools keep the explicit
    gather + sectioned dequant of the decode fallback — the sectioned
    ragged-kernel mode exists (attention.ragged_paged_attention_pallas
    quant_sections) but is unwired here until it has device truth."""
    from ..attention import _on_tpu, ragged_supported

    cfg, bsz = statics.cfg, statics.block_size
    TT = tokens.shape[0]
    H = cfg.num_heads
    rank, dr = cfg.kv_lora_rank, cfg.qk_rope_head_dim
    scale = softmax_scale(cfg)
    row_tables = jnp.take(block_tables, row_slot, axis=0)      # [TT, M]
    slots = (row_tables[jnp.arange(TT), positions // bsz] * bsz
             + positions % bsz)
    seq_lens = positions + 1
    quantized = kv["kv"].dtype == jnp.int8
    # the latent pool is MQA-shaped for the kernel: one "kv head" of
    # the full row width (decode_forward's MQA framing); unsupported
    # geometries / int8 rows fall back to the per-row paths, so a
    # forced impl never hard-fails here (decode_forward's leniency)
    W = kv["kv"].shape[2]
    ok = (not quantized and rank % 128 == 0
          and ragged_supported(H, 1, W, bsz, max_rows,
                               kv_dtype=kv["kv"].dtype))
    impl = statics.attn_impl
    use_kernel = False
    if ok:
        if impl == "auto":
            use_kernel = _on_tpu()
        elif impl == "pallas_interpret":
            use_kernel = "interpret"
        elif impl == "pallas":
            use_kernel = True
    if use_kernel:
        last_rows = seq_starts + jnp.maximum(seq_counts - 1, 0)
        seq_ctx = jnp.where(seq_counts > 0,
                            jnp.take(positions, last_rows) + 1, 0)

    def attn(q_nope, q_pe, _rows, kv_flat, lp, li):
        NTOK = kv_flat.shape[0] // cfg.num_layers
        num_blocks = NTOK // bsz
        tables_l = row_tables + li * num_blocks
        w_k, w_v = _split_wkv_b(lp, cfg)
        q_lat = jnp.einsum("bhd,hrd->bhr", q_nope.astype(jnp.float32),
                           w_k.astype(jnp.float32))
        if not quantized:
            vl = rank if rank % 128 == 0 else None
            qc = jnp.concatenate(
                [q_lat, q_pe.astype(jnp.float32),
                 jnp.zeros((TT, H, W - rank - dr), jnp.float32)],
                axis=-1).astype(kv_flat.dtype)
            if use_kernel:
                ctx = ragged_paged_attention_pallas(
                    qc, kv_flat, kv_flat,
                    block_tables + li * num_blocks, seq_starts,
                    seq_counts, seq_ctx, block_size=bsz, scale=scale,
                    max_rows=max_rows, v_lanes=vl,
                    coalesce=statics.kv_coalesce,
                    interpret=(use_kernel == "interpret"))
            else:
                from ..attention import paged_attention
                ctx = paged_attention(
                    qc, kv_flat, kv_flat, tables_l, seq_lens,
                    block_size=bsz, scale=scale,
                    impl=statics.attn_impl, kv_heads=1, v_lanes=vl,
                    coalesce=statics.kv_coalesce)
            ctx = ctx[..., :rank].astype(jnp.float32)
        else:
            idx = flat_token_indices(tables_l, bsz)
            T = idx.shape[1]
            rows = jnp.take(kv_flat, idx, axis=0)    # [TT, T, W]
            rows = dequant_kv_rows_sections(rows, (rank, dr),
                                            jnp.float32)
            c = rows[..., :rank]
            k_pe = rows[..., rank:rank + dr]
            scores = (jnp.einsum("bhr,btr->bht", q_lat, c)
                      + jnp.einsum("bhd,btd->bht",
                                   q_pe.astype(jnp.float32),
                                   k_pe)) * scale
            mask = jnp.arange(T)[None, :] < seq_lens[:, None]
            scores = jnp.where(mask[:, None, :], scores, NEG_INF)
            probs = jax.nn.softmax(scores, axis=-1)
            ctx = jnp.einsum("bht,btr->bhr", probs, c)
        out = jnp.einsum("bhr,hrd->bhd", ctx,
                         w_v.astype(jnp.float32))
        return out.reshape(TT, H * cfg.v_head_dim).astype(q_nope.dtype)

    x = _embed(params, tokens, cfg)
    x, kv_new = _run_layers(params, kv, x, positions, slots, cfg, attn)
    if sample_all_rows:
        # ragged×spec variant (llama.ragged_forward): per-row logits
        # for lockstep acceptance over speculative spans
        return _logits(params, x, cfg), kv_new             # [TT, V]
    sel = jnp.take(x, sample_rows, axis=0)                     # [S, D]
    return _logits(params, sel, cfg), kv_new


def decode_forward(params: Params, kv: KVCache, tokens: jax.Array,
                   positions: jax.Array, block_tables: jax.Array,
                   statics: ModelStatics) -> Tuple[jax.Array, KVCache]:
    """Same contract as llama.decode_forward: tokens [B], positions [B],
    block_tables [B, M] -> (logits [B, V], new kv).

    Absorption: scores_h = (q_nope_h W_k_h)·c + q_pe_h·k_pe and
    out_h = (probs·c) W_v_h — queries drop into latent space once per
    step, so the per-token HBM read is ONE (rank+rope)-lane row shared
    by all H heads (the serving win MLA exists for).

    Full-precision pools route through the SHARED paged-attention stack
    (attention.paged_attention) as MQA: the 128-aligned latent row
    (latent_row_lanes) is the single "kv head", the combined query
    [q_lat | q_pe | 0-pad] dots against whole rows (pad lanes are
    zeros on both sides), the pool serves as k AND v, and the output's
    first `rank` lanes ARE probs·c. On TPU that is the block-DMA
    Pallas kernel — the XLA row-gather measured ~27x the pure-bandwidth
    cost of the latent read at seq ≈1K (PERF.md). int8 pools take the
    kernel too on TPU (quant_sections: in-kernel per-section dequant +
    v-aliases-k, the rows stream ONCE at int8 width); the explicit
    gather + sectioned dequant remains the fallback (CPU, non-aligned
    ranks, attn_impl=xla)."""
    cfg, bsz = statics.cfg, statics.block_size
    B = tokens.shape[0]
    H = cfg.num_heads
    rank, dr = cfg.kv_lora_rank, cfg.qk_rope_head_dim
    scale = softmax_scale(cfg)
    slots = (block_tables[jnp.arange(B), positions // bsz] * bsz
             + positions % bsz)
    seq_lens = positions + 1

    def attn(q_nope, q_pe, _rows, kv_flat, lp, li):
        NTOK = kv_flat.shape[0] // cfg.num_layers
        num_blocks = NTOK // bsz
        tables_l = block_tables + li * num_blocks
        w_k, w_v = _split_wkv_b(lp, cfg)
        # absorb the k expansion into the query: [B, H, rank]
        q_lat = jnp.einsum("bhd,hrd->bhr", q_nope.astype(jnp.float32),
                           w_k.astype(jnp.float32))
        if kv_flat.dtype != jnp.int8:
            from ..attention import paged_attention
            W = kv_flat.shape[-1]
            # Deliberate: the kernel dots q against pool rows in the
            # pool dtype, so the f32 query rounds to bf16 here (the XLA
            # fallback keeps f32 queries — scores differ in the last
            # bits). A mixed-precision kernel dot costs a second VREG
            # stream for no measured accuracy gain.
            qc = jnp.concatenate(
                [q_lat, q_pe.astype(jnp.float32),
                 jnp.zeros((B, H, W - rank - dr), jnp.float32)],
                axis=-1).astype(kv_flat.dtype)
            # v_lanes=rank: v IS the c section of each row — the kernel
            # skips the v-side DMA entirely (halving the latent stream)
            # and returns probs·c directly. Ranks that don't lane-align
            # (tiny test geometries) slice after instead
            vl = rank if rank % 128 == 0 else None
            ctx = paged_attention(
                qc, kv_flat, kv_flat, tables_l, seq_lens,
                block_size=bsz, scale=scale, impl=statics.attn_impl,
                kv_heads=1, v_lanes=vl,
                coalesce=statics.kv_coalesce)[..., :rank].astype(
                    jnp.float32)
        else:
            from ..attention import (_on_tpu, paged_attention_pallas,
                                     pallas_supported)
            Wq = -(-(rank + dr) // 128) * 128
            if (statics.attn_impl in ("auto", "pallas") and _on_tpu()
                    and rank % 128 == 0
                    and pallas_supported(H, 1, Wq, bsz,
                                         kv_dtype=jnp.int8)):
                # sectioned-int8 kernel mode: in-kernel per-section
                # dequant + v-aliases-k — the int8 row streams ONCE
                qc = jnp.concatenate(
                    [q_lat, q_pe.astype(jnp.float32),
                     jnp.zeros((B, H, Wq - rank - dr), jnp.float32)],
                    axis=-1).astype(jnp.bfloat16)
                ctx = paged_attention_pallas(
                    qc, kv_flat, kv_flat, tables_l, seq_lens,
                    block_size=bsz, scale=scale, v_lanes=rank,
                    quant_sections=(rank, dr),
                    coalesce=statics.kv_coalesce).astype(jnp.float32)
            else:
                idx = flat_token_indices(tables_l, bsz)
                T = idx.shape[1]
                rows = jnp.take(kv_flat, idx, axis=0)    # [B, T, W]
                rows = dequant_kv_rows_sections(rows, (rank, dr),
                                                jnp.float32)
                c = rows[..., :rank]
                k_pe = rows[..., rank:rank + dr]
                scores = (jnp.einsum("bhr,btr->bht", q_lat, c)
                          + jnp.einsum("bhd,btd->bht",
                                       q_pe.astype(jnp.float32),
                                       k_pe)) * scale
                mask = jnp.arange(T)[None, :] < seq_lens[:, None]
                scores = jnp.where(mask[:, None, :], scores, NEG_INF)
                probs = jax.nn.softmax(scores, axis=-1)
                ctx = jnp.einsum("bht,btr->bhr", probs, c)  # [B,H,rank]
        out = jnp.einsum("bhr,hrd->bhd", ctx,
                         w_v.astype(jnp.float32))        # [B, H, dv]
        return out.reshape(B, H * cfg.v_head_dim).astype(q_nope.dtype)

    x = _embed(params, tokens, cfg)
    x, kv_new = _run_layers(params, kv, x, positions, slots, cfg, attn)
    return _logits(params, x, cfg), kv_new
